"""Gang scheduling (PodGroup) subsystem: API + admission + solve
acceptance + daemon commit + lifecycle controller.

The acceptance bar (ISSUE 2): a 2-group backlog where only one group
fits — the fitting group binds completely, the other binds ZERO pods,
gets an event + Unschedulable status from the gang controller, and the
scalar and TPU batch paths accept the same group set.
"""

import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.controllers.gangs import GangController
from kubernetes_tpu.models.objects import POD_GROUP_LABEL
from kubernetes_tpu.scheduler.daemon import (
    BatchScheduler,
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.server import APIError, APIServer
from kubernetes_tpu.server.admission import new_from_plugins
from kubernetes_tpu.server.httpserver import APIHTTPServer

pytestmark = pytest.mark.gang


def pg_wire(name, min_member=1, max_member=0, timeout=0, ns="default"):
    spec = {"minMember": min_member}
    if max_member:
        spec["maxMember"] = max_member
    if timeout:
        spec["scheduleTimeoutSeconds"] = timeout
    return {
        "kind": "PodGroup",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": ns},
        "spec": spec,
    }


def pod_wire(name, cpu="100m", mem="64Mi", group="", ns="default"):
    labels = {POD_GROUP_LABEL: group} if group else {}
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "labels": labels},
        "spec": {
            "containers": [
                {"name": "c", "image": "pause",
                 "resources": {"limits": {"cpu": cpu, "memory": mem}}}
            ]
        },
    }


def node_wire(name, cpu="1", mem="8Gi", pods="110"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": pods},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def wait_until(cond, timeout=60.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# API resource
# ---------------------------------------------------------------------------


class TestPodGroupResource:
    def test_crud_and_status_subresource(self):
        client = Client(LocalTransport(APIServer()))
        created = client.create("podgroups", pg_wire("g1", min_member=4))
        assert created.spec.min_member == 4
        assert created.status.phase == "Pending"
        client.update_status(
            "podgroups",
            {"kind": "PodGroup",
             "metadata": {"name": "g1", "namespace": "default"},
             "status": {"phase": "Scheduled", "members": 4, "bound": 4}},
            namespace="default",
        )
        got = client.get("podgroups", "g1", namespace="default")
        assert got.status.phase == "Scheduled"
        assert got.status.bound == 4
        assert got.spec.min_member == 4  # status write preserved spec
        items, _ = client.list("podgroups", namespace="default")
        assert [g.metadata.name for g in items] == ["g1"]

    def test_validation(self):
        client = Client(LocalTransport(APIServer()))
        with pytest.raises(APIError) as e:
            client.create("podgroups", pg_wire("bad", min_member=0))
        assert e.value.code == 422
        with pytest.raises(APIError) as e:
            client.create(
                "podgroups", pg_wire("bad", min_member=4, max_member=2)
            )
        assert e.value.code == 422
        bad = pg_wire("bad")
        bad["spec"]["scheduleTimeoutSeconds"] = -5
        with pytest.raises(APIError) as e:
            client.create("podgroups", bad)
        assert e.value.code == 422

    def test_ktctl_get_podgroups_table(self, capsys):
        from kubernetes_tpu.cli.ktctl import main

        client = Client(LocalTransport(APIServer()))
        client.create("podgroups", pg_wire("trainer", min_member=16))
        assert main(["get", "pg", "-n", "default"], client=client) == 0
        out = capsys.readouterr().out
        assert "MIN-MEMBER" in out and "trainer" in out and "16" in out


# ---------------------------------------------------------------------------
# Admission
# ---------------------------------------------------------------------------


class TestPodGroupAdmission:
    def _client(self):
        api = APIServer()
        api.admission = new_from_plugins(api, ["PodGroup"])
        return Client(LocalTransport(api))

    def test_unknown_group_rejected(self):
        client = self._client()
        with pytest.raises(APIError) as e:
            client.create("pods", pod_wire("p1", group="nope"))
        assert e.value.code == 404

    def test_oversized_group_rejected(self):
        client = self._client()
        client.create("podgroups", pg_wire("g1", min_member=1, max_member=2))
        client.create("pods", pod_wire("p1", group="g1"))
        client.create("pods", pod_wire("p2", group="g1"))
        with pytest.raises(APIError) as e:
            client.create("pods", pod_wire("p3", group="g1"))
        assert e.value.code == 403
        assert "full" in e.value.message

    def test_ungrouped_and_unbounded_pods_unaffected(self):
        client = self._client()
        client.create("pods", pod_wire("free"))
        client.create("podgroups", pg_wire("g1", min_member=3))  # no max
        for i in range(5):
            client.create("pods", pod_wire(f"m{i}", group="g1"))

    def test_update_joining_a_gang_is_gated(self):
        """Relabeling an existing pod into a gang is the same
        membership change as creating it there — unknown groups and
        full groups reject; untouched labels pass."""
        from kubernetes_tpu.models import serde

        client = self._client()
        client.create("podgroups", pg_wire("g1", min_member=1, max_member=1))
        client.create("pods", pod_wire("member", group="g1"))
        client.create("pods", pod_wire("outsider"))
        outsider = serde.to_wire(
            client.get("pods", "outsider", namespace="default")
        )
        outsider["metadata"]["labels"] = {POD_GROUP_LABEL: "ghost"}
        with pytest.raises(APIError) as e:
            client.update("pods", outsider, namespace="default")
        assert e.value.code == 404
        outsider["metadata"]["labels"] = {POD_GROUP_LABEL: "g1"}
        with pytest.raises(APIError) as e:  # g1 is full
            client.update("pods", outsider, namespace="default")
        assert e.value.code == 403
        # Unchanged membership: updating the existing member passes
        # even though its group is at maxMember (it never counts
        # itself).
        member = serde.to_wire(
            client.get("pods", "member", namespace="default")
        )
        member["metadata"]["annotations"] = {"touched": "yes"}
        client.update("pods", member, namespace="default")

    def test_terminated_members_free_their_gang_slot(self):
        """A crashed member's replacement must admit: Succeeded/Failed
        pods (and ones being deleted) do not count toward maxMember."""
        client = self._client()
        client.create("podgroups", pg_wire("g1", min_member=2, max_member=2))
        client.create("pods", pod_wire("m0", group="g1"))
        client.create("pods", pod_wire("m1", group="g1"))
        client.update_status(
            "pods",
            {"kind": "Pod",
             "metadata": {"name": "m1", "namespace": "default"},
             "status": {"phase": "Failed"}},
            namespace="default",
        )
        client.create("pods", pod_wire("m1-replacement", group="g1"))
        with pytest.raises(APIError):  # live count is back at max
            client.create("pods", pod_wire("m2", group="g1"))


# ---------------------------------------------------------------------------
# Solve-level acceptance
# ---------------------------------------------------------------------------


class TestGangSolve:
    def test_rejected_group_releases_capacity_into_the_solve(self):
        """A rejected gang's tentative placements free capacity the
        SAME solve then hands to other pods (the release-and-resolve
        loop, not just a veto)."""
        from kubernetes_tpu.scheduler.batch import schedule_backlog_gang_scalar
        from kubernetes_tpu.scheduler.gang import partition_backlog
        from tests.test_solver_parity import mk_node, mk_pod

        pods = []
        for i in range(2):  # gang of 2 x 600m: only one fits -> reject
            p = mk_pod(f"b{i}", cpu=600)
            p.metadata.labels[POD_GROUP_LABEL] = "gb"
            pods.append(p)
        pods.append(mk_pod("single", cpu=800))  # fits only post-release
        nodes = [mk_node("n0", cpu=1000)]
        groups = partition_backlog(pods, min_member_of=lambda ns, n: 2)
        dests, accepted, rejected = schedule_backlog_gang_scalar(
            pods, nodes, groups=groups
        )
        assert [g.key for g in rejected] == ["default/gb"]
        assert dests == [None, None, "n0"]

    def test_already_bound_members_count_toward_min_member(self):
        from kubernetes_tpu.scheduler.batch import schedule_backlog_gang_scalar
        from kubernetes_tpu.scheduler.gang import partition_backlog
        from tests.test_solver_parity import mk_node, mk_pod

        bound = mk_pod("b0", cpu=100)
        bound.metadata.labels[POD_GROUP_LABEL] = "ga"
        bound.spec.node_name = "n0"
        p = mk_pod("p0", cpu=100)
        p.metadata.labels[POD_GROUP_LABEL] = "ga"
        groups = partition_backlog(
            [p], assigned=[bound], min_member_of=lambda ns, n: 2
        )
        assert groups[0].bound == 1
        dests, accepted, rejected = schedule_backlog_gang_scalar(
            [p], [mk_node("n0")], assigned=[bound], groups=groups
        )
        assert not rejected and dests == ["n0"]

    def test_terminal_bound_members_do_not_credit_the_floor(self):
        """A Failed member keeps its label and nodeName but must not
        count toward minMember — otherwise its replacement binds solo
        below the floor."""
        from kubernetes_tpu.scheduler.gang import partition_backlog
        from tests.test_solver_parity import mk_pod

        dead = mk_pod("dead", cpu=100)
        dead.metadata.labels[POD_GROUP_LABEL] = "ga"
        dead.spec.node_name = "n0"
        dead.status.phase = "Failed"
        p = mk_pod("replacement", cpu=100)
        p.metadata.labels[POD_GROUP_LABEL] = "ga"
        (g,) = partition_backlog(
            [p], assigned=[dead], min_member_of=lambda ns, n: 2
        )
        assert g.bound == 0  # the dead pod frees its credit

    def test_unknown_group_degrades_to_per_pod(self):
        from kubernetes_tpu.scheduler.gang import partition_backlog
        from tests.test_solver_parity import mk_pod

        p = mk_pod("p0")
        p.metadata.labels[POD_GROUP_LABEL] = "ghost"
        (g,) = partition_backlog([p], min_member_of=lambda ns, n: None)
        assert g.min_member == 0  # never rejects

    def test_host_and_device_reducers_agree(self):
        import numpy as np

        from kubernetes_tpu.ops.pipeline import gang_member_counts_device
        from kubernetes_tpu.scheduler.gang import member_counts_host

        rng = np.random.RandomState(7)
        for _ in range(5):
            n, g = rng.randint(1, 64), rng.randint(1, 9)
            placed = rng.rand(n) < 0.6
            gids = rng.randint(-1, g, size=n).astype(np.int32)
            host = member_counts_host(placed, gids, g)
            dev = gang_member_counts_device(placed, gids, g)
            assert (host == dev).all(), (host, dev)


# ---------------------------------------------------------------------------
# Gang lifecycle controller
# ---------------------------------------------------------------------------


class TestGangController:
    def test_scheduled_when_min_member_bound(self):
        client = Client(LocalTransport(APIServer()))
        client.create("podgroups", pg_wire("g1", min_member=2))
        client.create("pods", pod_wire("m0", group="g1"))
        client.create("pods", pod_wire("m1", group="g1"))
        ctrl = GangController(client)
        ctrl.sync_once()
        got = client.get("podgroups", "g1", namespace="default")
        assert got.status.phase == "Pending"
        assert got.status.members == 2 and got.status.bound == 0
        client.bind("m0", "n0", namespace="default")
        client.bind("m1", "n1", namespace="default")
        ctrl.sync_once()
        got = client.get("podgroups", "g1", namespace="default")
        assert got.status.phase == "Scheduled" and got.status.bound == 2
        client.flush_events()
        events, _ = client.list(
            "events", namespace="default",
            field_selector="involvedObject.name=g1",
        )
        assert any(e.reason == "GangScheduled" for e in events)

    def test_pending_past_timeout_marked_unschedulable(self):
        client = Client(LocalTransport(APIServer()))
        client.create("podgroups", pg_wire("g1", min_member=2, timeout=5))
        client.create("pods", pod_wire("m0", group="g1"))
        ctrl = GangController(client)
        ctrl.sync_once()  # young: stays Pending
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Pending"
        )
        ctrl.sync_once(now=time.time() + 60)  # aged past the timeout
        got = client.get("podgroups", "g1", namespace="default")
        assert got.status.phase == "Unschedulable"
        assert "still 0/2" in got.status.message
        client.flush_events()
        events, _ = client.list(
            "events", namespace="default",
            field_selector="involvedObject.name=g1",
        )
        assert any(e.reason == "GangTimeout" for e in events)

    def test_repending_gang_gets_a_fresh_timeout_window(self):
        """A Scheduled gang that loses members re-pends and ages from
        the re-pend time (status.pendingSince), not creation — no
        instant spurious GangTimeout."""
        client = Client(LocalTransport(APIServer()))
        client.create("podgroups", pg_wire("g1", min_member=1, timeout=30))
        client.create("pods", pod_wire("m0", group="g1"))
        client.bind("m0", "n0", namespace="default")
        ctrl = GangController(client)
        ctrl.sync_once()
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Scheduled"
        )
        client.delete("pods", "m0", namespace="default")
        late = time.time() + 1000  # way past creation + timeout
        ctrl.sync_once(now=late)
        got = client.get("podgroups", "g1", namespace="default")
        assert got.status.phase == "Pending"  # NOT instantly timed out
        assert got.status.pending_since
        ctrl.sync_once(now=late + 5)  # inside the fresh window
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Pending"
        )
        ctrl.sync_once(now=late + 60)  # fresh window exhausted
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Unschedulable"
        )

    def test_crashed_gang_repends_instead_of_staying_scheduled(self):
        """Terminal members keep nodeName but are not 'bound': a gang
        whose pods all crashed must leave Scheduled (and can then age
        out), not sit green with zero running members."""
        client = Client(LocalTransport(APIServer()))
        client.create("podgroups", pg_wire("g1", min_member=1))
        client.create("pods", pod_wire("m0", group="g1"))
        client.bind("m0", "n0", namespace="default")
        ctrl = GangController(client)
        ctrl.sync_once()
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Scheduled"
        )
        client.update_status(
            "pods",
            {"kind": "Pod",
             "metadata": {"name": "m0", "namespace": "default"},
             "status": {"phase": "Failed"}},
            namespace="default",
        )
        ctrl.sync_once()
        got = client.get("podgroups", "g1", namespace="default")
        assert got.status.phase == "Pending"
        assert got.status.bound == 0 and got.status.members == 0

    def test_unschedulable_recovers_to_scheduled(self):
        client = Client(LocalTransport(APIServer()))
        client.create("podgroups", pg_wire("g1", min_member=1, timeout=5))
        client.create("pods", pod_wire("m0", group="g1"))
        ctrl = GangController(client)
        ctrl.sync_once(now=time.time() + 60)
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Unschedulable"
        )
        client.bind("m0", "n0", namespace="default")
        ctrl.sync_once()
        assert (
            client.get("podgroups", "g1", namespace="default").status.phase
            == "Scheduled"
        )


# ---------------------------------------------------------------------------
# Daemon integration (the ISSUE acceptance bar)
# ---------------------------------------------------------------------------


def _two_group_cluster(client):
    """Two 1-cpu nodes; gang ga (2 x 900m — fits, one pod per node) and
    gang gb (2 x 900m, minMember 2 — cannot fit once ga lands)."""
    for j in range(2):
        client.create("nodes", node_wire(f"n{j}", cpu="1"))
    client.create("podgroups", pg_wire("ga", min_member=2))
    client.create("podgroups", pg_wire("gb", min_member=2, timeout=1))
    for i in range(2):
        client.create("pods", pod_wire(f"a{i}", cpu="900m", group="ga"))
    for i in range(2):
        client.create("pods", pod_wire(f"b{i}", cpu="900m", group="gb"))


def _assert_all_or_nothing(client):
    pods, _ = client.list("pods", namespace="default")
    by_name = {p.metadata.name: p for p in pods}
    assert by_name["a0"].spec.node_name and by_name["a1"].spec.node_name
    assert {by_name["a0"].spec.node_name, by_name["a1"].spec.node_name} == {
        "n0", "n1",
    }
    # The losing gang bound ZERO pods — no stragglers.
    assert not by_name["b0"].spec.node_name
    assert not by_name["b1"].spec.node_name


@pytest.mark.parametrize("daemon_cls", [BatchScheduler, IncrementalBatchScheduler])
def test_two_group_backlog_all_or_nothing(daemon_cls):
    """One group fits, the other binds zero pods, gets an event +
    Unschedulable from the gang controller — on both batch daemons."""
    api = APIServer()
    client = Client(LocalTransport(api))
    _two_group_cluster(client)
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = daemon_cls(cfg)
        processed = 0
        deadline = time.monotonic() + 60
        while processed < 4 and time.monotonic() < deadline:
            processed += sched.schedule_batch(timeout=0.5)
        assert processed >= 4
        _assert_all_or_nothing(client)
        # Rejected-gang pods carry a gang-specific FailedScheduling event.
        cfg.client.flush_events()
        events, _ = client.list(
            "events", namespace="default",
            field_selector="involvedObject.name=b0",
        )
        assert any(
            "pod group" in e.message and "gb" in e.message for e in events
        ), [e.message for e in events]
        # The gang controller ages the stuck group to Unschedulable
        # (scheduleTimeoutSeconds=1) with an event; the winner is
        # Scheduled.
        ctrl = GangController(client)
        ctrl.sync_once(now=time.time() + 60)
        ga = client.get("podgroups", "ga", namespace="default")
        gb = client.get("podgroups", "gb", namespace="default")
        assert ga.status.phase == "Scheduled" and ga.status.bound == 2
        assert gb.status.phase == "Unschedulable" and gb.status.bound == 0
        client.flush_events()
        events, _ = client.list(
            "events", namespace="default",
            field_selector="involvedObject.name=gb",
        )
        assert any(e.reason == "GangTimeout" for e in events)
    finally:
        cfg.stop()


def test_transient_podgroup_fetch_failure_defers_gangs(monkeypatch):
    """If PodGroup specs cannot be resolved this tick (informer lag on
    a group the cache hasn't seen AND the read-through fetch hits an
    apiserver hiccup), grouped pods are DEFERRED — never scheduled
    per-pod, which would break the all-or-nothing contract — while
    ungrouped pods still schedule."""
    api = APIServer()
    client = Client(LocalTransport(api))
    client.create("nodes", node_wire("n0", cpu="4"))
    client.create("podgroups", pg_wire("ga", min_member=2))
    client.create("pods", pod_wire("a0", group="ga"))
    client.create("pods", pod_wire("a1", group="ga"))
    client.create("pods", pod_wire("solo"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg)
        real_list = cfg.client.list

        def flaky_list(resource, *a, **k):
            if resource == "podgroups":
                raise ConnectionError("apiserver hiccup")
            return real_list(resource, *a, **k)

        # Specs come from the podgroups informer now; a hiccup only
        # bites when the cache MISSES the group (watch lag) and the
        # read-through fetch fails too. Simulate both.
        real_store_list = cfg.podgroups.store.list
        monkeypatch.setattr(cfg.podgroups.store, "list", lambda: [])
        monkeypatch.setattr(cfg.client, "list", flaky_list)
        processed = 0
        deadline = time.monotonic() + 30
        while processed < 3 and time.monotonic() < deadline:
            processed += sched.schedule_batch(timeout=0.5)
        pods, _ = client.list("pods", namespace="default")
        by_name = {p.metadata.name: p.spec.node_name for p in pods}
        assert by_name["solo"] == "n0"
        assert not by_name["a0"] and not by_name["a1"]
        # Specs resolvable again: the deferred gang binds whole.
        monkeypatch.setattr(cfg.client, "list", real_list)
        monkeypatch.setattr(cfg.podgroups.store, "list", real_store_list)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.5)
            pods, _ = client.list("pods", namespace="default")
            if all(p.spec.node_name for p in pods):
                break
        assert all(p.spec.node_name for p in pods)
    finally:
        cfg.stop()


def test_device_outage_falls_back_to_scalar_gang_solve(monkeypatch):
    """When the device path is down, gang batches must still schedule:
    the fallback runs the scalar solver AND the host acceptance reducer
    (the device reducer would just re-raise the outage)."""
    import kubernetes_tpu.ops.pipeline as pipeline
    import kubernetes_tpu.scheduler.batch as batch

    api = APIServer()
    client = Client(LocalTransport(api))
    _two_group_cluster(client)
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    try:
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg)

        def broken(*a, **k):
            raise RuntimeError("device unavailable")

        monkeypatch.setattr(batch, "schedule_backlog_tpu", broken)
        monkeypatch.setattr(pipeline, "gang_member_counts_device", broken)
        processed = 0
        deadline = time.monotonic() + 60
        while processed < 4 and time.monotonic() < deadline:
            processed += sched.schedule_batch(timeout=0.5)
        assert processed >= 4
        assert sched.fallback_count > 0
        _assert_all_or_nothing(client)
    finally:
        cfg.stop()


def test_scalar_and_tpu_paths_accept_same_group_set():
    """The acceptance loop is path-independent: scalar fallback and the
    device scan agree on the accepted-group set AND destinations."""
    from kubernetes_tpu.models import serde
    from kubernetes_tpu.models.objects import Node, Pod
    from kubernetes_tpu.scheduler.batch import (
        schedule_backlog_gang_scalar,
        schedule_backlog_gang_tpu,
    )
    from kubernetes_tpu.scheduler.gang import partition_backlog

    pods = [
        serde.from_wire(Pod, pod_wire(f"a{i}", cpu="900m", group="ga"))
        for i in range(2)
    ] + [
        serde.from_wire(Pod, pod_wire(f"b{i}", cpu="900m", group="gb"))
        for i in range(2)
    ]
    nodes = [serde.from_wire(Node, node_wire(f"n{j}", cpu="1")) for j in range(2)]
    groups = partition_backlog(pods, min_member_of=lambda ns, n: 2)
    ds, acc_s, rej_s = schedule_backlog_gang_scalar(pods, nodes, groups=groups)
    dt, acc_t, rej_t = schedule_backlog_gang_tpu(pods, nodes, groups=groups)
    assert [g.key for g in acc_s] == [g.key for g in acc_t] == ["default/ga"]
    assert [g.key for g in rej_s] == [g.key for g in rej_t] == ["default/gb"]
    assert ds == dt
    assert ds[2] is None and ds[3] is None


@pytest.mark.gang
def test_http_smoke_podgroup_binds_all_or_nothing():
    """Tier-1 smoke: create a PodGroup over the HTTP API, schedule with
    an HTTP-backed batch daemon, and watch the gang bind all-or-nothing
    (losing gang: zero bindings on the watch stream)."""
    server = APIHTTPServer(APIServer()).start()
    try:
        client = Client(HTTPTransport(server.address))
        _two_group_cluster(client)
        assert (
            client.get("podgroups", "ga", namespace="default").spec.min_member
            == 2
        )
        _, version = client.list("pods", namespace="default")
        stream = client.watch("pods", namespace="default", since=version)
        cfg = SchedulerConfig(
            Client(HTTPTransport(server.address))
        ).start()
        try:
            assert cfg.wait_for_sync(timeout=60)
            sched = BatchScheduler(cfg)
            processed = 0
            deadline = time.monotonic() + 60
            while processed < 4 and time.monotonic() < deadline:
                processed += sched.schedule_batch(timeout=0.5)
            _assert_all_or_nothing(client)
            # Watch saw exactly the winner gang's two bindings.
            bound = set()
            while True:
                ev = stream.next(timeout=1.0)
                if ev is None:
                    break
                if ev.type == "MODIFIED" and ev.object["spec"].get("nodeName"):
                    bound.add(ev.object["metadata"]["name"])
            assert bound == {"a0", "a1"}
            GangController(client).sync_once()
            assert (
                client.get("podgroups", "ga", namespace="default").status.phase
                == "Scheduled"
            )
        finally:
            cfg.stop()
        stream.close()
    finally:
        server.stop()


@pytest.mark.chaos
def test_gang_all_or_nothing_across_daemon_crash_restart():
    """ISSUE 15: the incremental daemon dies between the gang solve and
    its atomic commit (scheduler.commit.crash). At NO observable point
    may a proper subset of the gang be bound, and the restarted daemon
    — rebuilding its SolverSession from LIST+watch — must converge the
    whole gang."""
    from tests.test_microtick import kill_daemon
    from kubernetes_tpu.utils import faults

    faults.clear()
    faults.reset_stats(reseed=0)
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(f"n{j}", cpu="4"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = IncrementalBatchScheduler(cfg).start()
    killed = False
    try:
        # Warm-up commit lands clean so the NEXT job is the gang's.
        client.create("pods", pod_wire("warm"), namespace="default")
        assert wait_until(
            lambda: client.get(
                "pods", "warm", namespace="default"
            ).spec.node_name
        )
        rule = faults.inject(faults.SCHED_COMMIT_CRASH, every=1, times=1)
        client.create("podgroups", pg_wire("gx", min_member=4))
        members = [f"gx-m{i}" for i in range(4)]
        for m in members:
            client.create(
                "pods", pod_wire(m, group="gx"), namespace="default"
            )
        assert wait_until(lambda: rule.fired > 0, timeout=30), (
            "gang commit crash never fired"
        )
        faults.clear()

        def bound_count():
            pods, _ = client.list(
                "pods", namespace="default",
                label_selector=f"{POD_GROUP_LABEL}=gx",
            )
            return sum(1 for p in pods if p.spec.node_name)

        # Mid-crash: the atomic commit never ran — nothing is bound,
        # and every poll from here to convergence must see 0 or 4.
        observed = set()
        kill_daemon(sched, cfg)
        killed = True
        cfg = SchedulerConfig(
            Client(LocalTransport(api)), raw_scheduled_cache=True
        ).start()
        assert cfg.wait_for_sync(timeout=60)
        sched = IncrementalBatchScheduler(cfg).start()
        killed = False
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            n = bound_count()
            observed.add(n)
            if n == 4:
                break
            time.sleep(0.05)
        assert 4 in observed, "restarted daemon never bound the gang"
        assert observed <= {0, 4}, (
            f"gang observed half-bound across restart: {sorted(observed)}"
        )
    finally:
        faults.clear()
        if not killed:
            sched.stop()
