"""Priority parity tests — tables mirror
plugin/pkg/scheduler/algorithm/priorities/{priorities_test.go,
spreading_test.go}. Expected scores include the reference's integer
truncations; these numbers are the oracle for the TPU batch path."""

import pytest

from kubernetes_tpu.models.objects import (
    Container,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ResourceRequirements,
    Service,
    ServiceSpec,
)
from kubernetes_tpu.models.quantity import Quantity
from kubernetes_tpu.scheduler import priorities as prios
from kubernetes_tpu.scheduler.types import (
    StaticNodeLister,
    StaticPodLister,
    StaticServiceLister,
)


def make_minion(name, milli_cpu, memory):
    """makeMinion (priorities_test.go:29-39)."""
    return Node(
        metadata=ObjectMeta(name=name),
        status=NodeStatus(
            capacity={
                "cpu": Quantity.from_milli(milli_cpu),
                "memory": Quantity.from_int(memory),
            }
        ),
    )


def _containers(*limits):
    return [
        Container(
            name=f"c{i}",
            image="x",
            resources=ResourceRequirements(
                limits={
                    k: (Quantity.from_milli(v) if k == "cpu" else Quantity.from_int(v))
                    for k, v in lim.items()
                }
            ),
        )
        for i, lim in enumerate(limits)
    ]


# Fixtures mirroring priorities_test.go:56-100.
def no_resources_pod(node=""):
    return Pod(spec=PodSpec(node_name=node))


def cpu_only_pod(node="machine1"):
    return Pod(spec=PodSpec(node_name=node, containers=_containers({"cpu": 1000}, {"cpu": 2000})))


def cpu_mem_pod(node="machine2"):
    return Pod(
        spec=PodSpec(
            node_name=node,
            containers=_containers(
                {"cpu": 1000, "memory": 2000}, {"cpu": 2000, "memory": 3000}
            ),
        )
    )


def scores(result):
    return {hp.host: hp.score for hp in result}


class TestLeastRequested:
    """priorities_test.go TestLeastRequested expectations (:100-260)."""

    @pytest.mark.parametrize(
        "pod,pods,nodes,expected,name",
        [
            (
                no_resources_pod(), [],
                [("machine1", 4000, 10000), ("machine2", 4000, 10000)],
                {"machine1": 10, "machine2": 10},
                "nothing scheduled, nothing requested",
            ),
            (
                cpu_mem_pod(""), [],
                [("machine1", 4000, 10000), ("machine2", 6000, 10000)],
                {"machine1": 3, "machine2": 5},
                "nothing scheduled, resources requested, differently sized",
            ),
            (
                no_resources_pod(),
                ["cpu_only:machine1", "cpu_only:machine1", "cpu_only:machine2", "cpu_mem:machine2"],
                [("machine1", 10000, 20000), ("machine2", 10000, 20000)],
                {"machine1": 7, "machine2": 5},
                "no resources requested, pods scheduled with resources",
            ),
            (
                cpu_mem_pod(""),
                ["cpu_only:machine1", "cpu_mem:machine2"],
                [("machine1", 10000, 20000), ("machine2", 10000, 20000)],
                {"machine1": 5, "machine2": 4},
                "resources requested, pods scheduled with resources",
            ),
            (
                cpu_mem_pod(""),
                ["cpu_only:machine1", "cpu_mem:machine2"],
                [("machine1", 10000, 20000), ("machine2", 10000, 50000)],
                {"machine1": 5, "machine2": 6},
                "differently sized machines",
            ),
            (
                cpu_only_pod(""),
                ["cpu_only:machine1", "cpu_mem:machine2"],
                [("machine1", 4000, 10000), ("machine2", 4000, 10000)],
                {"machine1": 5, "machine2": 2},
                "requested resources exceed minion capacity",
            ),
            (
                no_resources_pod(), [],
                [("machine1", 0, 0), ("machine2", 0, 0)],
                {"machine1": 0, "machine2": 0},
                "zero minion resources",
            ),
        ],
    )
    def test_table(self, pod, pods, nodes, expected, name):
        existing = []
        for spec in pods:
            kind, node = spec.split(":")
            existing.append(cpu_only_pod(node) if kind == "cpu_only" else cpu_mem_pod(node))
        lister = StaticNodeLister([make_minion(n, c, m) for n, c, m in nodes])
        got = scores(prios.least_requested_priority(pod, StaticPodLister(existing), lister))
        assert got == expected, name


class TestBalancedResourceAllocation:
    """priorities_test.go TestBalancedResourceAllocation (:430-600)."""

    @pytest.mark.parametrize(
        "pod,pods,nodes,expected,name",
        [
            (
                no_resources_pod(), [],
                [("machine1", 4000, 10000), ("machine2", 4000, 10000)],
                {"machine1": 10, "machine2": 10},
                "nothing scheduled, nothing requested",
            ),
            (
                cpu_mem_pod(""), [],
                [("machine1", 4000, 10000), ("machine2", 6000, 10000)],
                {"machine1": 7, "machine2": 10},
                "nothing scheduled, resources requested, differently sized",
            ),
            (
                no_resources_pod(),
                ["cpu_only:machine1", "cpu_only:machine1", "cpu_only:machine2", "cpu_mem:machine2"],
                [("machine1", 10000, 20000), ("machine2", 10000, 20000)],
                {"machine1": 4, "machine2": 6},
                "no resources requested, pods scheduled with resources",
            ),
            (
                cpu_mem_pod(""),
                ["cpu_only:machine1", "cpu_mem:machine2"],
                [("machine1", 10000, 20000), ("machine2", 10000, 20000)],
                {"machine1": 6, "machine2": 9},
                "resources requested, pods scheduled",
            ),
            (
                cpu_mem_pod(""),
                ["cpu_only:machine1", "cpu_mem:machine2"],
                [("machine1", 10000, 20000), ("machine2", 10000, 50000)],
                {"machine1": 6, "machine2": 6},
                "differently sized machines",
            ),
            (
                cpu_only_pod(""),
                ["cpu_only:machine1", "cpu_mem:machine2"],
                [("machine1", 4000, 10000), ("machine2", 4000, 10000)],
                {"machine1": 0, "machine2": 0},
                "requested exceed capacity",
            ),
            (
                no_resources_pod(), [],
                [("machine1", 0, 0), ("machine2", 0, 0)],
                {"machine1": 0, "machine2": 0},
                "zero minion resources",
            ),
        ],
    )
    def test_table(self, pod, pods, nodes, expected, name):
        existing = []
        for spec in pods:
            kind, node = spec.split(":")
            existing.append(cpu_only_pod(node) if kind == "cpu_only" else cpu_mem_pod(node))
        lister = StaticNodeLister([make_minion(n, c, m) for n, c, m in nodes])
        got = scores(
            prios.balanced_resource_allocation(pod, StaticPodLister(existing), lister)
        )
        assert got == expected, name


def labeled_pod(labels, ns="default", node=""):
    return Pod(
        metadata=ObjectMeta(name=f"p{id(labels) % 1000}", namespace=ns, labels=labels),
        spec=PodSpec(node_name=node),
    )


def plain_node(name, labels=None):
    return Node(metadata=ObjectMeta(name=name, labels=labels or {}))


class TestServiceSpread:
    """spreading_test.go TestServiceSpreadPriority expectations."""

    def test_no_services_all_ten(self):
        pod = labeled_pod({"app": "web"})
        nodes = StaticNodeLister([plain_node("m1"), plain_node("m2")])
        got = scores(
            prios.ServiceSpread(StaticServiceLister([]))(
                pod, StaticPodLister([]), nodes
            )
        )
        assert got == {"m1": 10, "m2": 10}

    def test_spread(self):
        svc = Service(
            metadata=ObjectMeta(name="s", namespace="default"),
            spec=ServiceSpec(selector={"app": "web"}),
        )
        pod = labeled_pod({"app": "web"})
        existing = [
            labeled_pod({"app": "web"}, node="m1"),
            labeled_pod({"app": "web"}, node="m1"),
            labeled_pod({"app": "web"}, node="m2"),
        ]
        nodes = StaticNodeLister([plain_node("m1"), plain_node("m2"), plain_node("m3")])
        got = scores(
            prios.ServiceSpread(StaticServiceLister([svc]))(
                pod, StaticPodLister(existing), nodes
            )
        )
        # maxCount=2: m1 -> 10*(2-2)/2=0, m2 -> 10*(2-1)/2=5, m3 -> 10.
        assert got == {"m1": 0, "m2": 5, "m3": 10}

    def test_other_namespace_ignored(self):
        svc = Service(
            metadata=ObjectMeta(name="s", namespace="default"),
            spec=ServiceSpec(selector={"app": "web"}),
        )
        pod = labeled_pod({"app": "web"})
        existing = [labeled_pod({"app": "web"}, ns="other", node="m1")]
        nodes = StaticNodeLister([plain_node("m1"), plain_node("m2")])
        got = scores(
            prios.ServiceSpread(StaticServiceLister([svc]))(
                pod, StaticPodLister(existing), nodes
            )
        )
        assert got == {"m1": 10, "m2": 10}


class TestServiceAntiAffinity:
    """spreading_test.go TestZoneSpreadPriority expectations."""

    def test_zone_spread(self):
        svc = Service(
            metadata=ObjectMeta(name="s", namespace="default"),
            spec=ServiceSpec(selector={"app": "web"}),
        )
        nodes = StaticNodeLister(
            [
                plain_node("m1", {"zone": "z1"}),
                plain_node("m2", {"zone": "z1"}),
                plain_node("m3", {"zone": "z2"}),
                plain_node("m4"),  # unlabeled -> score 0
            ]
        )
        existing = [
            labeled_pod({"app": "web"}, node="m1"),
            labeled_pod({"app": "web"}, node="m3"),
            labeled_pod({"app": "web"}, node="m3"),
        ]
        fn = prios.ServiceAntiAffinity(StaticServiceLister([svc]), "zone")
        got = scores(fn(labeled_pod({"app": "web"}), StaticPodLister(existing), nodes))
        # 3 service pods: z1 has 1, z2 has 2.
        # z1 nodes: 10*(3-1)/3 = 6 (int), z2: 10*(3-2)/3 = 3 (int), m4: 0.
        assert got == {"m1": 6, "m2": 6, "m3": 3, "m4": 0}


class TestNodeLabelPriority:
    """priorities_test.go TestNewNodeLabelPriority (:278-366)."""

    @pytest.mark.parametrize(
        "label,presence,expected",
        [
            ("baz", True, {"m1": 0, "m2": 0, "m3": 0}),
            ("baz", False, {"m1": 10, "m2": 10, "m3": 10}),
            ("foo", True, {"m1": 10, "m2": 0, "m3": 0}),
            ("foo", False, {"m1": 0, "m2": 10, "m3": 10}),
        ],
    )
    def test_table(self, label, presence, expected):
        nodes = StaticNodeLister(
            [
                plain_node("m1", {"foo": "1"}),
                plain_node("m2", {"bar": "1"}),
                plain_node("m3", {"bar": "1"}),
            ]
        )
        fn = prios.NodeLabelPrioritizer(label, presence)
        got = scores(fn(Pod(), StaticPodLister([]), nodes))
        assert got == expected


def test_equal_priority():
    nodes = StaticNodeLister([plain_node("m1"), plain_node("m2")])
    got = scores(prios.equal_priority(Pod(), StaticPodLister([]), nodes))
    assert got == {"m1": 1, "m2": 1}
