"""Cluster health-plane e2e surface (PR 20).

Windowed SLO evaluation both ways (a lifetime burn that recovered
verdicts pass inside the window; a clean lifetime with a fresh
in-window burn verdicts burn), the three HTTP debug endpoints
(/debug/alerts, /debug/timeseries, /debug/health) over a live
apiserver — cold-miss payloads, populated queries, and the 400 on a
non-numeric window — and the `ktctl alerts` / `ktctl top health` miss
and populated contracts over LocalTransport.

Tests that feed the PROCESS-GLOBAL retention/engine (the endpoints and
the CLI read module DEFAULTs) reset them in teardown so the windowed
fallback in unrelated suites keeps seeing an unsampled plane.
"""

import io
import json
import time
import urllib.error
import urllib.request
from contextlib import contextmanager, redirect_stderr, redirect_stdout

import pytest

from kubernetes_tpu.utils import alerts, metrics, slo, timeseries

pytestmark = pytest.mark.health


def _reset_globals():
    timeseries.DEFAULT.reset()
    alerts.DEFAULT.configure(rules=alerts.DEFAULT_RULES, clock_scale=1.0)


@contextmanager
def _quiet_global_registry():
    """Earlier suites observe into the process-global metrics.DEFAULT,
    so the lifetime SLO fallback would report THEIR burns inside the
    health rollup here; pin a fresh registry for the duration.

    Everything that registers process-global metrics at import time is
    imported BEFORE the swap — a first-import inside the window would
    bind its metric objects to the throwaway registry forever and the
    exposition goldens downstream would lose them."""
    import kubernetes_tpu.store.replication  # noqa: F401
    import kubernetes_tpu.utils.flightrecorder  # noqa: F401
    import kubernetes_tpu.utils.lease  # noqa: F401
    from kubernetes_tpu.cli import ktctl  # noqa: F401
    from kubernetes_tpu.server import api, httpserver  # noqa: F401

    saved = metrics.DEFAULT
    metrics.DEFAULT = metrics.Registry()
    try:
        yield
    finally:
        metrics.DEFAULT = saved


class TestWindowedSLO:
    """utils/slo.py window_s semantics: the verdict follows the
    window's DELTAS when retention history exists, and falls back to
    the lifetime cumulative path (exactly the pre-window behavior)
    when it does not."""

    def _history(self, reg):
        # Two retention samples 10s apart ending "now" on the live
        # monotonic clock (the slo engine queries against it).
        ret = timeseries.Retention()
        t1 = time.monotonic()
        return ret, (t1 - 10.0, t1)

    def test_recovered_burn_passes_in_window_but_burns_lifetime(self):
        reg = metrics.Registry()
        h = reg.histogram("bind_seconds", "x")
        ret = timeseries.Retention()
        t1 = time.monotonic()
        for _ in range(100):
            h.observe(8.0)  # the incident
        ret.sample_now(registry=reg, now=t1 - 10.0)
        for _ in range(100):
            h.observe(0.01)  # the recovery, inside the window
        ret.sample_now(registry=reg, now=t1)
        obj = slo.Objective(
            "bind", "bind_seconds", target=1.0, window_s=60.0
        )
        e = slo.evaluate_objective(obj, registry=reg, history=ret)
        assert e["windowed"] is True
        assert e["verdict"] == "pass", e
        # Same objective, no retention history: lifetime p99 still
        # carries the incident — the pre-PR-20 fallback verdict.
        cold = slo.evaluate_objective(
            obj, registry=reg, history=timeseries.Retention()
        )
        assert cold["windowed"] is False
        assert cold["verdict"] == "burn", cold

    def test_fresh_burn_inside_window_burns_despite_clean_lifetime(self):
        reg = metrics.Registry()
        h = reg.histogram("bind_seconds", "x")
        ret = timeseries.Retention()
        t1 = time.monotonic()
        for _ in range(100):
            h.observe(0.01)  # a long healthy history
        ret.sample_now(registry=reg, now=t1 - 10.0)
        for _ in range(80):
            h.observe(8.0)  # the fresh incident, inside the window
        ret.sample_now(registry=reg, now=t1)
        obj = slo.Objective(
            "bind", "bind_seconds", target=1.0, percentile=0.5,
            kind="quantile_max", window_s=60.0,
        )
        e = slo.evaluate_objective(obj, registry=reg, history=ret)
        assert e["windowed"] is True
        assert e["verdict"] == "burn", e
        # Lifetime p50 is dominated by the healthy majority: the
        # cumulative fallback would still read pass — the window is
        # what makes the fresh incident visible.
        cold = slo.evaluate_objective(
            obj, registry=reg, history=timeseries.Retention()
        )
        assert cold["windowed"] is False
        assert cold["verdict"] == "pass", cold

    def test_counter_burn_outside_window_passes_windowed(self):
        reg = metrics.Registry()
        c = reg.counter("drops_total", "x", ("resource",))
        ret = timeseries.Retention()
        t1 = time.monotonic()
        c.inc(50, resource="pods")  # an old storm
        ret.sample_now(registry=reg, now=t1 - 10.0)
        ret.sample_now(registry=reg, now=t1)  # quiet since
        obj = slo.Objective(
            "drops", "drops_total", target=0.0, kind="counter_max",
            window_s=60.0,
        )
        e = slo.evaluate_objective(obj, registry=reg, history=ret)
        assert e["windowed"] is True and e["verdict"] == "pass"
        cold = slo.evaluate_objective(
            obj, registry=reg, history=timeseries.Retention()
        )
        assert cold["windowed"] is False and cold["verdict"] == "burn"

    def test_wrong_shaped_series_is_no_data_not_a_crash(self):
        # A counter registered under a latency objective's name is
        # unmeasurable, not a crash — /debug/health proxies this
        # evaluation, so an exception here would 500 the rollup.
        reg = metrics.Registry()
        c = reg.counter("bind_seconds", "x")
        c.inc(100)
        ret = timeseries.Retention()
        t1 = time.monotonic()
        ret.sample_now(registry=reg, now=t1 - 10.0)
        ret.sample_now(registry=reg, now=t1)
        obj = slo.Objective(
            "bind", "bind_seconds", target=1.0, window_s=60.0
        )
        e = slo.evaluate_objective(obj, registry=reg, history=ret)
        assert e["verdict"] == "no_data"
        assert e["samples"] == 0

    def test_windowless_objective_never_uses_history(self):
        reg = metrics.Registry()
        reg.histogram("bind_seconds", "x").observe(0.1)
        ret, (t0, t1) = self._history(reg)
        ret.sample_now(registry=reg, now=t0)
        ret.sample_now(registry=reg, now=t1)
        obj = slo.Objective("bind", "bind_seconds", target=1.0)
        e = slo.evaluate_objective(obj, registry=reg, history=ret)
        assert e["windowed"] is False
        assert "windowS" not in e

    def test_published_objectives_declare_windows(self):
        by_name = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        # Satellite 2: the replication-lag and lease-renew advisory
        # objectives are part of the published set.
        assert by_name["replication_follower_lag"].severity == "warn"
        assert by_name["replication_follower_lag"].kind == "gauge_max"
        assert by_name["lease_renew_latency"].severity == "warn"
        windowed = [o for o in slo.DEFAULT_OBJECTIVES if o.window_s > 0]
        assert len(windowed) >= 6


class TestDebugEndpoints:
    def _srv(self):
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        return APIHTTPServer(api).start()

    def _get(self, srv, path):
        with urllib.request.urlopen(srv.address + path, timeout=10) as r:
            return json.loads(r.read())

    def test_cold_miss_payloads(self):
        _reset_globals()
        srv = self._srv()
        try:
            with _quiet_global_registry():
                a = self._get(srv, "/debug/alerts")
                assert a["kind"] == "AlertReport" and a["sampled"] is False
                assert {r["name"] for r in a["rules"]} == {
                    r.name for r in alerts.DEFAULT_RULES
                }
                t = self._get(srv, "/debug/timeseries")
                assert t["kind"] == "TimeseriesReport"
                assert t["sampled"] is False and t["series"] == []
                h = self._get(srv, "/debug/health")
                assert h["kind"] == "HealthRollup"
                assert h["sampled"] is False
                assert {"slo", "alerts"} <= set(h["components"])
        finally:
            srv.stop()
            _reset_globals()

    def test_bad_window_is_400(self):
        srv = self._srv()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv, "/debug/timeseries?series=x&window=bogus")
            assert ei.value.code == 400
        finally:
            srv.stop()

    def test_populated_endpoints(self):
        srv = self._srv()
        reg = metrics.Registry()
        g = reg.gauge("hp_lag_versions", "x")
        rule = alerts.AlertRule(
            name="hp_lag_high", series="hp_lag_versions",
            threshold=100.0, kind="gauge_max",
            windows=(alerts.BurnWindow(60.0, 20.0, 1.0),),
            for_s=0.0, resolve_s=60.0, severity="page",
        )
        try:
            t1 = time.monotonic()
            g.set(500.0)
            timeseries.DEFAULT.sample_now(registry=reg, now=t1 - 5.0)
            timeseries.DEFAULT.sample_now(registry=reg, now=t1)
            alerts.DEFAULT.configure(rules=(rule,))
            alerts.DEFAULT.evaluate()

            ts = self._get(
                srv, "/debug/timeseries?series=hp_lag_versions&window=60"
            )
            assert ts["sampled"] is True
            q = ts["query"]
            assert q["found"] and q["type"] == "gauge"
            assert q["labelSets"][0]["max"] == 500.0

            a = self._get(srv, "/debug/alerts")
            assert a["sampled"] is True
            assert a["firing"] == ["hp_lag_high"]
            (row,) = a["rules"]
            assert row["state"] == "firing" and row["value"] == 500.0

            h = self._get(srv, "/debug/health")
            assert h["sampled"] is True
            comp = h["components"]["alerts"]
            assert comp["verdict"] == "burn"  # a firing page rule
            assert comp["firing"] == ["hp_lag_high"]
            assert h["verdict"] == "burn"
        finally:
            srv.stop()
            _reset_globals()


class TestKtctlContracts:
    def _client(self):
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        return Client(LocalTransport(APIServer()))

    def _run(self, argv, client):
        from kubernetes_tpu.cli import ktctl

        out, err = io.StringIO(), io.StringIO()
        with redirect_stdout(out), redirect_stderr(err):
            rc = ktctl.main(argv, client=client)
        return rc, out.getvalue(), err.getvalue()

    def test_alerts_miss_contract(self):
        _reset_globals()
        rc, out, err = self._run(["alerts"], self._client())
        assert rc == 1
        assert out == ""
        assert "no alert evaluations recorded" in err

    def test_top_health_miss_contract(self, monkeypatch):
        from kubernetes_tpu.cli import ktctl

        # The SLO plane is process-global and other suites may have
        # observed real samples; pin the fetch to an unmeasured
        # rollup to model the freshly booted cluster (check.sh proves
        # the same contract in a genuinely fresh process).
        monkeypatch.setattr(
            ktctl,
            "_fetch_health_rollup",
            lambda client, args: {
                "kind": "HealthRollup", "verdict": "no_data",
                "sampled": False, "components": {},
            },
        )
        rc, out, err = self._run(["top", "health"], self._client())
        assert rc == 1
        assert out == ""
        assert "no health samples recorded" in err

    def test_alerts_and_top_health_populated(self):
        reg = metrics.Registry()
        g = reg.gauge("hp_cli_lag_versions", "x")
        rule = alerts.AlertRule(
            name="hp_cli_lag", series="hp_cli_lag_versions",
            threshold=100.0, kind="gauge_max",
            windows=(alerts.BurnWindow(60.0, 20.0, 1.0),),
            for_s=0.0, resolve_s=60.0, severity="ticket",
        )
        try:
            with _quiet_global_registry():
                t1 = time.monotonic()
                g.set(900.0)
                timeseries.DEFAULT.sample_now(registry=reg, now=t1 - 5.0)
                timeseries.DEFAULT.sample_now(registry=reg, now=t1)
                alerts.DEFAULT.configure(rules=(rule,))
                alerts.DEFAULT.evaluate()
                client = self._client()

                rc, out, err = self._run(["alerts"], client)
                assert rc == 0, err
                assert "hp_cli_lag" in out and "firing" in out
                assert "firing: 1 (hp_cli_lag)" in out
                assert "RECENT TRANSITIONS" in out

                rc, out, _err = self._run(["alerts", "-o", "json"], client)
                assert rc == 0
                assert json.loads(out)["firing"] == ["hp_cli_lag"]

                rc, out, err = self._run(["top", "health"], client)
                assert rc == 0, err
                # A firing ticket-severity rule degrades overall to
                # warn (page severity would be burn).
                assert "overall: warn" in out
                assert "alerts" in out and "hp_cli_lag" in out
        finally:
            _reset_globals()
