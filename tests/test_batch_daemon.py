"""Batch (TPU) scheduler daemon against the real apiserver — the
minimum end-to-end slice of the north star: a backlog scheduled via the
device solver, bindings visible through the watch."""

import time

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
from kubernetes_tpu.server import APIServer


def pod_wire(name, cpu="100m", mem="64Mi"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {"name": "c", "image": "nginx",
                 "resources": {"limits": {"cpu": cpu, "memory": mem}}}
            ]
        },
    }


def node_wire(name, cpu="4", mem="8Gi", pods="110"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": pods},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def wait_until(cond, timeout=60.0):
    # Generous: the first batch solve inside the window pays the XLA
    # compile, which can exceed 20s when this single-core box is
    # contended (observed as a rare suite flake).
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    return False


def test_batch_schedules_backlog_config1():
    """BASELINE config 1: 100 pods x 10 nodes, resource predicates,
    scheduled via the device path, all bound."""
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(10):
        client.create("nodes", node_wire(f"n{j}"))
    for i in range(100):
        client.create("pods", pod_wire(f"p{i}"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg)
    # Watch from the current version to observe bindings flow out.
    _, version = client.list("pods", namespace="default")
    stream = client.watch("pods", namespace="default", since=version)
    total = 0
    deadline = time.monotonic() + 30
    while total < 100 and time.monotonic() < deadline:
        total += sched.schedule_batch(timeout=0.5)
    assert total == 100
    assert sched.fallback_count == 0, "device path fell back to scalar"
    pods, _ = client.list("pods", namespace="default")
    assert all(p.spec.node_name for p in pods)
    # Bindings were observable as MODIFIED events on the watch.
    seen = 0
    while True:
        ev = stream.next(timeout=0.5)
        if ev is None:
            break
        if ev.type == "MODIFIED" and ev.object["spec"].get("nodeName"):
            seen += 1
    assert seen == 100
    stream.close()
    cfg.stop()


def test_batch_daemon_thread_with_churn():
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(f"n{j}"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg).start()
    for i in range(40):
        client.create("pods", pod_wire(f"c{i}"))
        if i % 10 == 9:
            time.sleep(0.05)
    assert wait_until(
        lambda: all(
            p.spec.node_name for p in client.list("pods", namespace="default")[0]
        )
        and len(client.list("pods", namespace="default")[0]) == 40
    )
    sched.stop()


def test_batch_unschedulable_and_mixed():
    api = APIServer()
    client = Client(LocalTransport(api))
    client.create("nodes", node_wire("n0", cpu="1"))
    client.create("pods", pod_wire("fits", cpu="500m"))
    client.create("pods", pod_wire("huge", cpu="64"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg)
    assert wait_until(lambda: len(cfg.pod_queue) == 2)
    sched.schedule_batch(timeout=1)
    assert client.get("pods", "fits", namespace="default").spec.node_name == "n0"
    assert client.get("pods", "huge", namespace="default").spec.node_name == ""
    # Events ride the async broadcaster on the SCHEDULER's client.
    cfg.client.flush_events()
    events, _ = client.list("events", namespace="default")
    assert any(e.reason == "FailedScheduling" for e in events)
    cfg.stop()


def test_wave_mode_schedules_backlog():
    """The wave-commit mode places a whole backlog with valid bindings
    through the same daemon plumbing (bulk bindings, events)."""
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(f"n{j}"))
    for i in range(24):
        client.create("pods", pod_wire(f"w{i}"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg, mode="wave")
    try:
        processed = 0
        deadline = time.monotonic() + 60
        while processed < 24 and time.monotonic() < deadline:
            processed += sched.schedule_batch(timeout=0.5)
        pods, _ = client.list("pods", namespace="default")
        assert len(pods) == 24
        assert all(p.spec.node_name for p in pods)
        # Valid bindings: every target exists.
        names = {f"n{j}" for j in range(4)}
        assert all(p.spec.node_name in names for p in pods)
    finally:
        cfg.stop()


def test_sinkhorn_mode_schedules_backlog():
    """The Sinkhorn-matched mode drives the same daemon plumbing."""
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(f"n{j}"))
    for i in range(24):
        client.create("pods", pod_wire(f"s{i}"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg, mode="sinkhorn")
    try:
        processed = 0
        deadline = time.monotonic() + 60
        while processed < 24 and time.monotonic() < deadline:
            processed += sched.schedule_batch(timeout=0.5)
        pods, _ = client.list("pods", namespace="default")
        assert len(pods) == 24
        names = {f"n{j}" for j in range(4)}
        assert all(p.spec.node_name in names for p in pods)
    finally:
        cfg.stop()


def test_batch_mode_validation():
    api = APIServer()
    cfg = SchedulerConfig(Client(LocalTransport(api)))
    import pytest as _pytest

    with _pytest.raises(ValueError):
        BatchScheduler(cfg, mode="warp")


def test_batch_respects_assumed_capacity_across_batches():
    """Two sequential batches: the second must see the first's
    assumed placements before the watch confirms them."""
    api = APIServer()
    client = Client(LocalTransport(api))
    client.create("nodes", node_wire("n0", cpu="1", pods="40"))
    client.create("nodes", node_wire("n1", cpu="1", pods="40"))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg)
    client.create("pods", pod_wire("a", cpu="600m"))
    assert wait_until(lambda: len(cfg.pod_queue) == 1)
    sched.schedule_batch(timeout=1)
    client.create("pods", pod_wire("b", cpu="600m"))
    assert wait_until(lambda: len(cfg.pod_queue) >= 1)
    sched.schedule_batch(timeout=1)
    hosts = sorted(
        p.spec.node_name for p in client.list("pods", namespace="default")[0]
    )
    assert hosts == ["n0", "n1"]
    cfg.stop()


def node_wire_labeled(name, labels, **kw):
    w = node_wire(name, **kw)
    w["metadata"]["labels"] = labels
    return w


def test_batch_honors_scheduler_policy():
    """--batch --policy-config-file: the device path must schedule with
    the CONFIGURED plugin set, not defaults (round-2 VERDICT Weak #1).
    Policy: only nodes carrying tier=fast are eligible."""
    api = APIServer()
    client = Client(LocalTransport(api))
    client.create("nodes", node_wire_labeled("slow0", {"tier": "slow"}))
    client.create("nodes", node_wire_labeled("fast0", {"tier": "fast"}))
    for i in range(20):
        client.create("pods", pod_wire(f"p{i}"))
    policy = {
        "kind": "Policy",
        "predicates": [
            {"name": "PodFitsResources"},
            {"name": "tier", "argument": {
                "labelsPresence": {"labels": ["tier"], "presence": True}}},
            # Note: NO general label predicate keeps slow0 in; the
            # real constraint below is the label-preference priority.
        ],
        "priorities": [
            {"name": "fast", "weight": 1, "argument": {
                "labelPreference": {"label": "fast-disk", "presence": True}}},
        ],
    }
    # Give only fast0 the preferred label: every pod must land there
    # under the policy (default policy would spread across both).
    api.store.guaranteed_update(
        "/registry/nodes/fast0",
        lambda n: {**n, "metadata": {**n["metadata"],
                   "labels": {"tier": "fast", "fast-disk": "true"}}},
    )
    cfg = SchedulerConfig(Client(LocalTransport(api)), policy=policy).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg, mode="sinkhorn")  # must be overridden
    assert sched.mode == "scan", "non-default policy must force the scan solver"
    assert not sched.policy_scalar
    total = 0
    deadline = time.monotonic() + 60
    while total < 20 and time.monotonic() < deadline:
        total += sched.schedule_batch(timeout=0.5)
    assert total == 20
    assert sched.fallback_count == 0, "policy lowering fell back to scalar"
    pods, _ = client.list("pods", namespace="default")
    assert all(p.spec.node_name == "fast0" for p in pods), [
        (p.metadata.name, p.spec.node_name) for p in pods if p.spec.node_name != "fast0"
    ]


def test_batch_unlowerable_policy_runs_scalar_with_policy():
    """A policy naming a custom-registered predicate can't lower; the
    batch daemon must run the CONFIGURED plugins on the scalar path
    (never default-policy decisions, never a crash)."""
    from kubernetes_tpu.scheduler.plugins import register_fit_predicate

    register_fit_predicate(
        "OnlyEvenNodes",
        lambda args: lambda pod, existing, node: node[-1] in "02468",
    )
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(f"n{j}"))
    for i in range(10):
        client.create("pods", pod_wire(f"p{i}"))
    policy = {
        "predicates": [{"name": "PodFitsResources"}, {"name": "OnlyEvenNodes"}],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
    }
    cfg = SchedulerConfig(Client(LocalTransport(api)), policy=policy).start()
    assert cfg.wait_for_sync(timeout=60)
    sched = BatchScheduler(cfg)
    assert sched.policy_scalar, "unlowerable policy must pin the scalar path"
    total = 0
    deadline = time.monotonic() + 30
    while total < 10 and time.monotonic() < deadline:
        total += sched.schedule_batch(timeout=0.5)
    assert total == 10
    pods, _ = client.list("pods", namespace="default")
    assert all(p.spec.node_name in ("n0", "n2") for p in pods), [
        (p.metadata.name, p.spec.node_name) for p in pods
    ]


def test_batch_mode_auto_is_topology_aware():
    """--batch-mode auto picks the scan (pallas-eligible, exact
    parity) for an unsharded solve — even on a multi-device host,
    since the daemon's solve runs on one device unless a mesh is in
    play — and the wave solver when the solve shards over a mesh,
    where the scan's per-pod step would pay one collective round per
    pod (docs/performance.md, mesh crossover)."""
    import jax

    from kubernetes_tpu.scheduler.batch import resolve_batch_mode

    # Explicit modes pass through untouched.
    for m in ("scan", "wave", "sinkhorn"):
        assert resolve_batch_mode(m) == m
    # This test process sees 8 virtual devices, but an unsharded solve
    # still wants the scan.
    assert len(jax.devices()) > 1
    assert resolve_batch_mode("auto") == "scan"
    assert resolve_batch_mode("auto", mesh=object()) == "wave"


def test_daemon_accepts_auto_mode():
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
    from kubernetes_tpu.server import APIServer

    cfg = SchedulerConfig(Client(LocalTransport(APIServer()))).start()
    try:
        assert cfg.wait_for_sync()
        sched = BatchScheduler(cfg, mode="auto")
        assert sched.mode in ("scan", "wave")  # resolved, never "auto"
    finally:
        cfg.stop()


def test_batch_mode_auto_resolution_keyed_on_mesh_argument():
    """Direct unit coverage for resolve_batch_mode's `mesh` keying with
    a REAL jax.sharding.Mesh (not a sentinel): auto resolves by the
    mesh the solve will actually run on, and explicit modes are never
    second-guessed by topology."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from kubernetes_tpu.scheduler.batch import resolve_batch_mode

    assert resolve_batch_mode("auto", mesh=None) == "scan"
    mesh = Mesh(np.array(jax.devices()), ("nodes",))
    assert resolve_batch_mode("auto", mesh=mesh) == "wave"
    for m in ("scan", "wave", "sinkhorn"):
        assert resolve_batch_mode(m, mesh=mesh) == m
        assert resolve_batch_mode(m, mesh=None) == m


def test_batch_mode_auto_honors_kt_mesh_devices(monkeypatch):
    """The KT_MESH_DEVICES=N escape hatch (this test process sees 8
    forced CPU devices): auto consults env_mesh() when no mesh was
    passed, so operators can engage the wave path before ROADMAP item
    2 threads a session mesh through the daemons. Unset, =1 (explicit
    no-mesh), and garbage values all fall back to the unsharded scan
    instead of crashing the scheduler."""
    from kubernetes_tpu.scheduler import batch

    monkeypatch.setenv("KT_MESH_DEVICES", "8")
    assert batch.env_mesh() is not None
    assert batch.resolve_batch_mode("auto") == "wave"
    # Explicit modes are never second-guessed by the hatch.
    for m in ("scan", "wave", "sinkhorn"):
        assert batch.resolve_batch_mode(m) == m
    # An explicit mesh argument wins regardless of the env.
    assert batch.resolve_batch_mode("auto", mesh=object()) == "wave"

    monkeypatch.delenv("KT_MESH_DEVICES")
    assert batch.env_mesh() is None
    assert batch.resolve_batch_mode("auto") == "scan"

    for bad in ("1", "0", "not-a-number", "1000000"):
        monkeypatch.setenv("KT_MESH_DEVICES", bad)
        assert batch.env_mesh() is None, bad
        assert batch.resolve_batch_mode("auto") == "scan"


def test_batch_mode_auto_meshless_warns_once(caplog):
    """ADVICE r5: no shipped daemon threads a mesh, so auto always
    resolves to scan in production — resolve_batch_mode says so in the
    log, ONCE per process, and never when a mesh is actually passed."""
    import logging

    from kubernetes_tpu.scheduler import batch

    batch._AUTO_NO_MESH_WARNED = False  # fresh one-shot for this test
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.scheduler.batch"):
        batch.resolve_batch_mode("auto")
        batch.resolve_batch_mode("auto")  # second resolve: silent
        batch.resolve_batch_mode("scan")  # explicit modes: silent
    warned = [
        r for r in caplog.records if "auto currently ALWAYS selects scan" in r.message
    ]
    assert len(warned) == 1
