"""Admission chain, plugins, and authn/authz tests.

Reference behavior: pkg/admission/ + plugin/pkg/admission/ (chain,
LimitRanger, ResourceQuota, namespace plugins, ServiceAccount,
SecurityContextDeny), pkg/apiserver/authn.go, pkg/auth/authorizer/abac,
pkg/serviceaccount/jwt.go."""

import base64
import json
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.server import admission as adm
from kubernetes_tpu.server import auth as authpkg
from kubernetes_tpu.server.api import APIError, APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def make_api(*plugin_names):
    api = APIServer()
    api.admission = adm.new_from_plugins(api, list(plugin_names))
    return api


POD = {
    "kind": "Pod",
    "metadata": {"name": "p1"},
    "spec": {"containers": [{"name": "c", "image": "nginx"}]},
}


def pod_with_resources(cpu="500m", mem="128Mi", name="p1"):
    return {
        "kind": "Pod",
        "metadata": {"name": name},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "nginx",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }


class TestChain:
    def test_always_deny(self):
        api = make_api("AlwaysDeny")
        with pytest.raises(APIError) as ei:
            api.create("pods", "default", dict(POD))
        assert ei.value.code == 403

    def test_always_admit(self):
        api = make_api("AlwaysAdmit")
        assert api.create("pods", "default", json.loads(json.dumps(POD)))

    def test_unknown_plugin(self):
        with pytest.raises(ValueError):
            adm.new_from_plugins(APIServer(), ["NoSuchPlugin"])

    def test_first_rejection_wins(self):
        api = make_api("AlwaysAdmit", "AlwaysDeny")
        with pytest.raises(APIError):
            api.create("pods", "default", dict(POD))


class TestNamespacePlugins:
    def test_exists_rejects_missing(self):
        api = make_api("NamespaceExists")
        pod = json.loads(json.dumps(POD))
        pod["metadata"]["namespace"] = "nope"
        with pytest.raises(APIError) as ei:
            api.create("pods", "nope", pod)
        assert ei.value.code == 404

    def test_autoprovision_creates(self):
        api = make_api("NamespaceAutoProvision")
        pod = json.loads(json.dumps(POD))
        pod["metadata"]["namespace"] = "fresh"
        api.create("pods", "fresh", pod)
        assert api.get("namespaces", "", "fresh")["metadata"]["name"] == "fresh"

    def test_lifecycle_rejects_terminating(self):
        api = make_api("NamespaceLifecycle")
        api.create("namespaces", "", {"metadata": {"name": "dying"}})
        api.update_status(
            "namespaces", "", "dying", {"status": {"phase": "Terminating"}}
        )
        pod = json.loads(json.dumps(POD))
        pod["metadata"]["namespace"] = "dying"
        with pytest.raises(APIError) as ei:
            api.create("pods", "dying", pod)
        assert ei.value.code == 403


class TestLimitRanger:
    def setup_method(self):
        self.api = make_api("LimitRanger")
        self.api.create(
            "limitranges",
            "default",
            {
                "kind": "LimitRange",
                "metadata": {"name": "limits"},
                "spec": {
                    "limits": [
                        {
                            "type": "Container",
                            "min": {"cpu": "100m"},
                            "max": {"cpu": "2", "memory": "1Gi"},
                            "default": {"cpu": "250m", "memory": "128Mi"},
                        }
                    ]
                },
            },
        )

    def test_defaults_applied(self):
        created = self.api.create("pods", "default", json.loads(json.dumps(POD)))
        limits = created["spec"]["containers"][0]["resources"]["limits"]
        assert limits["cpu"] == "250m"
        assert limits["memory"] == "128Mi"

    def test_max_enforced(self):
        with pytest.raises(APIError) as ei:
            self.api.create("pods", "default", pod_with_resources(cpu="4"))
        assert "maximum cpu" in ei.value.message

    def test_min_enforced(self):
        with pytest.raises(APIError) as ei:
            self.api.create("pods", "default", pod_with_resources(cpu="50m"))
        assert "minimum cpu" in ei.value.message

    def test_patch_cannot_evade_limits(self):
        """PATCH runs the admission chain on the MERGED object — a
        merge patch must not be a side door around LimitRanger."""
        self.api.create("pods", "default", pod_with_resources(cpu="500m"))
        with pytest.raises(APIError) as ei:
            self.api.patch(
                "pods",
                "default",
                "p1",
                {
                    "spec": {
                        "containers": [
                            {
                                "name": "c",
                                "image": "nginx",
                                "resources": {"limits": {"cpu": "4"}},
                            }
                        ]
                    }
                },
            )
        assert "maximum cpu" in ei.value.message


class TestResourceQuota:
    def setup_method(self):
        self.api = make_api("ResourceQuota")
        self.api.create(
            "resourcequotas",
            "default",
            {
                "kind": "ResourceQuota",
                "metadata": {"name": "q"},
                "spec": {"hard": {"pods": "2", "cpu": "1"}},
            },
        )

    def test_pod_count_enforced(self):
        self.api.create("pods", "default", pod_with_resources(cpu="100m", name="a"))
        self.api.create("pods", "default", pod_with_resources(cpu="100m", name="b"))
        with pytest.raises(APIError) as ei:
            self.api.create("pods", "default", pod_with_resources(cpu="100m", name="c"))
        assert "limited to 2 pods" in ei.value.message

    def test_cpu_quota_enforced(self):
        self.api.create("pods", "default", pod_with_resources(cpu="800m", name="a"))
        with pytest.raises(APIError) as ei:
            self.api.create("pods", "default", pod_with_resources(cpu="500m", name="b"))
        assert "cpu quota exceeded" in ei.value.message

    def test_status_used_updated(self):
        self.api.create("pods", "default", pod_with_resources(cpu="800m", name="a"))
        q = self.api.get("resourcequotas", "default", "q")
        assert q["status"]["used"]["pods"] == "1"
        assert q["status"]["used"]["cpu"] == "800m"


class TestServiceAccountAndSecurityContext:
    def test_sa_defaulted(self):
        api = make_api("ServiceAccount")
        created = api.create("pods", "default", json.loads(json.dumps(POD)))
        assert created["spec"]["serviceAccount"] == "default"

    def test_api_token_mounted(self):
        """The account's token Secret is mounted into every container
        at the well-known path (plugin/pkg/admission/serviceaccount
        mountServiceAccountToken)."""
        api = make_api("ServiceAccount")
        api.create(
            "secrets",
            "default",
            {
                "kind": "Secret",
                "metadata": {"name": "default-token"},
                "type": "kubernetes.io/service-account-token",
                "data": {"token": "eyJ..."},
            },
        )
        api.create(
            "serviceaccounts",
            "default",
            {
                "kind": "ServiceAccount",
                "metadata": {"name": "default"},
                "secrets": [{"kind": "Secret", "name": "default-token"}],
            },
        )
        created = api.create("pods", "default", json.loads(json.dumps(POD)))
        vols = created["spec"]["volumes"]
        assert any(
            (v.get("secret") or {}).get("secretName") == "default-token"
            for v in vols
        )
        mounts = created["spec"]["containers"][0]["volumeMounts"]
        sa_mount = next(
            m
            for m in mounts
            if m["mountPath"] == "/var/run/secrets/kubernetes.io/serviceaccount"
        )
        assert sa_mount["readOnly"] is True

    def test_no_token_secret_is_soft(self):
        """No SA / no token secret yet: pod admits untouched (the
        plugin must not block during controller warm-up)."""
        api = make_api("ServiceAccount")
        created = api.create("pods", "default", json.loads(json.dumps(POD)))
        assert not created["spec"].get("volumes")

    def test_privileged_denied(self):
        api = make_api("SecurityContextDeny")
        pod = json.loads(json.dumps(POD))
        pod["spec"]["containers"][0]["securityContext"] = {"privileged": True}
        with pytest.raises(APIError) as ei:
            api.create("pods", "default", pod)
        assert "privileged" in ei.value.message


class TestAuthenticators:
    def test_password(self):
        a = authpkg.PasswordAuthenticator(
            {"alice": ("secret", authpkg.UserInfo(name="alice", uid="1"))}
        )
        assert a.authenticate_password("alice", "secret").name == "alice"
        with pytest.raises(authpkg.AuthenticationError):
            a.authenticate_password("alice", "wrong")

    def test_token_file(self, tmp_path):
        p = tmp_path / "tokens.csv"
        p.write_text("tok123,bob,2,admins,devs\n# comment\n")
        a = authpkg.TokenAuthenticator.from_file(str(p))
        info = a.authenticate_token("tok123")
        assert info.name == "bob" and "admins" in info.groups

    def test_sa_jwt_roundtrip(self):
        mgr = authpkg.ServiceAccountTokenManager(b"cluster-signing-key")
        tok = mgr.mint("default", "builder", uid="u1", secret_name="builder-token")
        info = mgr.authenticate_token(tok)
        assert info.name == "system:serviceaccount:default:builder"
        assert "system:serviceaccounts" in info.groups
        # Tampering is detected.
        h, c, s = tok.split(".")
        bad_claims = base64.urlsafe_b64encode(
            json.dumps({"iss": authpkg.ISSUER}).encode()
        ).rstrip(b"=").decode()
        with pytest.raises(authpkg.AuthenticationError):
            mgr.authenticate_token(f"{h}.{bad_claims}.{s}")


class TestABAC:
    def make(self):
        return authpkg.ABACAuthorizer(
            [
                authpkg.Policy(user="admin"),
                authpkg.Policy(user="reader", readonly=True),
                authpkg.Policy(group="schedulers", resource="pods"),
                authpkg.Policy(user="nsuser", namespace="team1"),
            ]
        )

    def attrs(self, name, groups=(), **kw):
        return authpkg.AuthzAttributes(
            user=authpkg.UserInfo(name=name, groups=tuple(groups)), **kw
        )

    def test_admin_all(self):
        self.make().authorize(self.attrs("admin", resource="pods"))

    def test_reader_only_reads(self):
        a = self.make()
        a.authorize(self.attrs("reader", readonly=True, resource="pods"))
        with pytest.raises(authpkg.AuthorizationError):
            a.authorize(self.attrs("reader", readonly=False, resource="pods"))

    def test_group_and_resource_scope(self):
        a = self.make()
        a.authorize(self.attrs("x", groups=["schedulers"], resource="pods"))
        with pytest.raises(authpkg.AuthorizationError):
            a.authorize(self.attrs("x", groups=["schedulers"], resource="nodes"))

    def test_namespace_scope(self):
        a = self.make()
        a.authorize(self.attrs("nsuser", resource="pods", namespace="team1"))
        with pytest.raises(authpkg.AuthorizationError):
            a.authorize(self.attrs("nsuser", resource="pods", namespace="team2"))

    def test_policy_file(self, tmp_path):
        p = tmp_path / "policy.jsonl"
        p.write_text(
            '{"user": "alice"}\n'
            '# comment\n'
            '{"group": "system:serviceaccounts", "readonly": true}\n'
        )
        a = authpkg.ABACAuthorizer.from_file(str(p))
        a.authorize(self.attrs("alice", resource="pods"))
        a.authorize(
            self.attrs("sa", groups=["system:serviceaccounts"], readonly=True)
        )


class TestHTTPAuth:
    """Auth enforced at the HTTP boundary: 401 bad creds, 403 denied."""

    def setup_method(self):
        authn = authpkg.UnionAuthenticator(
            password=authpkg.PasswordAuthenticator(
                {"admin": ("pw", authpkg.UserInfo(name="admin"))}
            ),
            tokens=[
                authpkg.TokenAuthenticator(
                    {"rotoken": authpkg.UserInfo(name="reader")}
                )
            ],
        )
        authz = authpkg.ABACAuthorizer(
            [
                authpkg.Policy(user="admin"),
                authpkg.Policy(user="reader", readonly=True),
            ]
        )
        self.srv = APIHTTPServer(
            APIServer(), authenticator=authn, authorizer=authz
        ).start()
        self.base = self.srv.address

    def teardown_method(self):
        self.srv.stop()

    def req(self, method, path, body=None, headers=None):
        data = json.dumps(body).encode() if body is not None else None
        r = urllib.request.Request(
            self.base + path, data=data, method=method, headers=headers or {}
        )
        try:
            with urllib.request.urlopen(r) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def basic(self, user, pw):
        return {
            "Authorization": "Basic " + base64.b64encode(f"{user}:{pw}".encode()).decode()
        }

    def test_no_creds_401(self):
        code, _ = self.req("GET", "/api/v1/pods")
        assert code == 401

    def test_bad_password_401(self):
        code, _ = self.req("GET", "/api/v1/pods", headers=self.basic("admin", "no"))
        assert code == 401

    def test_admin_can_write(self):
        code, _ = self.req(
            "POST",
            "/api/v1/namespaces/default/pods",
            body=POD,
            headers=self.basic("admin", "pw"),
        )
        assert code == 201

    def test_reader_can_read_not_write(self):
        hdr = {"Authorization": "Bearer rotoken"}
        code, _ = self.req("GET", "/api/v1/pods", headers=hdr)
        assert code == 200
        code, _ = self.req(
            "POST", "/api/v1/namespaces/default/pods", body=POD, headers=hdr
        )
        assert code == 403

    def test_healthz_unauthenticated(self):
        r = urllib.request.Request(self.base + "/healthz")
        with urllib.request.urlopen(r) as resp:
            assert resp.status == 200


class TestResourceQuotaUpdateDelete:
    """UPDATE and DELETE paths of the quota plugin (reference handles
    Create and Update; delete reconciliation keeps used accurate)."""

    def setup_method(self):
        self.api = make_api("ResourceQuota")
        self.api.create(
            "resourcequotas",
            "default",
            {
                "kind": "ResourceQuota",
                "metadata": {"name": "q"},
                "spec": {"hard": {"pods": "5", "cpu": "1"}},
            },
        )

    def test_update_enforces_cpu(self):
        self.api.create("pods", "default", pod_with_resources(cpu="800m", name="a"))
        grown = pod_with_resources(cpu="4", name="a")
        with pytest.raises(APIError) as ei:
            self.api.update("pods", "default", "a", grown)
        assert "cpu quota exceeded" in ei.value.message
        # Shrinking is always allowed.
        self.api.update("pods", "default", "a", pod_with_resources(cpu="100m", name="a"))
        q = self.api.get("resourcequotas", "default", "q")
        assert q["status"]["used"]["cpu"] == "100m"

    def test_delete_decrements_used(self):
        self.api.create("pods", "default", pod_with_resources(cpu="500m", name="a"))
        self.api.delete("pods", "default", "a")
        q = self.api.get("resourcequotas", "default", "q")
        assert q["status"]["used"]["pods"] == "0"
        assert q["status"]["used"]["cpu"] == "0"

    def test_delete_missing_leaves_status(self):
        self.api.create("pods", "default", pod_with_resources(cpu="500m", name="a"))
        with pytest.raises(APIError):
            self.api.delete("pods", "default", "ghost")
        q = self.api.get("resourcequotas", "default", "q")
        assert q["status"]["used"]["pods"] == "1"

    def test_concurrent_creates_cannot_exceed(self):
        import threading

        api = make_api("ResourceQuota")
        api.create(
            "resourcequotas",
            "default",
            {
                "kind": "ResourceQuota",
                "metadata": {"name": "q"},
                "spec": {"hard": {"pods": "3"}},
            },
        )
        results = []

        def creator(i):
            try:
                api.create("pods", "default", pod_with_resources(name=f"p{i}"))
                results.append(True)
            except APIError:
                results.append(False)

        threads = [threading.Thread(target=creator, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sum(results) == 3
        assert len(api.list("pods", "default")["items"]) == 3


class TestExecAdmission:
    def test_deny_exec_on_privileged(self):
        api = make_api("DenyExecOnPrivileged")
        pod = json.loads(json.dumps(POD))
        pod["spec"]["containers"][0]["securityContext"] = {"privileged": True}
        api.create("pods", "default", pod)
        with pytest.raises(APIError) as ei:
            api.connect("pods", "default", "p1", "exec")
        assert ei.value.code == 403
        # Unprivileged pods pass the gate.
        unpriv = json.loads(json.dumps(POD))
        unpriv["metadata"]["name"] = "p2"
        api.create("pods", "default", unpriv)
        api.connect("pods", "default", "p2", "exec")  # no raise


class TestAdmissionErrorReasons:
    def test_missing_namespace_reason_notfound(self):
        api = make_api("NamespaceExists")
        pod = json.loads(json.dumps(POD))
        pod["metadata"]["namespace"] = "nope"
        with pytest.raises(APIError) as ei:
            api.create("pods", "nope", pod)
        assert ei.value.code == 404 and ei.value.reason == "NotFound"
