"""Static pod sources: manifest dir (file) and manifest URL (http).

Reference: pkg/kubelet/config/{file,http}.go — the kubelet's three pod
sources are the apiserver watch, a manifest directory, and a polled
manifest URL; file/URL pods are mirrored to the apiserver as
"<name>-<node>" pods."""

import http.server
import json
import os
import threading
import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.kubelet.agent import Kubelet
from kubernetes_tpu.kubelet.runtime import FakeRuntime
from kubernetes_tpu.server.api import APIServer


def wait_until(cond, timeout=15.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def manifest(name, image="static"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": image}]},
    }


class _ManifestHandler(http.server.BaseHTTPRequestHandler):
    payload = b"{}"

    def log_message(self, *a):
        pass

    def do_GET(self):
        self.send_response(200)
        self.send_header("Content-Length", str(len(self.payload)))
        self.end_headers()
        self.wfile.write(self.payload)


@pytest.fixture
def manifest_server():
    handler = type("H", (_ManifestHandler,), {})
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv, handler
    srv.shutdown()
    srv.server_close()


def pod_names(client):
    pods, _ = client.list("pods", namespace="default")
    return {p.metadata.name for p in pods}


class TestManifestURL:
    def test_url_pods_mirror_update_and_remove(self, manifest_server):
        srv, handler = manifest_server
        handler.payload = json.dumps(manifest("web")).encode()
        api = APIServer()
        client = Client(LocalTransport(api))
        kubelet = Kubelet(
            Client(LocalTransport(api)),
            node_name="n1",
            runtime=FakeRuntime(),
            heartbeat_period=0.5,
            sync_period=0.3,
            manifest_url=f"http://127.0.0.1:{srv.server_address[1]}/",
        ).start()
        try:
            assert wait_until(lambda: "web-n1" in pod_names(client))
            pod = client.get("pods", "web-n1", namespace="default")
            assert pod.spec.node_name == "n1"  # pinned to this node

            # List payloads work; removing an entry deletes its mirror.
            handler.payload = json.dumps(
                {
                    "kind": "PodList",
                    "items": [manifest("web"), manifest("extra")],
                }
            ).encode()
            assert wait_until(lambda: "extra-n1" in pod_names(client))
            handler.payload = json.dumps(manifest("web")).encode()
            assert wait_until(lambda: "extra-n1" not in pod_names(client))

            # Edited manifest replaces the mirror pod.
            handler.payload = json.dumps(manifest("web", image="v2")).encode()
            assert wait_until(
                lambda: "web-n1" in pod_names(client)
                and client.get("pods", "web-n1", namespace="default")
                .spec.containers[0]
                .image
                == "v2"
            )
        finally:
            kubelet.stop()

    def test_malformed_but_parseable_payload_keeps_state(
        self, manifest_server
    ):
        """{} / error JSON with HTTP 200 must not tear down static pods
        (only a well-formed Pod/PodList may add or remove)."""
        srv, handler = manifest_server
        handler.payload = json.dumps(manifest("keepme")).encode()
        api = APIServer()
        client = Client(LocalTransport(api))
        kubelet = Kubelet(
            Client(LocalTransport(api)),
            node_name="n1",
            runtime=FakeRuntime(),
            heartbeat_period=0.5,
            sync_period=0.3,
            manifest_url=f"http://127.0.0.1:{srv.server_address[1]}/",
        ).start()
        try:
            assert wait_until(lambda: "keepme-n1" in pod_names(client))
            for bad in (b"{}", b"null", b'{"error": "busy"}'):
                handler.payload = bad
                time.sleep(2.5)
                assert "keepme-n1" in pod_names(client), bad
            # But an explicit empty PodList DOES clear them.
            handler.payload = json.dumps(
                {"kind": "PodList", "items": []}
            ).encode()
            assert wait_until(lambda: "keepme-n1" not in pod_names(client))
        finally:
            kubelet.stop()

    def test_unreachable_url_keeps_state(self, manifest_server):
        """A fetch failure must NOT tear down running static pods
        (config/http.go keeps the last good config)."""
        srv, handler = manifest_server
        handler.payload = json.dumps(manifest("stay")).encode()
        api = APIServer()
        client = Client(LocalTransport(api))
        kubelet = Kubelet(
            Client(LocalTransport(api)),
            node_name="n1",
            runtime=FakeRuntime(),
            heartbeat_period=0.5,
            sync_period=0.3,
            manifest_url=f"http://127.0.0.1:{srv.server_address[1]}/",
        ).start()
        try:
            assert wait_until(lambda: "stay-n1" in pod_names(client))
            srv.shutdown()
            srv.server_close()
            time.sleep(3)  # a few failed polls
            assert "stay-n1" in pod_names(client)
        finally:
            kubelet.stop()


class TestManifestDir:
    def test_dir_pods_mirror_and_remove(self, tmp_path):
        api = APIServer()
        client = Client(LocalTransport(api))
        path = tmp_path / "static.json"
        path.write_text(json.dumps(manifest("disk")))
        kubelet = Kubelet(
            Client(LocalTransport(api)),
            node_name="n1",
            runtime=FakeRuntime(),
            heartbeat_period=0.5,
            sync_period=0.3,
            manifest_dir=str(tmp_path),
        ).start()
        try:
            assert wait_until(lambda: "disk-n1" in pod_names(client))
            os.unlink(path)
            assert wait_until(lambda: "disk-n1" not in pod_names(client))
        finally:
            kubelet.stop()
