"""Solver sidecar: process isolation + the crash-fallback story.

Reference framing: SURVEY §2.15/§5 — the north star's control plane
and accelerator live in separate processes; a solver failure degrades
to the stock scalar path (VERDICT r1 A8 flagged this as untested)."""

import os
import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.ops.sidecar import SidecarError, SidecarSolver, spawn_sidecar
from kubernetes_tpu.scheduler.batch import parity_report, schedule_backlog_tpu
from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
from kubernetes_tpu.server.api import APIServer
from test_solver_parity import random_cluster


def _stop_proc(proc):
    """terminate, then kill: SIGTERM can't interrupt a native XLA
    compile, and a hung wait() here flakes the whole module."""
    if proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except Exception:
        proc.kill()
        proc.wait(timeout=10)


@pytest.fixture(scope="module")
def sidecar():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # the subprocess owns its own backend
    proc, sock_path = spawn_sidecar(env=env, wait=120)
    yield sock_path
    _stop_proc(proc)


class TestSidecarSolve:
    def test_matches_in_process_solver(self, sidecar):
        pods, nodes, assigned, services = random_cluster(4)
        local = schedule_backlog_tpu(pods, nodes, assigned, services)
        remote = SidecarSolver(sidecar).solve(pods, nodes, assigned, services)
        parity, mismatches = parity_report(local, remote)
        assert parity == 1.0, mismatches

    def test_ping(self, sidecar):
        assert SidecarSolver(sidecar).ping()

    def test_wave_mode_travels_to_sidecar(self, sidecar):
        """mode='wave' must run the wave solver inside the sidecar —
        valid placements for the whole backlog."""
        pods, nodes, assigned, services = random_cluster(2)
        remote = SidecarSolver(sidecar).solve(
            pods, nodes, assigned, services, mode="wave"
        )
        assert len(remote) == len(pods)
        names = {n.metadata.name for n in nodes}
        assert all(dest is None or dest in names for dest in remote)

    def test_garbage_frame_does_not_kill_sidecar(self, sidecar):
        """Per-connection containment: a junk frame must not exit the
        serve loop."""
        import socket as socketlib
        import struct

        s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
        s.connect(sidecar)
        s.sendall(struct.pack(">Q", 7) + b"garbage")
        s.close()
        assert SidecarSolver(sidecar).ping()  # still alive

    def test_dead_socket_raises_sidecar_error(self):
        pods, nodes, assigned, services = random_cluster(1)
        dead = SidecarSolver("/nonexistent/solver.sock", timeout=2)
        assert not dead.ping()
        with pytest.raises(SidecarError):
            dead.solve(pods, nodes, assigned, services)


def node_wire(name):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": "4", "memory": "8Gi", "pods": "40"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "x",
                    "resources": {"limits": {"cpu": "100m", "memory": "64Mi"}},
                }
            ]
        },
    }


class TestCrashFallback:
    def test_scheduler_survives_dead_sidecar_via_scalar_fallback(self):
        """Sidecar gone -> the batch scheduler's fallback seam runs the
        scalar oracle and the backlog still schedules."""
        api = APIServer()
        client = Client(LocalTransport(api))
        for j in range(3):
            client.create("nodes", node_wire(f"n{j}"))
        for i in range(9):
            client.create("pods", pod_wire(f"p{i}"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = BatchScheduler(
            cfg, sidecar_path="/nonexistent/solver.sock"
        )
        sched.sidecar.timeout = 2  # fail fast in the test
        try:
            processed = 0
            deadline = time.monotonic() + 60
            while processed < 9 and time.monotonic() < deadline:
                processed += sched.schedule_batch(timeout=0.5)
            pods, _ = client.list("pods", namespace="default")
            assert all(p.spec.node_name for p in pods)
            assert sched.fallback_count > 0  # the fallback actually ran
        finally:
            cfg.stop()

    def test_live_sidecar_then_killed_mid_run(self, tmp_path):
        """Scheduler uses a live sidecar, the sidecar dies, scheduling
        continues through the fallback."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc, sock_path = spawn_sidecar(env=env, wait=120)
        try:
            api = APIServer()
            client = Client(LocalTransport(api))
            for j in range(3):
                client.create("nodes", node_wire(f"n{j}"))
            cfg = SchedulerConfig(Client(LocalTransport(api))).start()
            assert cfg.wait_for_sync()
            sched = BatchScheduler(cfg, sidecar_path=sock_path)
            # The sidecar's FIRST solve pays the XLA compile; on a
            # contended box that can blow the 15s default timeout and
            # fake a crash (observed suite flake). The short timeout
            # matters for the post-kill phase only.
            sched.sidecar.timeout = 120
            try:
                client.create("pods", pod_wire("before"))
                deadline = time.monotonic() + 60
                done = 0
                while done < 1 and time.monotonic() < deadline:
                    done += sched.schedule_batch(timeout=0.5)
                assert client.get(
                    "pods", "before", namespace="default"
                ).spec.node_name
                assert sched.fallback_count == 0  # sidecar did the work

                _stop_proc(proc)
                sched.sidecar.timeout = 2
                client.create("pods", pod_wire("after"))
                done = 0
                deadline = time.monotonic() + 60
                while done < 1 and time.monotonic() < deadline:
                    done += sched.schedule_batch(timeout=0.5)
                assert client.get(
                    "pods", "after", namespace="default"
                ).spec.node_name
                assert sched.fallback_count > 0
            finally:
                cfg.stop()
        finally:
            _stop_proc(proc)


class TestWireProtocol:
    """The schema'd array protocol (VERDICT r2 Weak #6: no pickle —
    version skew fails clean, frames carry data only)."""

    def test_encode_decode_round_trip(self):
        import numpy as np

        from kubernetes_tpu.models.algspec import LoweredSpec
        from kubernetes_tpu.ops.sidecar import _decode, _encode

        msg = {
            "op": "solve",
            "mode": "scan",
            "pods": {
                "cpu": np.arange(6, dtype=np.float32),
                "bits": np.array([[1, 2], [3, 4]], dtype=np.uint32),
                "empty": np.zeros((0, 3), dtype=np.int32),
            },
            "weights": (2, 0, 1),
            "lowered": LoweredSpec(
                ports=False, aa_weights=(3,), aa_zones=(16,)
            ),
            "none_field": None,
            "flag": True,
        }
        header, arrays = _encode(msg)
        body = b"".join(a.tobytes() for a in arrays)
        out = _decode(header, body)
        assert out["op"] == "solve" and out["flag"] is True
        assert out["none_field"] is None
        assert out["weights"] == (2, 0, 1)
        assert isinstance(out["lowered"], LoweredSpec)
        assert out["lowered"].aa_weights == (3,)
        assert not out["lowered"].ports
        np.testing.assert_array_equal(out["pods"]["cpu"], msg["pods"]["cpu"])
        np.testing.assert_array_equal(out["pods"]["bits"], msg["pods"]["bits"])
        assert out["pods"]["empty"].shape == (0, 3)

    def test_version_skew_fails_clean(self, tmp_path):
        import socket
        import struct
        import threading

        from kubernetes_tpu.ops.sidecar import (
            SidecarError,
            _MAGIC,
            _recv_msg,
        )

        a, b = socket.socketpair()
        try:
            # A peer speaking a future v9: header says so, receiver
            # must raise a version-skew SidecarError, not garbage.
            hdr = b'{"meta":{},"arrays":[]}'
            frame = _MAGIC + struct.pack(">HQI", 9, len(hdr), len(hdr)) + hdr
            threading.Thread(target=a.sendall, args=(frame,), daemon=True).start()
            with pytest.raises(SidecarError, match="version skew"):
                _recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_garbage_magic_fails_clean(self):
        import socket
        import threading

        from kubernetes_tpu.ops.sidecar import SidecarError, _recv_msg

        a, b = socket.socketpair()
        try:
            threading.Thread(
                target=a.sendall, args=(b"\x00" * 64,), daemon=True
            ).start()
            with pytest.raises(SidecarError, match="magic"):
                _recv_msg(b)
        finally:
            a.close()
            b.close()
