"""Solver sidecar: process isolation + the crash-fallback story.

Reference framing: SURVEY §2.15/§5 — the north star's control plane
and accelerator live in separate processes; a solver failure degrades
to the stock scalar path (VERDICT r1 A8 flagged this as untested)."""

import os
import time

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.ops.sidecar import SidecarError, SidecarSolver, spawn_sidecar
from kubernetes_tpu.scheduler.batch import parity_report, schedule_backlog_tpu
from kubernetes_tpu.scheduler.daemon import BatchScheduler, SchedulerConfig
from kubernetes_tpu.server.api import APIServer
from test_solver_parity import random_cluster


def _stop_proc(proc):
    """terminate, then kill: SIGTERM can't interrupt a native XLA
    compile, and a hung wait() here flakes the whole module."""
    if proc.poll() is not None:
        return
    proc.terminate()
    try:
        proc.wait(timeout=10)
    except Exception:
        proc.kill()
        proc.wait(timeout=10)


@pytest.fixture(scope="module")
def sidecar():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"  # the subprocess owns its own backend
    proc, sock_path = spawn_sidecar(env=env, wait=120)
    yield sock_path
    _stop_proc(proc)


class TestSidecarSolve:
    def test_matches_in_process_solver(self, sidecar):
        pods, nodes, assigned, services = random_cluster(4)
        local = schedule_backlog_tpu(pods, nodes, assigned, services)
        remote = SidecarSolver(sidecar).solve(pods, nodes, assigned, services)
        parity, mismatches = parity_report(local, remote)
        assert parity == 1.0, mismatches

    def test_ping(self, sidecar):
        assert SidecarSolver(sidecar).ping()

    def test_wave_mode_travels_to_sidecar(self, sidecar):
        """mode='wave' must run the wave solver inside the sidecar —
        valid placements for the whole backlog."""
        pods, nodes, assigned, services = random_cluster(2)
        remote = SidecarSolver(sidecar).solve(
            pods, nodes, assigned, services, mode="wave"
        )
        assert len(remote) == len(pods)
        names = {n.metadata.name for n in nodes}
        assert all(dest is None or dest in names for dest in remote)

    def test_garbage_frame_does_not_kill_sidecar(self, sidecar):
        """Per-connection containment: a junk frame must not exit the
        serve loop."""
        import socket as socketlib
        import struct

        s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
        s.connect(sidecar)
        s.sendall(struct.pack(">Q", 7) + b"garbage")
        s.close()
        assert SidecarSolver(sidecar).ping()  # still alive

    def test_dead_socket_raises_sidecar_error(self):
        pods, nodes, assigned, services = random_cluster(1)
        dead = SidecarSolver("/nonexistent/solver.sock", timeout=2)
        assert not dead.ping()
        with pytest.raises(SidecarError):
            dead.solve(pods, nodes, assigned, services)


def node_wire(name):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": "4", "memory": "8Gi", "pods": "40"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "x",
                    "resources": {"limits": {"cpu": "100m", "memory": "64Mi"}},
                }
            ]
        },
    }


class TestCrashFallback:
    def test_scheduler_survives_dead_sidecar_via_scalar_fallback(self):
        """Sidecar gone -> the batch scheduler's fallback seam runs the
        scalar oracle and the backlog still schedules."""
        api = APIServer()
        client = Client(LocalTransport(api))
        for j in range(3):
            client.create("nodes", node_wire(f"n{j}"))
        for i in range(9):
            client.create("pods", pod_wire(f"p{i}"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = BatchScheduler(
            cfg, sidecar_path="/nonexistent/solver.sock"
        )
        sched.sidecar.timeout = 2  # fail fast in the test
        try:
            processed = 0
            deadline = time.monotonic() + 60
            while processed < 9 and time.monotonic() < deadline:
                processed += sched.schedule_batch(timeout=0.5)
            pods, _ = client.list("pods", namespace="default")
            assert all(p.spec.node_name for p in pods)
            assert sched.fallback_count > 0  # the fallback actually ran
        finally:
            cfg.stop()

    def test_live_sidecar_then_killed_mid_run(self, tmp_path):
        """Scheduler uses a live sidecar, the sidecar dies, scheduling
        continues through the fallback."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc, sock_path = spawn_sidecar(env=env, wait=120)
        try:
            api = APIServer()
            client = Client(LocalTransport(api))
            for j in range(3):
                client.create("nodes", node_wire(f"n{j}"))
            cfg = SchedulerConfig(Client(LocalTransport(api))).start()
            assert cfg.wait_for_sync()
            sched = BatchScheduler(cfg, sidecar_path=sock_path)
            try:
                client.create("pods", pod_wire("before"))
                deadline = time.monotonic() + 60
                done = 0
                while done < 1 and time.monotonic() < deadline:
                    done += sched.schedule_batch(timeout=0.5)
                assert client.get(
                    "pods", "before", namespace="default"
                ).spec.node_name
                assert sched.fallback_count == 0  # sidecar did the work

                _stop_proc(proc)
                sched.sidecar.timeout = 2
                client.create("pods", pod_wire("after"))
                done = 0
                deadline = time.monotonic() + 60
                while done < 1 and time.monotonic() < deadline:
                    done += sched.schedule_batch(timeout=0.5)
                assert client.get(
                    "pods", "after", namespace="default"
                ).spec.node_name
                assert sched.fallback_count > 0
            finally:
                cfg.stop()
        finally:
            _stop_proc(proc)
