"""Device-time profiling plane (ops/ledger.py, utils/profiler.py).

Covers: the traced-jit compile ledger (detection via the PR-7
``_cache_size()`` sentinel, background cost/memory harvest, bucket
growth without double-counting cached compiles), ledger completeness
against the KT006 ``ORACLE_TWINS`` registry (the acceptance gate:
every registered jitted kernel that ran has a ledger row with compile
time + cost analysis), duty-cycle/overlap series from a live
micro-tick daemon, the ``ktctl profile`` miss/populated exit contract,
the HTTP surfaces (``/debug/kernels``, ``/debug/profile?format=
collapsed``, ``/debug/device-profile``), and the overhead guard
pinning ledger + duty-cycle accounting at <5% of the bulk-churn drill
(the PR-9 always-on budget)."""

import io
import json
import os
import re
import threading
import time
from contextlib import redirect_stderr, redirect_stdout

import numpy as np
import pytest

from kubernetes_tpu.ops import ledger
from kubernetes_tpu.utils import profiler

pytestmark = pytest.mark.profiler


def node_wire(name, cpu="8"):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": cpu, "memory": "16Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def pod_wire(name, cpu="50m"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c", "image": "pause",
                    "resources": {"limits": {"cpu": cpu, "memory": "32Mi"}},
                }
            ]
        },
    }


class TestTracedJit:
    def test_compile_recorded_with_cost_then_calls_only(self):
        """First call at a shape = one compile event (wall time + the
        harvested Compiled.cost_analysis()/memory_analysis()); repeat
        calls increment the call counter, never the compile count."""
        import jax.numpy as jnp

        led = ledger.CompileLedger()

        @ledger.traced_jit
        def _profiler_probe_kernel(x):
            return (x * 2.0).sum()

        # Point the wrapper's bookkeeping at a private ledger so this
        # test owns its rows end to end. Kernel names derive from
        # module + qualname ('<locals>' stripped) — the ORACLE_TWINS
        # key format.
        key = _profiler_probe_kernel.kernel
        assert key.startswith("test_profiler.")
        assert key.endswith("._profiler_probe_kernel")
        assert "<locals>" not in key
        real_default, ledger.DEFAULT = ledger.DEFAULT, led
        try:
            x = jnp.ones((257,), jnp.float32)
            _profiler_probe_kernel(x)
            _profiler_probe_kernel(x)
            _profiler_probe_kernel(x)
            assert led.wait_pending(60), "cost harvest never drained"
        finally:
            ledger.DEFAULT = real_default
        (row,) = led.rows()
        assert row["kernel"] == key
        assert row["compiles"] == 1 and row["calls"] == 3
        assert row["compile_seconds"] > 0
        (shape,) = row["shapes"]
        assert shape["cost_status"] == "ok"
        assert shape["flops"] > 0 and shape["bytes_accessed"] > 0
        assert shape["argument_bytes"] >= 257 * 4
        assert "f32[257]" in shape["signature"]
        # The metric counter carries the same event.
        assert ledger.COMPILE_SECONDS.value(kernel=key) > 0

    def test_bucket_growth_without_double_counting(self):
        """The PR-7 recompilation sentinel, ledger edition: randomized
        backlog sizes funnel into pow2 buckets, and the ledger records
        exactly as many NEW compile events as the jit cache grew by —
        a cached bucket re-solve must never mint a ledger row."""
        import random

        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops import device_snapshot, solve_assignments
        from kubernetes_tpu.ops.solver import _solve_xla
        from test_solver_parity import mk_node, mk_pod

        def scan_row():
            for r in ledger.DEFAULT.rows():
                if r["kernel"] == "solver._solve_xla":
                    return r
            return {"compiles": 0, "calls": 0, "shapes": []}

        cache_before = int(_solve_xla._cache_size())
        row_before = scan_row()
        rng = random.Random(0xBEEF)
        runs = 8
        for _ in range(runs):
            P = rng.randint(1, 500)
            pods = [mk_pod(f"p{i}", cpu=100) for i in range(P)]
            nodes = [mk_node(f"n{j}") for j in range(4)]
            d = device_snapshot(build_snapshot(pods, nodes))
            assert len(solve_assignments(d)) == P
        row_after = scan_row()
        cache_grew = int(_solve_xla._cache_size()) - cache_before
        new_compiles = row_after["compiles"] - row_before["compiles"]
        assert new_compiles == cache_grew, (
            f"ledger recorded {new_compiles} compiles but the jit "
            f"cache grew by {cache_grew} — double-counted cached "
            "buckets"
        )
        # Every run was a call; only cache growth compiled.
        assert row_after["calls"] - row_before["calls"] == runs
        assert new_compiles < runs, "pow2 bucketing regressed"

    def test_wrapper_forwards_pjit_surface(self):
        """Adopting traced_jit must not rot the sentinel surface the
        PR-7/PR-9 consumers read: _cache_size/lower/clear_cache
        forward to the wrapped pjit function, and nested kernels key
        exactly like the ORACLE_TWINS registry."""
        from kubernetes_tpu.ops.preemption import _victim_prefix_kernel
        from kubernetes_tpu.ops.solver import _solve_xla

        assert isinstance(_solve_xla, ledger.TracedJit)
        assert isinstance(_solve_xla._cache_size(), int)
        assert callable(_solve_xla.lower)
        assert _solve_xla.kernel == "solver._solve_xla"
        kernel = _victim_prefix_kernel()
        assert kernel.kernel == "preemption._victim_prefix_kernel.kernel"


class TestLedgerCompleteness:
    """The acceptance gate: cross-check the compile ledger against the
    KT006 ORACLE_TWINS registry on the live tree."""

    def test_every_registered_kernel_that_ran_has_a_ledger_row(self):
        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops import device_snapshot
        from kubernetes_tpu.ops.incremental import SolverSession
        from kubernetes_tpu.ops.pallas_scan import solve_with_state_pallas
        from kubernetes_tpu.ops.parity import ORACLE_TWINS
        from kubernetes_tpu.ops.pipeline import (
            explain_backlog,
            gang_member_counts_device,
        )
        from kubernetes_tpu.ops.preemption import candidate_prefixes_device
        from kubernetes_tpu.ops.sinkhorn import (
            solve_sinkhorn,
            solve_sinkhorn_with_state,
        )
        from kubernetes_tpu.ops.solver import (
            DEFAULT_WEIGHTS,
            solve_assignments,
            solve_with_state,
        )
        from kubernetes_tpu.ops.wave import (
            solve_waves_with_state,
            wave_assignments,
        )
        from test_solver_parity import mk_node, mk_pod

        pods = [mk_pod(f"p{i}", cpu=100) for i in range(4)]
        nodes = [mk_node(f"n{j}") for j in range(2)]

        def dsnap():
            return device_snapshot(build_snapshot(pods, nodes))

        # One minimal exercise per registered kernel family. Whether
        # each call compiles HERE or hit a cache warmed earlier in the
        # test session is irrelevant: the ledger is process-global and
        # always-on, so the compile event was recorded wherever it
        # happened.
        d = dsnap()
        solve_assignments(d)                                # _solve_xla
        d = dsnap()
        solve_with_state(d.pods, d.nodes)                   # _solve_with_state_xla
        explain_backlog(pods, nodes)                        # explain_rows
        wave_assignments(dsnap())                           # solve_waves
        d = dsnap()
        solve_waves_with_state(d.pods, d.nodes)             # solve_waves_with_state
        d = dsnap()
        solve_sinkhorn(d.pods, d.nodes)                     # solve_sinkhorn_stats
        d = dsnap()
        solve_sinkhorn_with_state(d.pods, d.nodes)          # solve_sinkhorn_with_state
        gang_member_counts_device(                          # gang_member_counts
            np.array([True, False]), np.array([0, 0], np.int32), 1
        )
        sess = SolverSession(nodes)                         # _scatter_rows
        sess.upsert_node(nodes[0])
        sess._flush_dirty()
        candidate_prefixes_device(                          # preemption kernel
            np.array([100.0]), np.array([64.0]),
            np.array([0], np.int64), np.array([0], np.int32),
            np.array([True]),
            np.array([0.0]), np.array([0.0]), np.array([1.0]),
            np.array([True]),
            100.0, 64.0, 10,
        )
        d = dsnap()
        solve_with_state_pallas(                            # _solve_packed
            d.pods, d.nodes, DEFAULT_WEIGHTS, interpret=True
        )
        from kubernetes_tpu.utils.capacity import (
            DEFAULT as capacity_monitor,
            cluster_columns,
        )

        cols, names = cluster_columns(nodes, [])
        assert capacity_monitor.sample(cols, names)         # capacity_report
        from kubernetes_tpu.utils.capacity import DEFAULT_SLICE_SHAPES
        from kubernetes_tpu.utils.rebalance import fragment_score

        # fragment_score IS plan_moves at zero budget (rebalance.plan_moves)
        assert fragment_score(cols, DEFAULT_SLICE_SHAPES) is not None

        assert ledger.DEFAULT.wait_pending(180), (
            "cost harvest never drained"
        )
        have = set(ledger.DEFAULT.kernels())
        missing = sorted(set(ORACLE_TWINS) - have)
        assert not missing, (
            f"registered kernels ran but have no ledger row: {missing}"
        )
        # Every row carries compile wall time AND a harvested
        # cost/memory analysis for at least one shape.
        for row in ledger.DEFAULT.rows():
            if row["kernel"] not in ORACLE_TWINS:
                continue
            assert row["compiles"] >= 1, row["kernel"]
            assert row["compile_seconds"] > 0, row["kernel"]
            ok = [
                s for s in row["shapes"] if s.get("cost_status") == "ok"
            ]
            assert ok, (
                f"{row['kernel']}: no shape with harvested cost "
                f"analysis ({[s.get('cost_status') for s in row['shapes']]})"
            )
            assert any(
                s.get("flops", 0) >= 0
                and "argument_bytes" in s
                and "temp_bytes" in s
                for s in ok
            ), row["kernel"]
            # Declared-vs-observed join (ops/contracts.py): the real
            # staged shapes this workload dispatched sit ON the
            # declared bucket lattice — every kernel shows an "ok"
            # CONTRACT verdict. (At least one per kernel, not all
            # rows: the ledger is process-global and test_ktshape
            # dispatches a DELIBERATELY off-lattice shape.)
            assert any(
                s.get("contract") == "ok" for s in row["shapes"]
            ), (
                f"{row['kernel']}: no staged shape joins its "
                f"contract: "
                f"{[(s['signature'], s.get('contract')) for s in row['shapes']]}"
            )


class TestDutyCycle:
    def test_live_microtick_daemon_populates_series(self):
        """A started micro-tick daemon binding real pods observes one
        duty-cycle + overlap sample per resolved tick, with ratio
        values inside [0, 1] and busy-seconds accumulating."""
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.scheduler.daemon import (
            IncrementalBatchScheduler,
            SchedulerConfig,
        )
        from kubernetes_tpu.server.api import APIServer

        duty0 = profiler.DUTY_CYCLE.count()
        over0 = profiler.OVERLAP.count()
        busy0 = profiler.DEVICE_BUSY.value()
        api = APIServer()
        client = Client(LocalTransport(api))
        client.create("nodes", node_wire("n0"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync()
        sched = IncrementalBatchScheduler(cfg, prewarm_buckets=128)
        sched.prewarm()
        sched.start()
        try:
            n = 5
            for i in range(n):
                client.create("pods", pod_wire(f"duty-{i}"))
                time.sleep(0.1)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                pods, _ = client.list("pods", namespace="default")
                if sum(1 for p in pods if p.spec.node_name) == n:
                    break
                time.sleep(0.05)
            assert sum(1 for p in pods if p.spec.node_name) == n
        finally:
            sched.stop()
            cfg.stop()
        assert profiler.DUTY_CYCLE.count() - duty0 >= 1
        assert profiler.OVERLAP.count() - over0 >= 1
        assert profiler.DEVICE_BUSY.value() > busy0
        # Ratio ladders: every observation landed in a finite bucket
        # (values are clamped to [0, 1] <= the top bound).
        for h in (profiler.DUTY_CYCLE, profiler.OVERLAP):
            assert h.quantile(0.99) <= 1.0

    def test_observe_tick_clamps(self):
        base = profiler.DUTY_CYCLE.count()
        # Clock jitter making device > wall or blocked > device must
        # clamp into [0, 1], and degenerate ticks observe nothing.
        profiler.observe_tick(2.0, 1.0, 5.0)
        profiler.observe_tick(0.0, 1.0, 0.0)
        profiler.observe_tick(1.0, 0.0, 0.0)
        assert profiler.DUTY_CYCLE.count() == base + 1
        assert profiler.DUTY_CYCLE.quantile(1.0) <= 1.0


class TestKtctlProfile:
    def test_kernels_miss_contract_on_cold_process(self, monkeypatch, capsys):
        """`ktctl profile kernels` on a process with no compiles: exit
        1, empty stdout, 'no compiles recorded' on stderr — the
        trace/explain/slo miss contract."""
        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        monkeypatch.setattr(ledger, "DEFAULT", ledger.CompileLedger())
        client = Client(LocalTransport(APIServer()))
        rc = ktctl.main(["profile", "kernels"], client=client)
        captured = capsys.readouterr()
        assert rc == 1
        assert captured.out == ""
        assert "no compiles recorded" in captured.err

    def test_kernels_populated_renders_table(self, monkeypatch, capsys):
        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        led = ledger.CompileLedger()
        led.record_compile("solver._solve_xla", "f32[128]", 1.25)
        led.attach_cost(
            "solver._solve_xla", "f32[128]",
            {"flops": 2.0e9, "bytes_accessed": 1.0e6,
             "arithmetic_intensity": 2000.0},
            {"temp_bytes": 10, "argument_bytes": 20, "output_bytes": 5,
             "generated_code_bytes": 0},
        )
        monkeypatch.setattr(ledger, "DEFAULT", led)
        client = Client(LocalTransport(APIServer()))
        rc = ktctl.main(["profile", "kernels"], client=client)
        out = capsys.readouterr().out
        assert rc == 0
        assert "solver._solve_xla" in out
        assert "KERNEL" in out and "COMPILE_S" in out
        assert "2.00G" in out  # flops, engineering-formatted
        # JSON output round-trips the full ledger dump.
        rc = ktctl.main(["profile", "kernels", "-o", "json"], client=client)
        parsed = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert parsed["summary"]["compiles"] == 1

    def test_cpu_profile_local_formats(self, capsys):
        """`ktctl profile cpu` over an injected LocalTransport renders
        the sampling profiler; --format collapsed emits folded
        stacks."""
        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i * i for i in range(400))

        t = threading.Thread(target=busy, daemon=True)
        t.start()
        try:
            client = Client(LocalTransport(APIServer()))
            rc = ktctl.main(
                ["profile", "cpu", "--seconds", "0.3"], client=client
            )
            out = capsys.readouterr().out
            assert rc == 0 and "sampling profile:" in out
            rc = ktctl.main(
                ["profile", "cpu", "--seconds", "0.3",
                 "--format", "collapsed"],
                client=client,
            )
            out = capsys.readouterr().out
            assert rc == 0
            folded = [ln for ln in out.splitlines() if ln.strip()]
            assert folded, "collapsed profile produced no stacks"
            assert all(
                re.match(r"^.+ \d+$", ln) for ln in folded
            ), folded[:3]
            assert any(";" in ln for ln in folded)
        finally:
            stop.set()
            t.join(timeout=5)


class TestHTTPSurfaces:
    def _server(self):
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        return APIHTTPServer(api).start()

    def test_debug_kernels_and_collapsed_profile(self):
        import urllib.request

        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, HTTPTransport

        # Guarantee at least one ledger row in this process.
        import jax.numpy as jnp

        @ledger.traced_jit
        def _http_probe_kernel(x):
            return x + 1

        _http_probe_kernel(jnp.ones((33,)))
        srv = self._server()
        try:
            client = Client(HTTPTransport(srv.address))
            data = client.t.get_json("/debug/kernels")
            names = {r["kernel"] for r in data["kernels"]}
            assert _http_probe_kernel.kernel in names
            assert data["summary"]["compiles"] >= 1
            # ktctl profile kernels over HTTP sees the same ledger.
            out = io.StringIO()
            with redirect_stdout(out):
                rc = ktctl.main(
                    ["profile", "kernels"], client=client
                )
            assert rc == 0
            assert "_http_probe_kernel" in out.getvalue()
            # Folded stacks over HTTP.
            with urllib.request.urlopen(
                srv.address + "/debug/profile?seconds=0.3&format=collapsed",
                timeout=30,
            ) as resp:
                body = resp.read().decode()
            lines = [ln for ln in body.splitlines() if ln.strip()]
            assert lines and all(
                re.match(r"^.+ \d+$", ln) for ln in lines
            )
            # Unknown format: 400, not a silent default.
            try:
                urllib.request.urlopen(
                    srv.address + "/debug/profile?seconds=0.1&format=bogus",
                    timeout=10,
                )
                assert False, "expected HTTP 400"
            except urllib.error.HTTPError as e:
                assert e.code == 400
        finally:
            srv.stop()

    def test_device_profile_capture(self):
        from kubernetes_tpu.client import Client, HTTPTransport

        srv = self._server()
        try:
            client = Client(HTTPTransport(srv.address))
            info = client.t.get_json(
                "/debug/device-profile", query={"seconds": "0.2"}
            )
            assert os.path.isdir(info["dir"])
            assert info["files"], "device trace produced no files"
            assert info["seconds"] == 0.2
        finally:
            srv.stop()

    def test_device_capture_is_exclusive(self):
        """Two concurrent captures: the second gets TraceInProgress
        (the profiler backend cannot nest sessions)."""
        results = {}

        def first():
            results["first"] = profiler.capture_device_trace(seconds=1.0)

        t = threading.Thread(target=first, daemon=True)
        t.start()
        time.sleep(0.3)
        with pytest.raises(profiler.TraceInProgress):
            profiler.capture_device_trace(seconds=0.2)
        t.join(timeout=30)
        assert "first" in results


class TestCollapsedFormatUnit:
    def test_both_formats_from_one_sampler(self):
        """Regression for the two renderings: 'top' keeps the
        historical human format, 'collapsed' emits root-first folded
        stacks flamegraph.pl/speedscope accept."""
        from kubernetes_tpu.utils import debug

        stop = threading.Event()

        def busy():
            while not stop.is_set():
                sum(i for i in range(200))

        t = threading.Thread(target=busy, daemon=True)
        t.start()
        try:
            top = debug.sample_profile(seconds=0.3, fmt="top")
            folded = debug.sample_profile(seconds=0.3, fmt="collapsed")
        finally:
            stop.set()
            t.join(timeout=5)
        assert top.startswith("sampling profile:")
        assert "samples over" in top
        lines = [ln for ln in folded.splitlines() if ln.strip()]
        assert lines
        for ln in lines:
            frames, _, count = ln.rpartition(" ")
            assert count.isdigit() and frames
        # The busy thread's stack folds root-first: the thread
        # bootstrap frame leads, the hot frame trails.
        busy_lines = [ln for ln in lines if "busy" in ln]
        assert busy_lines, "sampler never caught the busy thread"
        assert busy_lines[0].index("_bootstrap") < busy_lines[0].index(
            "busy"
        )


class TestOverheadGuard:
    """Always-on observability must be affordable: the ledger + duty
    accounting added per tick is pinned at <5% of the bulk-churn
    drill's wall (the PR-9 SLI guard's shape)."""

    def test_profiling_plane_under_5pct_of_bulk_churn(self):
        from kubernetes_tpu.client import Client, HTTPTransport
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        n_pods, batch = 2000, 500
        api = APIServer()
        api.list("pods", "default")
        srv = APIHTTPServer(api, max_in_flight=800).start()
        try:
            client = Client(HTTPTransport(srv.address))
            stream = Client(HTTPTransport(srv.address)).watch(
                "pods", namespace="default"
            )
            seen = {"n": 0}

            def consume():
                while seen["n"] < 2 * n_pods:
                    ev = stream.next(timeout=10.0)
                    if ev is None:
                        if stream.closed:
                            return
                        continue
                    seen["n"] += 1

            watcher = threading.Thread(target=consume, daemon=True)
            t0 = time.perf_counter()
            watcher.start()
            for s in range(0, n_pods, batch):
                items = [
                    pod_wire(f"prof-{i}") for i in range(s, s + batch)
                ]
                res = client.create_bulk("pods", items, namespace="default")
                assert all(r.get("status") == "Success" for r in res)
            for s in range(0, n_pods, batch):
                client.delete_bulk(
                    "pods",
                    [f"prof-{i}" for i in range(s, s + batch)],
                    namespace="default",
                )
            watcher.join(timeout=30)
            drill_wall = time.perf_counter() - t0
            stream.close()
            assert seen["n"] >= 2 * n_pods, seen
        finally:
            srv.stop()

        # Standalone cost of the profiling plane at a density far
        # beyond reality: one traced-jit call bookkeeping per pod
        # EVENT (a real tick batches hundreds of pods into ~4 kernel
        # dispatches), one duty/overlap observation per batch, plus a
        # full ledger render per batch (the /debug/kernels scrape).
        # Best of three repeats: a GC pause inside one repeat must not
        # fail the guard.
        led = ledger.CompileLedger()
        led.record_compile("solver._solve_with_state_xla", "f32[128]", 1.0)
        cost = float("inf")
        for _repeat in range(3):
            t0 = time.perf_counter()
            for _ in range(2 * n_pods):
                led.note_call("solver._solve_with_state_xla")
            for _ in range(2 * n_pods // batch):
                profiler.observe_tick(0.002, 0.01, 0.001)
                led.summary()
            cost = min(cost, time.perf_counter() - t0)
        assert cost < 0.05 * drill_wall, (
            f"profiling plane cost {cost:.4f}s is >=5% of the "
            f"{drill_wall:.4f}s bulk-churn drill"
        )
