"""Label/field selector parity (reference: pkg/labels/, pkg/fields/)."""

import pytest

from kubernetes_tpu.models import labels


def test_selector_from_set():
    sel = labels.selector_from_set({"a": "b", "c": "d"})
    assert sel.matches({"a": "b", "c": "d", "extra": "x"})
    assert not sel.matches({"a": "b"})
    assert not sel.matches({})
    assert labels.selector_from_set({}).matches({"anything": "goes"})


@pytest.mark.parametrize(
    "expr,labels_map,want",
    [
        ("x=a", {"x": "a"}, True),
        ("x=a", {"x": "b"}, False),
        ("x==a", {"x": "a"}, True),
        ("x!=a", {"x": "b"}, True),
        ("x!=a", {"x": "a"}, False),
        ("x!=a", {}, True),
        ("x in (a,b)", {"x": "b"}, True),
        ("x in (a,b)", {"x": "c"}, False),
        ("x in (a,b)", {}, False),
        ("x notin (a,b)", {"x": "c"}, True),
        ("x notin (a,b)", {"x": "a"}, False),
        ("x notin (a,b)", {}, True),
        ("x", {"x": "anything"}, True),
        ("x", {}, False),
        ("x=a,y=b", {"x": "a", "y": "b"}, True),
        ("x=a,y=b", {"x": "a"}, False),
        ("x in (a,b),y!=c", {"x": "a", "y": "d"}, True),
        ("", {"x": "a"}, True),
    ],
)
def test_parse_and_match(expr, labels_map, want):
    assert labels.parse(expr).matches(labels_map) is want


def test_parse_invalid():
    with pytest.raises(ValueError):
        labels.parse("x==,=")


def test_field_selector():
    fs = labels.parse_fields("spec.nodeName=,status.phase!=Failed")
    assert fs.matches({"spec.nodeName": "", "status.phase": "Running"})
    assert not fs.matches({"spec.nodeName": "n1", "status.phase": "Running"})
    assert not fs.matches({"spec.nodeName": "", "status.phase": "Failed"})
    assert labels.parse_fields("").matches({"a": "b"})
