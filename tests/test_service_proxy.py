"""Services proxy subresource through the apiserver.

Reference: pkg/registry/service/rest.go ResourceLocation (random ready
endpoint, ':port' selects by endpoint port name) + pkg/apiserver/
proxy.go relays. Completes the proxy/redirect trio (pods, nodes,
services) — the URLs `ktctl cluster-info` prints are exactly these.
"""

import http.server
import json
import threading
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.server import APIError, APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


@pytest.fixture
def backend():
    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = json.dumps({"path": self.path, "who": "backend"}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield srv.server_address
    srv.shutdown()
    srv.server_close()


@pytest.fixture
def cluster(backend):
    api = APIServer()
    srv = APIHTTPServer(api).start()
    ip, port = backend
    api.create(
        "services",
        "default",
        {
            "kind": "Service",
            "metadata": {"name": "web", "namespace": "default"},
            "spec": {"selector": {"app": "web"}, "ports": [{"name": "http", "port": 80}]},
        },
    )
    api.create(
        "endpoints",
        "default",
        {
            "kind": "Endpoints",
            "metadata": {"name": "web", "namespace": "default"},
            "subsets": [
                {
                    "addresses": [{"ip": ip}],
                    "ports": [{"name": "http", "port": port}],
                }
            ],
        },
    )
    yield api, srv, port
    srv.stop()


class TestServiceProxy:
    def test_relays_to_endpoint(self, cluster):
        api, srv, port = cluster
        url = f"{srv.address}/api/v1/namespaces/default/services/web/proxy/some/path"
        with urllib.request.urlopen(url, timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["who"] == "backend"
        assert body["path"] == "/some/path"

    def test_named_port_selector(self, cluster):
        api, srv, port = cluster
        url = f"{srv.address}/api/v1/namespaces/default/services/web:http/proxy/"
        with urllib.request.urlopen(url, timeout=5) as resp:
            assert json.loads(resp.read())["who"] == "backend"
        # Unknown port name -> no candidates -> 503.
        bad = f"{srv.address}/api/v1/namespaces/default/services/web:nope/proxy/"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(bad, timeout=5)
        assert e.value.code == 503

    def test_no_endpoints_503(self, cluster):
        api, srv, port = cluster
        api.create(
            "services",
            "default",
            {
                "kind": "Service",
                "metadata": {"name": "lonely", "namespace": "default"},
                "spec": {"selector": {"app": "x"}, "ports": [{"port": 80}]},
            },
        )
        url = f"{srv.address}/api/v1/namespaces/default/services/lonely/proxy/"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url, timeout=5)
        assert e.value.code == 503

    def test_location_is_random_across_endpoints(self):
        api = APIServer()
        api.create(
            "services",
            "default",
            {
                "kind": "Service",
                "metadata": {"name": "multi", "namespace": "default"},
                "spec": {"selector": {"app": "m"}, "ports": [{"port": 80}]},
            },
        )
        api.create(
            "endpoints",
            "default",
            {
                "kind": "Endpoints",
                "metadata": {"name": "multi", "namespace": "default"},
                "subsets": [
                    {
                        "addresses": [{"ip": "10.5.0.1"}, {"ip": "10.5.0.2"}],
                        "ports": [{"port": 9000}],
                    }
                ],
            },
        )
        picks = {api.service_location("default", "multi")[0] for _ in range(50)}
        assert picks == {"10.5.0.1", "10.5.0.2"}


class TestRedirect:
    """Legacy REDIRECT verb (pkg/apiserver/redirect.go): 307 with the
    backend Location instead of relaying."""

    def _get_redirect(self, url):
        import urllib.request

        class NoFollow(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **k):
                return None

        opener = urllib.request.build_opener(NoFollow)
        try:
            opener.open(url, timeout=5)
        except urllib.error.HTTPError as e:
            return e.code, e.headers.get("Location", "")
        raise AssertionError("expected a redirect status")

    def test_service_redirect(self, cluster):
        api, srv, port = cluster
        code, loc = self._get_redirect(
            f"{srv.address}/api/v1/redirect/namespaces/default/services/web"
        )
        assert code == 307
        assert loc == f"http://127.0.0.1:{port}/"

    def test_pod_redirect_uses_pod_ip_and_port(self, cluster):
        api, srv, port = cluster
        api.create(
            "pods",
            "default",
            {
                "kind": "Pod",
                "metadata": {"name": "rp"},
                "spec": {
                    "containers": [
                        {"name": "c", "image": "x",
                         "ports": [{"containerPort": 8080}]}
                    ]
                },
            },
        )
        api.update_status(
            "pods", "default", "rp",
            {"status": {"podIP": "10.9.8.7", "phase": "Running"}},
        )
        code, loc = self._get_redirect(
            f"{srv.address}/api/v1/redirect/namespaces/default/pods/rp"
        )
        assert code == 307
        assert loc == "http://10.9.8.7:8080/"

    def test_non_redirector_405(self, cluster):
        import urllib.error
        import urllib.request

        api, srv, port = cluster
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"{srv.address}/api/v1/redirect/namespaces/default/"
                "secrets/whatever",
                timeout=5,
            )
        assert e.value.code == 405
