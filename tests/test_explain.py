"""Scheduling flight recorder end to end: ring mechanics, the explain
readback's bounded verdict shape, both batch daemons feeding decisions
(joined with trace ids), the /debug/decisions + /debug/solves HTTP
surfaces, `ktctl explain`, and the solver convergence telemetry."""

import json
import time
import urllib.request

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.scheduler.daemon import (
    BatchScheduler,
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.utils import flightrecorder, tracing

pytestmark = pytest.mark.explain

SCHED_TIMEOUT = 60.0


@pytest.fixture(autouse=True)
def _clean_recorder():
    flightrecorder.configure(
        ring=4096, solve_ring=512, explain_top_k=3,
        explain_failed_nodes=16, explain_limit=64,
    )
    flightrecorder.DEFAULT.clear()
    tracing.configure(sample_rate=1.0, log_threshold_s=0.0)
    tracing.DEFAULT_BUFFER.clear()
    yield
    flightrecorder.configure(
        ring=4096, solve_ring=512, explain_top_k=3,
        explain_failed_nodes=16, explain_limit=64,
    )
    flightrecorder.DEFAULT.clear()
    tracing.DEFAULT_BUFFER.clear()


def pod_wire(name, selector=None, cpu="100m"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "nodeSelector": selector or {},
            "containers": [
                {"name": "c", "image": "nginx",
                 "resources": {"limits": {"cpu": cpu, "memory": "64Mi"}}}
            ],
        },
    }


def node_wire(name):
    return {
        "kind": "Node",
        "metadata": {"name": name},
        "status": {
            "capacity": {"cpu": "4", "memory": "8Gi", "pods": "110"},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


class TestFlightRecorderMechanics:
    def test_ring_is_bounded_newest_win(self):
        flightrecorder.configure(ring=8)
        flightrecorder.DEFAULT.record(
            flightrecorder.Decision(
                pod=f"default/p{i}", tick=1, trace_id="t", mode="scan",
                outcome="bound", node="n0",
            )
            for i in range(20)
        )
        size, cap = flightrecorder.DEFAULT.ring_stats()
        assert size == cap == 8
        got = flightrecorder.DEFAULT.decisions(limit=100)["decisions"]
        # Newest first, oldest 12 evicted.
        assert [d["pod"] for d in got] == [
            f"default/p{i}" for i in range(19, 11, -1)
        ]

    def test_limit_zero_returns_nothing(self):
        flightrecorder.DEFAULT.record(
            [
                flightrecorder.Decision(
                    pod="default/p0", tick=1, trace_id="", mode="scan",
                    outcome="bound", node="n0",
                )
            ]
        )
        flightrecorder.DEFAULT.record_solve(
            flightrecorder.SolveRecord(
                tick=1, trace_id="", mode="scan", pods=1, duration_s=0.1,
            )
        )
        assert flightrecorder.DEFAULT.decisions(limit=0)["decisions"] == []
        assert flightrecorder.DEFAULT.decisions(limit=-3)["decisions"] == []
        assert flightrecorder.DEFAULT.solves(limit=0)["solves"] == []

    def test_last_solve_telemetry_is_consume_once(self):
        flightrecorder.observe_solve_telemetry(
            "sinkhorn", 24, residual=0.5, waves=3
        )
        tele = flightrecorder.take_last_solve_telemetry()
        assert tele == {
            "mode": "sinkhorn", "iterations": 24, "waves": 3,
            "residual": 0.5,
        }
        assert flightrecorder.take_last_solve_telemetry() is None

    def test_pod_filter_matches_key_and_bare_name(self):
        flightrecorder.DEFAULT.record(
            [
                flightrecorder.Decision(
                    pod="ns1/web", tick=1, trace_id="", mode="scan",
                    outcome="bound", node="n0",
                ),
                flightrecorder.Decision(
                    pod="ns2/web", tick=1, trace_id="", mode="scan",
                    outcome="unschedulable",
                ),
            ]
        )
        by_key = flightrecorder.DEFAULT.decisions(pod="ns1/web")["decisions"]
        assert [d["pod"] for d in by_key] == ["ns1/web"]
        by_name = flightrecorder.DEFAULT.decisions(pod="web")["decisions"]
        assert {d["pod"] for d in by_name} == {"ns1/web", "ns2/web"}

    def test_preemption_amends_latest_decision(self):
        before = flightrecorder.DECISIONS_TOTAL.value(
            outcome="preempt_nominated"
        )
        flightrecorder.DEFAULT.record(
            [
                flightrecorder.Decision(
                    pod="default/hi", tick=3, trace_id="abc", mode="scan",
                    outcome="unschedulable",
                )
            ]
        )
        flightrecorder.DEFAULT.record_preemption(
            "default/hi", "preempt_nominated", node="n2",
            victims=("default/lo",),
        )
        got = flightrecorder.DEFAULT.decisions(pod="default/hi")["decisions"]
        assert len(got) == 1  # amended in place, not appended
        assert got[0]["outcome"] == "preempt_nominated"
        assert got[0]["nominatedNode"] == "n2"
        assert got[0]["victims"] == ["default/lo"]
        assert got[0]["traceId"] == "abc"  # join with /debug/traces survives
        assert (
            flightrecorder.DECISIONS_TOTAL.value(outcome="preempt_nominated")
            == before + 1
        )


class TestExplainBacklogShape:
    def test_infeasible_pod_reasons_and_counts(self):
        from kubernetes_tpu.ops.pipeline import explain_backlog
        from tests.test_solver_parity import mk_node, mk_pod

        nodes = [mk_node(f"n{j}") for j in range(5)]
        entries = explain_backlog(
            [mk_pod("stuck", selector={"disk": "ssd"})], nodes,
            max_failed=2,
        )
        (entry,) = entries
        assert entry["pod"] == "default/stuck"
        assert entry["feasibleNodes"] == 0
        assert entry["totalNodes"] == 5
        # Only max_failed nodes listed individually; counts cover ALL.
        assert len(entry["nodes"]) == 2
        assert all(
            v["reasons"] == ["MatchNodeSelector"] for v in entry["nodes"]
        )
        assert entry["reasonCounts"] == {"MatchNodeSelector": 5}

    def test_feasible_pod_topk_scores_decompose(self):
        from kubernetes_tpu.ops.pipeline import explain_backlog
        from tests.test_solver_parity import mk_node, mk_pod

        loaded = mk_pod("a0", cpu=3000, mem_mib=4096)
        loaded.spec.node_name = "n0"
        nodes = [mk_node(f"n{j}") for j in range(4)]
        entries = explain_backlog(
            [mk_pod("p0")], nodes, assigned=[loaded], top_k=2,
        )
        (entry,) = entries
        assert entry["feasibleNodes"] == 4
        winners = [v for v in entry["nodes"] if v["ok"]]
        assert len(winners) == 2
        # Ranked by score desc; the loaded node can't head the list.
        assert winners[0]["score"] >= winners[1]["score"]
        assert winners[0]["node"] != "n0"
        for v in winners:
            assert v["score"] == sum(v["components"].values())
            assert set(v["components"]) == {
                "leastRequested", "balanced", "spreading",
            }


class TestDecisionsEndToEnd:
    def _schedule(self, incremental=False):
        api = APIServer()
        client = Client(LocalTransport(api))
        for j in range(5):
            client.create("nodes", node_wire(f"n{j}"))
        for i in range(6):
            client.create("pods", pod_wire(f"xp{i}"))
        client.create("pods", pod_wire("stuck", selector={"disk": "ssd"}))
        cfg = SchedulerConfig(
            Client(LocalTransport(api)),
            raw_scheduled_cache=incremental,
        ).start()
        assert cfg.wait_for_sync(timeout=SCHED_TIMEOUT)
        sched = (
            IncrementalBatchScheduler(cfg)
            if incremental
            else BatchScheduler(cfg)
        )
        deadline = time.monotonic() + SCHED_TIMEOUT
        while time.monotonic() < deadline:
            sched.schedule_batch(timeout=0.5)
            pods, _ = client.list("pods")
            if sum(1 for p in pods if p.spec.node_name) >= 6:
                break
        cfg.stop()
        assert sum(1 for p in pods if p.spec.node_name) >= 6
        return api, client

    def _assert_recorded(self):
        bound = flightrecorder.DEFAULT.decisions(pod="default/xp3")
        assert bound["decisions"], "no decision recorded for xp3"
        d = bound["decisions"][0]
        assert d["outcome"] == "bound"
        assert d["node"].startswith("n")
        assert d["traceId"]
        assert d["feasibleNodes"] >= 1
        winner = next(v for v in d["nodes"] if v["ok"])
        assert winner["score"] == sum(winner["components"].values())
        stuck = flightrecorder.DEFAULT.decisions(pod="default/stuck")
        s = stuck["decisions"][0]
        assert s["outcome"] == "unschedulable"
        assert s["feasibleNodes"] == 0
        assert s["reasonCounts"].get("MatchNodeSelector") == 5
        # The solve record joins by trace id.
        solves = flightrecorder.DEFAULT.solves()["solves"]
        assert any(r["traceId"] == d["traceId"] for r in solves)
        return d

    def test_batch_daemon_records_decisions(self):
        self._schedule()
        d = self._assert_recorded()
        assert d["mode"] == "scan"

    def test_incremental_daemon_records_decisions(self):
        self._schedule(incremental=True)
        self._assert_recorded()
        solves = flightrecorder.DEFAULT.solves()["solves"]
        assert any(r.get("incremental") for r in solves)

    def test_debug_endpoints_and_ktctl(self, capsys):
        from kubernetes_tpu.cli import ktctl

        api, client = self._schedule()
        http = APIHTTPServer(api).start()
        try:
            with urllib.request.urlopen(
                http.address + "/debug/decisions?pod=xp2", timeout=10
            ) as resp:
                data = json.loads(resp.read())
            with urllib.request.urlopen(
                http.address + "/debug/solves", timeout=10
            ) as resp:
                solves = json.loads(resp.read())
            assert data["kind"] == "DecisionList"
            assert data["decisions"][0]["pod"] == "default/xp2"
            assert solves["kind"] == "SolveList"
            assert solves["solves"], "no solve records served"
            # ktctl explain over HTTP renders the verdict table.
            hclient = Client(HTTPTransport(http.address))
            rc = ktctl.main(["explain", "pod", "xp2"], client=hclient)
        finally:
            http.stop(release_store=False)
        assert rc == 0
        out = capsys.readouterr().out
        assert "DECISION default/xp2" in out
        assert "outcome bound" in out
        assert "feasible" in out and "score" in out

        # ktctl explain for the stuck pod: per-predicate reasons.
        rc = ktctl.main(["explain", "pod", "stuck"], client=client)
        assert rc == 0
        out = capsys.readouterr().out
        assert "MatchNodeSelector" in out

        # Unknown pod: clean nonzero exit, nothing on stdout.
        rc = ktctl.main(["explain", "pod", "no-such-pod"], client=client)
        captured = capsys.readouterr()
        assert rc == 1
        assert captured.out == ""
        assert 'no decision recorded for pod "no-such-pod"' in captured.err

    def test_decision_counter_moves(self):
        before = flightrecorder.DECISIONS_TOTAL.value(outcome="bound")
        self._schedule()
        assert flightrecorder.DECISIONS_TOTAL.value(outcome="bound") >= (
            before + 6
        )


class TestSolveTelemetry:
    def test_sinkhorn_stats_and_metrics(self):
        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops import device_snapshot
        from kubernetes_tpu.ops.sinkhorn import (
            sinkhorn_assignments,
            solve_sinkhorn_stats,
        )
        from tests.test_solver_parity import mk_node, mk_pod

        pods = [mk_pod(f"p{i}", cpu=200) for i in range(12)]
        nodes = [mk_node(f"n{j}") for j in range(3)]
        d = device_snapshot(build_snapshot(pods, nodes))
        a, waves, titers, residual = solve_sinkhorn_stats(
            d.pods, d.nodes, window=8
        )
        assert int(waves) >= 1
        assert int(titers) >= 1
        assert float(residual) >= 0.0
        before = flightrecorder.SOLVE_ITERATIONS.count(mode="sinkhorn")
        d2 = device_snapshot(build_snapshot(pods, nodes))
        assign, wave_count = sinkhorn_assignments(d2, window=8)
        assert wave_count >= 1
        assert (
            flightrecorder.SOLVE_ITERATIONS.count(mode="sinkhorn")
            == before + 1
        )

    def test_wave_iterations_observed(self):
        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.ops import device_snapshot
        from kubernetes_tpu.ops.wave import wave_assignments
        from tests.test_solver_parity import mk_node, mk_pod

        pods = [mk_pod(f"p{i}") for i in range(6)]
        nodes = [mk_node(f"n{j}") for j in range(2)]
        before = flightrecorder.SOLVE_ITERATIONS.count(mode="wave")
        wave_assignments(device_snapshot(build_snapshot(pods, nodes)))
        assert (
            flightrecorder.SOLVE_ITERATIONS.count(mode="wave") == before + 1
        )
