"""Durable store: WAL + snapshot recovery.

The reference keeps all master state in etcd, so an apiserver process
death loses nothing (pkg/tools/etcd_helper.go:101, external daemon per
hack/local-up-cluster.sh:152-153). Here the KVStore itself is durable
when given a data_dir: these tests kill the apiserver with pods
mid-churn, restart it on the same data-dir, and assert every object,
binding, and allocator lease survives with version monotonicity intact
(VERDICT round-2 item 1).
"""

import json
import os
import threading
import time

import pytest

from kubernetes_tpu.client import Client, HTTPTransport
from kubernetes_tpu.server import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.store.kvstore import (
    CompactedError,
    ConflictError,
    KVStore,
    NotFoundError,
    StoreError,
)


def obj(name, **extra):
    return {"kind": "Pod", "metadata": {"name": name}, **extra}


class TestKVStoreRecovery:
    def test_objects_survive_reopen(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        s.create("/registry/pods/default/a", obj("a"))
        s.create("/registry/pods/default/b", obj("b"))
        s.set("/registry/pods/default/a", obj("a", spec={"nodeName": "n1"}))
        s.delete("/registry/pods/default/b")
        v_before = s.version
        s.close()

        s2 = KVStore(data_dir=d)
        got = s2.get("/registry/pods/default/a")
        assert got["spec"] == {"nodeName": "n1"}
        with pytest.raises(NotFoundError):
            s2.get("/registry/pods/default/b")
        # The logical clock never moves backwards across restarts.
        assert s2.version >= v_before
        nxt = s2.create("/registry/pods/default/c", obj("c"))
        assert int(nxt["metadata"]["resourceVersion"]) > v_before

    def test_per_key_versions_survive(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        created = s.create("/k/a", obj("a"))
        rv = int(created["metadata"]["resourceVersion"])
        s.close()
        s2 = KVStore(data_dir=d)
        assert int(s2.get("/k/a")["metadata"]["resourceVersion"]) == rv
        # CAS against the recovered version works; stale version conflicts.
        s2.set("/k/a", obj("a2"), expected_version=rv)
        with pytest.raises(ConflictError):
            s2.set("/k/a", obj("a3"), expected_version=rv)

    def test_ttl_is_wall_clock_across_restart(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        s.create("/k/ephemeral", obj("e"), ttl=0.2)
        s.create("/k/durable", obj("d"), ttl=60.0)
        s.close()
        time.sleep(0.25)
        s2 = KVStore(data_dir=d)
        with pytest.raises(NotFoundError):
            s2.get("/k/ephemeral")
        assert s2.get("/k/durable")["metadata"]["name"] == "d"

    def test_snapshot_rollover_truncates_wal(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d, snapshot_every=10)
        for i in range(35):
            s.create(f"/k/{i:03d}", obj(str(i)))
        s.close()
        wal_lines = open(os.path.join(d, "wal.log")).read().splitlines()
        assert len(wal_lines) < 10  # rolled over, not 35 records deep
        assert os.path.exists(os.path.join(d, "snapshot.json"))
        s2 = KVStore(data_dir=d)
        assert len(s2.keys("/k/")) == 35
        assert s2.version >= 35

    def test_torn_wal_tail_is_tolerated(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        s.create("/k/a", obj("a"))
        s.create("/k/b", obj("b"))
        s.close()
        # Simulate a crash mid-append: truncate the last record in half.
        wal = os.path.join(d, "wal.log")
        raw = open(wal).read()
        open(wal, "w").write(raw[: len(raw) - 20])
        s2 = KVStore(data_dir=d)
        assert s2.get("/k/a")["metadata"]["name"] == "a"
        with pytest.raises(NotFoundError):
            s2.get("/k/b")  # the torn write was never acknowledged
        # Store still writable after recovering from a torn tail.
        s2.create("/k/c", obj("c"))
        s2.close()
        s3 = KVStore(data_dir=d)
        assert s3.keys("/k/") == ["/k/a", "/k/c"]

    def test_torn_tail_truncated_before_new_appends(self, tmp_path):
        """A torn line must be cut from the file on recovery: otherwise
        the next acked write fuses onto the torn bytes and is itself
        lost at the restart after that."""
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        s.create("/k/a", obj("a"))
        s.snapshot()  # fold /k/a in; WAL now empty
        s.create("/k/b", obj("b"))  # the only WAL record
        s.close()
        wal = os.path.join(d, "wal.log")
        raw = open(wal, "rb").read()
        open(wal, "wb").write(raw[:-5])  # tear it: zero replayable records

        s2 = KVStore(data_dir=d)
        s2.create("/k/c", obj("c"))  # acked post-recovery write
        s2.close()
        # Every line in the WAL must be intact JSON now.
        for line in open(wal):
            if line.strip():
                json.loads(line)
        s3 = KVStore(data_dir=d)
        assert s3.keys("/k/") == ["/k/a", "/k/c"]

    def test_watch_resume_after_restart_raises_410(self, tmp_path):
        """History (watch replay buffer) is soft state: after a restart a
        watcher at an old version must get CompactedError and re-list,
        the same 410-Gone path etcd index clears trigger."""
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        s.create("/k/a", obj("a"))
        old_version = s.version
        for i in range(5):
            s.create(f"/k/more{i}", obj(str(i)))
        s.close()
        s2 = KVStore(data_dir=d)
        with pytest.raises(CompactedError):
            s2.watch("/k/", since=old_version)
        # From-now watches work immediately.
        stream = s2.watch("/k/", since=0)
        s2.create("/k/new", obj("new"))
        ev = stream.next(timeout=2)
        assert ev is not None and ev.object["metadata"]["name"] == "new"


def pod_wire(name, node=""):
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [{"name": "c", "image": "nginx"}],
            **({"nodeName": node} if node else {}),
        },
    }


def svc_wire(name, port=80):
    return {
        "kind": "Service",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"ports": [{"port": port}], "selector": {"app": name}},
    }


class TestDataDirExclusion:
    """Two stores on one data dir would interleave WAL appends and
    race snapshot.json via os.replace — etcd serializes this for the
    reference by having one member own the dir. We take an exclusive
    flock at construction; the OS drops it on any death (kill -9
    included), so a dead owner never wedges restart."""

    def test_second_open_fails_fast(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        with pytest.raises(StoreError, match="locked"):
            KVStore(data_dir=d)
        s.close()
        s2 = KVStore(data_dir=d)  # released on close
        s2.close()

    def test_closed_store_refuses_writes(self, tmp_path):
        """A write racing shutdown must be refused, not acked with the
        WAL handle already gone (an ack that recovery can't honor)."""
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d)
        s.close()
        with pytest.raises(StoreError, match="closed"):
            s.create("/k/a", obj("a"))


class TestGroupCommit:
    """fsync-before-ack is the default contract (etcd's); the fsync is
    group-committed — concurrent writers share disk flushes."""

    def test_default_is_fsync(self, tmp_path):
        s = KVStore(data_dir=str(tmp_path / "d"))
        assert s._fsync is True
        s.close()

    def test_concurrent_writers_all_durable(self, tmp_path):
        d = str(tmp_path / "data")
        s = KVStore(data_dir=d, fsync=True)
        errors = []

        def writer(wid):
            try:
                for i in range(50):
                    s.create(f"/k/w{wid}-{i}", obj(f"w{wid}-{i}"))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(w,)) for w in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        s.close()
        s2 = KVStore(data_dir=d)
        assert len(s2.keys("/k/")) == 400
        s2.close()

    def test_no_fsync_flag_parses(self):
        from kubernetes_tpu.cmd.daemons import apiserver_parser

        args = apiserver_parser().parse_args(["--no-data-fsync"])
        assert args.data_fsync is False
        assert apiserver_parser().parse_args([]).data_fsync is True


class TestApiserverRestart:
    """Kill the apiserver mid-churn; restart on the same data-dir."""

    def test_cluster_survives_apiserver_death(self, tmp_path):
        d = str(tmp_path / "data")
        server = APIHTTPServer(APIServer(store=KVStore(data_dir=d))).start()
        client = Client(HTTPTransport(server.address))

        client.create(
            "nodes",
            {
                "kind": "Node",
                "apiVersion": "v1",
                "metadata": {"name": "n1"},
                "status": {"capacity": {"cpu": "4", "memory": "8Gi"}},
            },
        )
        for i in range(10):
            client.create("pods", pod_wire(f"pod-{i}"))
        # Bind half of them (the guarded write the scheduler issues).
        for i in range(5):
            client.bind(f"pod-{i}", "n1", namespace="default")
        svc = client.create("services", svc_wire("web"))
        ip_before = svc.spec.cluster_ip
        items, _ = client.list("pods", namespace="default")
        pods_before = {p.metadata.name: p for p in items}
        max_rv = max(
            int(p.metadata.resource_version) for p in pods_before.values()
        )

        # Kill: stop HTTP, abandon the store object without closing it —
        # durability must come from the WAL, not a graceful shutdown.
        server.stop()

        server2 = APIHTTPServer(APIServer(store=KVStore(data_dir=d))).start()
        client2 = Client(HTTPTransport(server2.address))
        try:
            items2, _ = client2.list("pods", namespace="default")
            pods_after = {p.metadata.name: p for p in items2}
            assert set(pods_after) == set(pods_before)
            for i in range(5):
                assert pods_after[f"pod-{i}"].spec.node_name == "n1"
            for i in range(5, 10):
                assert not pods_after[f"pod-{i}"].spec.node_name
            # The service kept its cluster IP...
            svc_after = client2.get("services", "web", namespace="default")
            assert svc_after.spec.cluster_ip == ip_before
            # ...and the allocator lease survived: a new service must not
            # be handed the recovered service's IP.
            svc2 = client2.create("services", svc_wire("web2", port=81))
            assert svc2.spec.cluster_ip != ip_before
            # Version monotonicity: new writes are strictly newer than
            # anything the first incarnation handed out.
            p_new = client2.create("pods", pod_wire("post-restart"))
            assert int(p_new.metadata.resource_version) > max_rv
            # Binding a pre-death pod still enforces the guarded write.
            client2.bind("pod-7", "n1", namespace="default")
            assert (
                client2.get("pods", "pod-7", namespace="default").spec.node_name
                == "n1"
            )
        finally:
            server2.stop()

    def test_acked_writes_survive_kill_mid_churn(self, tmp_path):
        """A writer hammers creates while the server dies underneath it.
        Every create the client saw acknowledged must be present after
        recovery (the WAL append happens before the response)."""
        d = str(tmp_path / "data")
        server = APIHTTPServer(APIServer(store=KVStore(data_dir=d))).start()
        client = Client(HTTPTransport(server.address))

        acked = []
        errors = []
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                name = f"churn-{i:04d}"
                try:
                    client.create("pods", pod_wire(name))
                    acked.append(name)
                except Exception:
                    errors.append(name)
                    return
                i += 1

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        time.sleep(0.5)  # let some churn through
        server.stop()  # kill mid-churn
        stop.set()
        t.join(timeout=5)
        assert len(acked) > 10, "churn thread never got going"

        server2 = APIHTTPServer(APIServer(store=KVStore(data_dir=d))).start()
        client2 = Client(HTTPTransport(server2.address))
        try:
            items, _ = client2.list("pods", namespace="default")
            names = {p.metadata.name for p in items}
            missing = [n for n in acked if n not in names]
            assert not missing, f"acked writes lost across restart: {missing}"
        finally:
            server2.stop()


@pytest.mark.slow
class TestSubprocessKill:
    """The real thing: a separate apiserver process, SIGKILL, restart."""

    def test_kill_minus_9(self, tmp_path):
        import re
        import signal
        import subprocess
        import sys

        d = str(tmp_path / "data")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

        def spawn():
            proc = subprocess.Popen(
                [
                    sys.executable,
                    os.path.join(repo, "bin", "hyperkube"),
                    "apiserver",
                    "--port", "0",
                    "--data-dir", d,
                ],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=repo,
            )
            line = proc.stdout.readline()
            m = re.search(r"listening on .*?:(\d+)", line)
            assert m, f"no listen line: {line!r}"
            return proc, int(m.group(1))

        proc, port = spawn()
        try:
            client = Client(HTTPTransport(f"http://127.0.0.1:{port}"))
            for i in range(20):
                client.create("pods", pod_wire(f"kp-{i}"))
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)

            proc2, port2 = spawn()
            try:
                client2 = Client(HTTPTransport(f"http://127.0.0.1:{port2}"))
                items, _ = client2.list("pods", namespace="default")
                names = {p.metadata.name for p in items}
                assert names >= {f"kp-{i}" for i in range(20)}
            finally:
                proc2.kill()
                proc2.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def test_group_commit_survives_snapshot_rotation(tmp_path):
    """Round-4 review regression: a writer whose captured WAL handle is
    rotated by a concurrent snapshot mid-fsync must not surface a bogus
    failure (the snapshot made its record durable). snapshot_every=3
    with 4 writers x 30 records forces ~40 rotations under fire."""
    import threading

    from kubernetes_tpu.store import KVStore

    d = str(tmp_path / "data")
    s = KVStore(data_dir=d, fsync=True, snapshot_every=3)
    errors = []

    def writer(i):
        try:
            for j in range(30):
                s.create(f"/k{i}-{j}", {"metadata": {"name": f"x{i}-{j}"}})
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors, errors
    s.close()
    s2 = KVStore(data_dir=d)
    try:
        assert len(s2.keys("/k")) == 120  # every acked write recovered
    finally:
        s2.close()
