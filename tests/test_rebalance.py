"""Continuous rebalancing plane (ISSUE 17): the plan builder (move
staging, gang-atomic grouping, budget/disruption clamps), the
descheduler's journaled move protocol (evict -> recreate -> nominate,
crash recovery, stale-nomination sweep), the autoscaler's grow/shrink
loop, the /debug/rebalance HTTP surface, `ktctl rebalance`, the two
rebalance SLO objectives, and the <5% overhead guard.

The plan_moves kernel/oracle bit-exactness lives with the other solver
twins in tests/test_solver_parity.py (TestRebalanceParity)."""

import io
import json
import threading
import time
from contextlib import redirect_stderr, redirect_stdout

import numpy as np
import pytest

from kubernetes_tpu.models.objects import (
    POD_GROUP_LABEL,
    REBALANCE_DEST_ANNOTATION,
    REBALANCE_JOURNAL_LABEL,
)
from kubernetes_tpu.utils import capacity as capmod
from kubernetes_tpu.utils import faults, metrics, slo
from kubernetes_tpu.utils import rebalance as rebmod

pytestmark = pytest.mark.rebalance


def _pod_wire(name, cpu="200m", mem="64Mi", labels=None, node=None):
    w = {
        "kind": "Pod",
        "metadata": {
            "name": name, "namespace": "default", "labels": labels or {},
        },
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "pause",
                    "resources": {"limits": {"cpu": cpu, "memory": mem}},
                }
            ]
        },
    }
    return w


def _node_wire(name, cpu="1", mem="2Gi", pods="20"):
    return {
        "kind": "Node",
        "metadata": {"name": name, "labels": {}},
        "status": {
            "capacity": {"cpu": cpu, "memory": mem, "pods": pods},
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def _cols(n, cpu_cap=1000.0, mem_cap=2048.0, pods_cap=20.0, cpu_fit=0.0,
          mem_fit=0.0, pods_used=0.0):
    ones = np.ones(n, np.float32)
    return {
        "cpu_cap": ones * cpu_cap,
        "mem_cap": ones * mem_cap,
        "pods_cap": ones * pods_cap,
        "cpu_fit": ones * cpu_fit,
        "mem_fit": ones * mem_fit,
        "pods_used": ones * pods_used,
        "over": np.zeros(n, bool),
        "sched": np.ones(n, bool),
    }


def _mk_bound(client, name, node, cpu="200m", labels=None):
    client.create("pods", _pod_wire(name, cpu=cpu, labels=labels))
    res = client.bind_bulk([(name, node)])
    assert all(r.get("status") == "Success" for r in res), res


def _mk_api():
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.server.api import APIServer

    api = APIServer()
    return api, Client(LocalTransport(api))


def _fragment(client, n_nodes=6, per_node=3, cpu="200m"):
    """The canonical fragmented cluster: `per_node` small pods bound
    to every node, so each node keeps an unusable shard free."""
    for j in range(n_nodes):
        client.create("nodes", _node_wire(f"n{j}"))
    k = 0
    for j in range(n_nodes):
        for _ in range(per_node):
            _mk_bound(client, f"p{k}", f"n{j}", cpu=cpu)
            k += 1
    return k


@pytest.fixture(autouse=True)
def _fresh_monitors(monkeypatch):
    monkeypatch.setattr(rebmod, "DEFAULT", rebmod.RebalanceMonitor())
    monkeypatch.setattr(capmod, "DEFAULT", capmod.CapacityMonitor())
    faults.clear()
    yield
    faults.clear()


def _list_pods(client):
    pods, _ = client.list("pods")
    return pods


class TestBuildPlan:
    """The host half of the planner: staging, clamps, gang atomicity."""

    def _pods(self, spread, cpu="200m", labels=None):
        """Bound pods from a {node: count} spread, via serde objects."""
        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.objects import Pod

        out = []
        k = 0
        for node, count in spread.items():
            for _ in range(count):
                p = serde.from_wire(
                    Pod, _pod_wire(f"p{k}", cpu=cpu, labels=labels)
                )
                p.spec.node_name = node
                p.status.phase = "Running"
                out.append(p)
                k += 1
        return out

    # A 500m probe against 600m-charged kilocore nodes: each node's
    # 400m free shard strands it, and moving a single 200m pod off a
    # node opens a 600m shard that fits — so every single move has
    # positive marginal gain (a 700m probe would need a two-move
    # lookahead the greedy kernel deliberately does not do).
    PROBES = [("probe-500m", 500.0, 256.0, 1)]

    def test_consolidation_plan(self):
        """Six nodes each 600m charged by three 200m pods: a 500m
        probe is stranded everywhere; the plan pairs pods up and the
        forecast score drops."""
        names = [f"n{j}" for j in range(6)]
        cols = _cols(6, cpu_fit=600.0, pods_used=3.0)
        pods = self._pods({n: 3 for n in names})
        plan = rebmod.build_plan(cols, names, pods, self.PROBES)
        assert plan is not None and plan["moves"]
        assert plan["score_after"] < plan["score_before"]
        for m in plan["moves"]:
            assert m["from"] != m["to"] and m["gain"] > 0

    def test_move_budget_clamps(self):
        names = [f"n{j}" for j in range(6)]
        cols = _cols(6, cpu_fit=600.0, pods_used=3.0)
        pods = self._pods({n: 3 for n in names})
        plan = rebmod.build_plan(
            cols, names, pods, self.PROBES, move_budget=2
        )
        assert plan is not None and len(plan["moves"]) <= 2

    def test_empty_and_none_paths(self):
        assert rebmod.build_plan(_cols(2), ["a", "b"], [], self.PROBES) is None
        pods = self._pods({"a": 1})
        assert (
            rebmod.build_plan(
                _cols(2), ["a", "b"], pods, self.PROBES, move_budget=0
            )
            is None
        )
        assert rebmod.build_plan({}, [], pods, self.PROBES) is None  # broken

    def test_gang_atomicity_drops_partial_groups(self):
        """A gang whose movable members were only partly replanned
        must not move at all — a half-moved slice is worse
        fragmentation, not less."""
        names = [f"n{j}" for j in range(4)]
        cols = _cols(4, cpu_fit=600.0, pods_used=3.0)
        gang = {POD_GROUP_LABEL: "slice-a"}
        pods = self._pods({n: 3 for n in names}, labels=gang)
        plan = rebmod.build_plan(cols, names, pods, self.PROBES)
        assert plan is not None
        gang_key = "default/slice-a"
        if gang_key in plan["dropped_partial_gangs"]:
            assert plan["moves"] == []
        else:
            moved = {m["pod"] for m in plan["moves"]}
            assert moved in (set(), {f"default/p{k}" for k in range(12)})

    def test_movable_filter(self):
        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.objects import Pod

        bound = self._pods({"a": 1})[0]
        pending = serde.from_wire(Pod, _pod_wire("pend"))
        done = self._pods({"a": 1})[0]
        done.status.phase = "Succeeded"
        term = self._pods({"a": 1})[0]
        term.metadata.deletion_timestamp = "2026-01-01T00:00:00Z"
        mid_move = self._pods({"a": 1})[0]
        mid_move.metadata.annotations = {REBALANCE_DEST_ANNOTATION: "b"}
        movable = rebmod.movable_pods([bound, pending, done, term, mid_move])
        assert movable == [bound]


class TestMonitor:
    def test_cold_snapshot_contract(self):
        m = rebmod.RebalanceMonitor()
        snap = m.snapshot()
        assert snap["kind"] == "RebalanceReport"
        assert snap["sampled"] is False and snap["samples"] == 0
        assert snap["moves"] == [] and snap["trend"] == []

    def test_cycle_feeds_series_and_trend(self):
        m = rebmod.RebalanceMonitor()
        imp_before = rebmod.IMPROVEMENT.count()
        eff_before = rebmod.MOVES_PER_IMPROVEMENT.count()
        cycle = m.record_cycle(0.8, 0.3, moves_executed=5)
        assert cycle["improvement"] == 0.5
        assert rebmod.IMPROVEMENT.count() == imp_before + 1
        assert rebmod.MOVES_PER_IMPROVEMENT.count() == eff_before + 1
        snap = m.snapshot()
        assert snap["sampled"] and snap["samples"] == 1
        assert snap["trend"] == [0.5]

    def test_zero_improvement_saturates_efficiency(self):
        """Moves without score movement observe the ladder cap — the
        defrag-efficiency SLO must read a real breach, not a NaN."""
        m = rebmod.RebalanceMonitor()
        before = rebmod.MOVES_PER_IMPROVEMENT.count()
        m.record_cycle(0.5, 0.5, moves_executed=3)
        assert rebmod.MOVES_PER_IMPROVEMENT.count() == before + 1
        q = rebmod.MOVES_PER_IMPROVEMENT.quantile(0.99)
        assert q >= rebmod.EFFICIENCY_SATURATION / 2

    def test_stranded_outcome_burns_both_counters(self):
        m = rebmod.RebalanceMonitor()
        moves_before = rebmod.MOVES.value(outcome="stranded")
        stranded_before = rebmod.STRANDED.value()
        m.record_move("stranded")
        assert rebmod.MOVES.value(outcome="stranded") == moves_before + 1
        assert rebmod.STRANDED.value() == stranded_before + 1
        assert m.snapshot()["outcomes"] == {}  # cold until a cycle


class TestSLOObjectives:
    def test_objectives_are_registered(self):
        objs = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        eff = objs["rebalance_efficiency"]
        assert eff.series == "rebalance_moves_per_improvement"
        assert eff.severity == "warn"
        stranded = objs["rebalance_stranded_pods"]
        assert stranded.series == "rebalance_stranded_pods_total"
        assert stranded.kind == "counter_max" and stranded.target == 0.0
        assert stranded.severity == "gate"

    def test_stranded_pod_burns(self):
        reg = metrics.Registry()
        c = reg.counter("rebalance_stranded_pods_total", "x")
        objs = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        e = slo.evaluate_objective(
            objs["rebalance_stranded_pods"], registry=reg
        )
        assert e["verdict"] == "pass", e
        c.inc()
        e = slo.evaluate_objective(
            objs["rebalance_stranded_pods"], registry=reg
        )
        assert e["verdict"] == "burn", e

    def test_efficiency_warns_not_burns(self):
        reg = metrics.Registry()
        h = reg.histogram("rebalance_moves_per_improvement", "x")
        for _ in range(20):
            h.observe(119.0)
        objs = {o.name: o for o in slo.DEFAULT_OBJECTIVES}
        e = slo.evaluate_objective(objs["rebalance_efficiency"], registry=reg)
        assert e["verdict"] == "warn", e


class TestDescheduler:
    def _descheduler(self, client, **kw):
        from kubernetes_tpu.controllers.descheduler import Descheduler

        kw.setdefault("grace_period_seconds", 0)
        return Descheduler(client, **kw)

    def test_defrag_cycle_moves_and_improves(self):
        """The tentpole loop on a live apiserver: fragment, run one
        cycle, fragmentation drops, every move journaled+graceful,
        zero force-deletes, replacements pinned at destinations."""
        api, client = _mk_api()
        _fragment(client)
        client.create("pods", _pod_wire("waiting", cpu="500m"))
        d = self._descheduler(client)
        out = d.sync_once()
        assert out["triggered"] and out["moves_executed"] > 0
        assert out["score_after"] < out["score_before"]
        snap = rebmod.DEFAULT.snapshot()
        assert snap["sampled"]
        assert snap["outcomes"]["evicted"] == out["moves_executed"]
        # No journal leaks, no stranded pods, replacements pinned.
        tmpl, _ = client.list("podtemplates")
        assert tmpl == []
        pods = _list_pods(client)
        assert {p.metadata.name for p in pods} >= {
            f"p{k}" for k in range(18)
        }
        pinned = [
            p
            for p in pods
            if (p.metadata.annotations or {}).get(REBALANCE_DEST_ANNOTATION)
        ]
        assert len(pinned) == out["moves_executed"]
        for p in pinned:
            assert not p.spec.node_name  # pending toward its pin

    def test_trigger_gates_on_threshold_and_backlog(self):
        """Below the fragmentation threshold, or with an empty
        backlog, the periodic cycle observes but does not evict."""
        api, client = _mk_api()
        _fragment(client)
        d = self._descheduler(client)  # no pending pod -> no trigger
        out = d.sync_once()
        assert not out["triggered"] and out["moves_executed"] == 0
        assert rebmod.DEFAULT.snapshot()["sampled"] is False
        client.create("pods", _pod_wire("waiting", cpu="500m"))
        high = self._descheduler(client, frag_threshold=1.1)
        out = high.sync_once()
        assert not out["triggered"]  # threshold never crossed
        assert _list_pods(client) and not [
            t for t, _ in [client.list("podtemplates")]
        ][0]

    def test_disruption_cap_clamps_per_tick(self):
        api, client = _mk_api()
        _fragment(client)
        client.create("pods", _pod_wire("waiting", cpu="500m"))
        d = self._descheduler(client, disruption_cap=2)
        out = d.sync_once()
        assert out["triggered"]
        assert 0 < out["moves_executed"] <= 2

    def test_crash_mid_move_strands_nothing(self):
        """DESCHED_MOVE_CRASH between eviction and recreation: the
        journal survives, recovery replays it, the pod re-pends, and
        the stranded counter never burns."""
        api, client = _mk_api()
        _fragment(client)
        client.create("pods", _pod_wire("waiting", cpu="500m"))
        stranded_before = rebmod.STRANDED.value()
        rule = faults.inject(faults.DESCHED_MOVE_CRASH, p=1.0, times=1)
        d = self._descheduler(client)
        with pytest.raises(faults.FaultInjected):
            d.sync_once()
        assert rule.fired == 1
        tmpl, _ = client.list("podtemplates")
        assert len(tmpl) == 1  # the orphaned move intent
        assert REBALANCE_JOURNAL_LABEL in (tmpl[0].metadata.labels or {})
        missing = {f"p{k}" for k in range(18)} - {
            p.metadata.name for p in _list_pods(client)
        }
        assert len(missing) == 1  # evicted, not yet recreated
        faults.clear()
        assert d.recover() == 1
        tmpl, _ = client.list("podtemplates")
        assert tmpl == []
        assert {f"p{k}" for k in range(18)} <= {
            p.metadata.name for p in _list_pods(client)
        }
        assert rebmod.STRANDED.value() == stranded_before
        assert rebmod.MOVES.value(outcome="recovered") >= 1

    def test_sweep_settles_bound_and_stale_pods(self):
        api, client = _mk_api()
        client.create("nodes", _node_wire("n0"))
        # A bound pod still carrying its pin: the move completed.
        _mk_bound(client, "landed", "n0")
        client.patch(
            "pods",
            "landed",
            {"metadata": {"annotations": {REBALANCE_DEST_ANNOTATION: "n0"}}},
        )
        # A pending pod pinned past the TTL: wedged, must be freed.
        client.create("pods", _pod_wire("wedged"))
        client.patch(
            "pods",
            "wedged",
            {"metadata": {"annotations": {REBALANCE_DEST_ANNOTATION: "n9"}}},
        )
        d = self._descheduler(client, nomination_ttl_s=0.0)
        d._sweep_nominations()
        pods = {p.metadata.name: p for p in _list_pods(client)}
        assert not (pods["landed"].metadata.annotations or {}).get(
            REBALANCE_DEST_ANNOTATION
        )
        assert not (pods["wedged"].metadata.annotations or {}).get(
            REBALANCE_DEST_ANNOTATION
        )
        assert rebmod.MOVES.value(outcome="rebound") >= 1
        assert rebmod.MOVES.value(outcome="failed") >= 1

    def test_gang_group_commits_atomically(self):
        """A gang's moves recreate all members then land through one
        atomic bind_bulk — members end up BOUND at their destinations
        in the same cycle, not trickling through nominations."""
        api, client = _mk_api()
        for j in range(4):
            client.create("nodes", _node_wire(f"n{j}"))
        gang = {POD_GROUP_LABEL: "slice-a"}
        # Gang spread one-per-node + a filler each so consolidation
        # pays; the gang must move or hold as one unit.
        for j in range(3):
            _mk_bound(client, f"g{j}", f"n{j}", cpu="200m", labels=gang)
            _mk_bound(client, f"f{j}", f"n{j}", cpu="400m")
        client.create("pods", _pod_wire("waiting", cpu="900m"))
        d = self._descheduler(client, disruption_cap=8)
        out = d.sync_once(force=True)
        if out["moves_executed"] == 0:
            pytest.skip("planner found no gainful moves on this layout")
        pods = {p.metadata.name: p for p in _list_pods(client)}
        members = [pods[f"g{j}"] for j in range(3)]
        moved = [p for p in members if p.spec.node_name]
        # Gang members never split: the ones the plan touched are all
        # bound (atomic commit) — none left pending mid-move.
        gang_outcomes = rebmod.DEFAULT.snapshot()["outcomes"]
        if gang_outcomes.get("rebound"):
            assert all(p.spec.node_name for p in members), {
                p.metadata.name: p.spec.node_name for p in members
            }

    def test_drain_node_empties_forced_source(self):
        api, client = _mk_api()
        for j in range(3):
            client.create("nodes", _node_wire(f"n{j}"))
        for k in range(3):
            _mk_bound(client, f"d{k}", "n0", cpu="200m")
        d = self._descheduler(client, disruption_cap=8)
        out = d.drain_node("n0")
        assert out["moves_executed"] == 3
        for p in _list_pods(client):
            if p.spec.node_name:
                assert p.spec.node_name != "n0"
            else:
                dest = (p.metadata.annotations or {}).get(
                    REBALANCE_DEST_ANNOTATION, ""
                )
                assert dest and dest != "n0"


@pytest.mark.autoscale
class TestAutoscaler:
    class Pool:
        name = "hollow"

        def __init__(self, client, start=2):
            self.client = client
            self.n = start
            self.next = start
            self.shrunk = []

        def size(self):
            return self.n

        def node_names(self):
            return [f"n{j}" for j in range(self.next)]

        def grow(self, k):
            added = []
            for _ in range(k):
                nm = f"n{self.next}"
                self.client.create("nodes", _node_wire(nm))
                added.append(nm)
                self.next += 1
                self.n += 1
            return added

        def shrink(self, name):
            self.client.delete("nodes", name)
            self.shrunk.append(name)
            self.n -= 1

    def _mk(self, client, pool, **kw):
        from kubernetes_tpu.controllers.autoscaler import Autoscaler
        from kubernetes_tpu.controllers.descheduler import Descheduler

        kw.setdefault("grow_after", 2)
        kw.setdefault("shrink_after", 2)
        return Autoscaler(
            client,
            pool,
            descheduler=Descheduler(client, grace_period_seconds=0),
            **kw,
        )

    def test_grows_on_sustained_backlog(self):
        from kubernetes_tpu.controllers.autoscaler import (
            POOL_SIZE,
            SCALE_EVENTS,
        )

        api, client = _mk_api()
        for j in range(2):
            client.create("nodes", _node_wire(f"n{j}"))
        pool = self.Pool(client)
        a = self._mk(client, pool, max_size=3)
        _mk_bound(client, "f0", "n0", cpu="600m")
        _mk_bound(client, "f1", "n1", cpu="600m")
        client.create("pods", _pod_wire("starving", cpu="600m"))
        ups_before = SCALE_EVENTS.value(direction="up")
        acts = [a.sync_once()["action"] for _ in range(3)]
        assert "grow" in acts
        assert pool.size() == 3
        assert POOL_SIZE.value(pool="hollow") == 3
        assert SCALE_EVENTS.value(direction="up") == ups_before + 1
        # At max_size the pool holds even under sustained starvation.
        for _ in range(4):
            a.sync_once()
        assert pool.size() == 3

    def test_shrinks_via_cordon_drain(self):
        """Sustained idle: cordon the emptiest node, drain it through
        the descheduler's graceful path, retire it only once empty."""
        from kubernetes_tpu.controllers.autoscaler import SCALE_EVENTS

        api, client = _mk_api()
        for j in range(3):
            client.create("nodes", _node_wire(f"n{j}"))
        pool = self.Pool(client, start=3)
        a = self._mk(client, pool, min_size=2)
        _mk_bound(client, "keep", "n0", cpu="100m")
        _mk_bound(client, "mv", "n2", cpu="100m")
        downs_before = SCALE_EVENTS.value(direction="down")
        acts = [a.sync_once()["action"] for _ in range(5)]
        assert "shrink" in acts
        assert pool.size() == 2
        assert SCALE_EVENTS.value(direction="down") == downs_before + 1
        shrunk = pool.shrunk[0]
        # The drained node's pod moved out gracefully (exists, and is
        # either rebound elsewhere or pending toward a new pin).
        pods = {p.metadata.name: p for p in _list_pods(client)}
        assert "mv" in pods and pods["mv"].spec.node_name != shrunk
        nodes, _ = client.list("nodes")
        assert shrunk not in {n.metadata.name for n in nodes}

    def test_mixed_load_holds_steady(self):
        api, client = _mk_api()
        for j in range(2):
            client.create("nodes", _node_wire(f"n{j}"))
        pool = self.Pool(client)
        a = self._mk(client, pool, low_util=0.2)
        _mk_bound(client, "busy", "n0", cpu="900m")  # util high, no backlog
        for _ in range(5):
            s = a.sync_once()
            assert s["action"] == "none", s
        assert pool.size() == 2


class TestHTTPSurface:
    def test_debug_rebalance_cold_and_sampled(self):
        import urllib.error
        import urllib.request

        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        srv = APIHTTPServer(api).start()
        try:
            with urllib.request.urlopen(
                srv.address + "/debug/rebalance", timeout=10
            ) as resp:
                body = json.loads(resp.read())
            assert body["kind"] == "RebalanceReport"
            assert body["sampled"] is False
            rebmod.DEFAULT.record_plan(
                {"moves": [{"pod": "default/p0", "from": "a", "to": "b"}]}
            )
            rebmod.DEFAULT.record_cycle(0.7, 0.3, moves_executed=2)
            with urllib.request.urlopen(
                srv.address + "/debug/rebalance", timeout=10
            ) as resp:
                body = json.loads(resp.read())
            assert body["sampled"] and body["samples"] == 1
            assert body["last_cycle"]["improvement"] == 0.4
            assert body["moves"][0]["pod"] == "default/p0"
            # The 404 contract advertises the endpoint.
            try:
                urllib.request.urlopen(
                    srv.address + "/debug/nope", timeout=10
                )
                assert False, "404 expected"
            except urllib.error.HTTPError as e:
                assert "/debug/rebalance" in e.read().decode()
        finally:
            srv.stop()


class TestKtctl:
    @staticmethod
    def _run(client, argv):
        from kubernetes_tpu.cli import ktctl

        out, err = io.StringIO(), io.StringIO()
        with redirect_stdout(out), redirect_stderr(err):
            rc = ktctl.main(argv, client=client)
        return rc, out.getvalue(), err.getvalue()

    @pytest.fixture
    def client(self):
        return _mk_api()[1]

    def test_miss_contract(self, client):
        """Cold cluster: exit 1, 'no rebalance samples recorded' on
        stderr, EMPTY stdout — for both subcommands."""
        for what in ("plan", "status"):
            rc, out, err = self._run(client, ["rebalance", what])
            assert rc == 1
            assert out == ""
            assert "no rebalance samples recorded" in err

    def test_populated_plan_status_json_yaml(self, client):
        _fragment(client)
        client.create("pods", _pod_wire("waiting", cpu="500m"))
        from kubernetes_tpu.controllers.descheduler import Descheduler

        out = Descheduler(client, grace_period_seconds=0).sync_once()
        assert out["triggered"]
        rc, text, _ = self._run(client, ["rebalance", "plan"])
        assert rc == 0
        assert "POD" in text and "GAIN" in text and "defrag" in text
        rc, text, _ = self._run(client, ["rebalance", "status"])
        assert rc == 0
        assert "cycles: 1" in text and "evicted=" in text
        rc, text, _ = self._run(client, ["rebalance", "status", "-o", "json"])
        assert rc == 0
        parsed = json.loads(text)
        assert parsed["kind"] == "RebalanceReport" and parsed["sampled"]
        rc, text, _ = self._run(client, ["rebalance", "plan", "-o", "yaml"])
        assert rc == 0 and "kind: RebalanceReport" in text


class TestLiveDaemons:
    def test_fragment_defrag_rebind_score_drops(self):
        """The whole loop live: scheduler daemon + descheduler on one
        apiserver — fragment, defrag, the scheduler rebinds the
        replacements at their pins, measured fragmentation drops, and
        nothing is stranded or force-deleted."""
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.controllers.descheduler import Descheduler
        from kubernetes_tpu.scheduler.daemon import (
            BatchScheduler,
            SchedulerConfig,
        )

        api, client = _mk_api()
        n_pods = _fragment(client)
        client.create("pods", _pod_wire("waiting", cpu="500m"))
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync(timeout=60)
        sched = BatchScheduler(cfg)
        try:
            d = Descheduler(client, grace_period_seconds=0,
                            disruption_cap=8)
            out = d.sync_once()
            assert out["triggered"] and out["moves_executed"] > 0
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                sched.schedule_batch(timeout=0.2)
                pods = _list_pods(client)
                pending = [
                    p
                    for p in pods
                    if not p.spec.node_name
                    and p.status.phase not in ("Succeeded", "Failed")
                ]
                if not pending:
                    break
            pods = _list_pods(client)
            assert {p.metadata.name for p in pods} >= {
                f"p{k}" for k in range(n_pods)
            }, "a move stranded a pod"
            for p in pods:
                dest = (p.metadata.annotations or {}).get(
                    REBALANCE_DEST_ANNOTATION, ""
                )
                if dest:
                    assert p.spec.node_name == dest  # pin honored
            # Measured (not forecast) fragmentation dropped.
            from kubernetes_tpu.utils.capacity import cluster_columns

            nodes, _ = client.list("nodes")
            cols, _ = cluster_columns(nodes, pods)
            after = rebmod.fragment_score(
                cols, capmod.DEFAULT.probe_set()
            )
            assert after is not None and after < out["score_before"]
        finally:
            cfg.stop()

    def test_incremental_daemon_honors_dest_pin(self):
        """The INCREMENTAL daemon's own lowering honors the rebalance
        destination annotation as a soft pin (regression: only the
        one-shot build_snapshot staging did, so the micro-tick solver
        re-packed movers onto the very node the defrag cycle had just
        drained), and a vanished destination falls back to unpinned —
        the pod binds somewhere instead of stranding."""
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.scheduler.daemon import (
            IncrementalBatchScheduler,
            SchedulerConfig,
        )

        api, client = _mk_api()
        # n0 is empty (the packer's favorite); n1 carries 3000m of
        # 4000m — only the pin can route the mover there.
        client.create("nodes", _node_wire("n0", cpu="4", mem="8Gi"))
        client.create("nodes", _node_wire("n1", cpu="4", mem="8Gi"))
        _mk_bound(client, "ballast", "n1", cpu="3")
        pinned = _pod_wire("mover", cpu="500m")
        pinned["metadata"]["annotations"] = {
            REBALANCE_DEST_ANNOTATION: "n1"
        }
        ghost = _pod_wire("orphan", cpu="500m")
        ghost["metadata"]["annotations"] = {
            REBALANCE_DEST_ANNOTATION: "gone-node"
        }
        client.create("pods", pinned)
        client.create("pods", ghost)
        cfg = SchedulerConfig(Client(LocalTransport(api))).start()
        assert cfg.wait_for_sync(timeout=60)
        sched = IncrementalBatchScheduler(cfg)
        try:
            sched.start()
            deadline = time.monotonic() + 60
            mover = orphan = None
            while time.monotonic() < deadline:
                mover = client.get("pods", "mover", namespace="default")
                orphan = client.get("pods", "orphan", namespace="default")
                if mover.spec.node_name and orphan.spec.node_name:
                    break
                time.sleep(0.05)
            assert mover.spec.node_name == "n1"  # pin honored
            # Unknown dest -> unpinned, NOT infeasible: the orphan
            # still lands.
            assert orphan.spec.node_name in ("n0", "n1")
        finally:
            sched.stop()
            cfg.stop()


class TestOverheadGuard:
    """Planning must stay affordable for a periodic control loop:
    <5% of the bulk-churn drill's wall (the capacity/SLI bar)."""

    def test_plan_cost_under_5pct_of_bulk_churn(self):
        from kubernetes_tpu.client import Client, HTTPTransport
        from kubernetes_tpu.models import serde
        from kubernetes_tpu.models.objects import Pod
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        n_pods, batch = 2000, 500
        # Warm the plan compile out of the timed section (the
        # descheduler pays it once per process, not per cycle).
        names = [f"n{j}" for j in range(256)]
        cols = _cols(256, cpu_fit=600.0, pods_used=3.0)
        pods = []
        for k in range(64):
            p = serde.from_wire(Pod, _pod_wire(f"w{k}"))
            p.spec.node_name = names[k % 256]
            p.status.phase = "Running"
            pods.append(p)
        probes = [("probe-700m", 700.0, 256.0, 1)]
        assert rebmod.build_plan(cols, names, pods, probes) is not None

        api = APIServer()
        srv = APIHTTPServer(api, max_in_flight=800).start()
        try:
            client = Client(HTTPTransport(srv.address))
            stream = Client(HTTPTransport(srv.address)).watch(
                "pods", namespace="default"
            )
            seen = {"n": 0}

            def consume():
                while seen["n"] < 2 * n_pods:
                    ev = stream.next(timeout=10.0)
                    if ev is None:
                        if stream.closed:
                            return
                        continue
                    seen["n"] += 1

            watcher = threading.Thread(target=consume, daemon=True)
            t0 = time.perf_counter()
            watcher.start()
            for s in range(0, n_pods, batch):
                items = [
                    _pod_wire(f"reb-ov-{i}") for i in range(s, s + batch)
                ]
                res = client.create_bulk("pods", items, namespace="default")
                assert all(r.get("status") == "Success" for r in res)
            for s in range(0, n_pods, batch):
                client.delete_bulk(
                    "pods",
                    [f"reb-ov-{i}" for i in range(s, s + batch)],
                    namespace="default",
                )
            watcher.join(timeout=30)
            drill_wall = time.perf_counter() - t0
            stream.close()
            assert seen["n"] >= 2 * n_pods, seen
        finally:
            srv.stop()

        # One plan per drill batch (the descheduler plans at most once
        # per sync period). Best of three repeats.
        ticks = 2 * n_pods // batch
        cost = float("inf")
        for _repeat in range(3):
            t0 = time.perf_counter()
            for _ in range(ticks):
                rebmod.build_plan(cols, names, pods, probes)
            cost = min(cost, time.perf_counter() - t0)
        assert cost < 0.05 * drill_wall, (
            f"rebalance planning cost {cost:.4f}s is >=5% of the "
            f"{drill_wall:.4f}s bulk-churn drill"
        )
