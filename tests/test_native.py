"""Native kernel tests: C++ kernels vs NumPy fallback parity, and the
pause anchor binary (§2.14 deliverables)."""

import os
import signal
import subprocess
import time

import numpy as np
import pytest

from kubernetes_tpu import native


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.ensure_built():
        pytest.skip("native toolchain unavailable")


def _fallback_pack(id_lists, words):
    out = np.zeros((len(id_lists), words), dtype=np.uint32)
    for i, ids in enumerate(id_lists):
        for j in ids:
            out[i, j >> 5] |= np.uint32(1 << (j & 31))
    return out


class TestPackBitsets:
    def test_matches_fallback(self):
        rng = np.random.default_rng(0)
        id_lists = [
            list(rng.choice(96, size=rng.integers(0, 6), replace=False))
            for _ in range(500)
        ]
        got = native.pack_bitsets(id_lists, 3)
        want = _fallback_pack(id_lists, 3)
        assert np.array_equal(got, want)

    def test_empty(self):
        assert native.pack_bitsets([], 2).shape == (0, 2)


class TestGreedyFit:
    def test_matches_python_semantics(self):
        rng = np.random.default_rng(1)
        A, N = 2000, 50
        node_idx = rng.integers(-1, N, size=A).astype(np.int32)
        cpu = rng.choice([100, 500, 1000], size=A).astype(np.float32)
        mem = rng.choice([64, 256, 1024], size=A).astype(np.float32)
        cpu_cap = rng.choice([0, 4000, 8000], size=N).astype(np.float32)
        mem_cap = rng.choice([0, 8192, 16384], size=N).astype(np.float32)

        def run(use_native):
            cpu_fit = np.zeros(N, np.float32)
            mem_fit = np.zeros(N, np.float32)
            over = np.zeros(N, bool)
            cpu_used = np.zeros(N, np.float32)
            mem_used = np.zeros(N, np.float32)
            pods_used = np.zeros(N, np.float32)
            if use_native:
                native.greedy_fit(node_idx, cpu, mem, cpu_cap, mem_cap,
                                  cpu_fit, mem_fit, over, cpu_used,
                                  mem_used, pods_used)
            else:
                for i, j in enumerate(node_idx):
                    if j < 0:
                        continue
                    cpu_used[j] += cpu[i]
                    mem_used[j] += mem[i]
                    pods_used[j] += 1
                    fc = cpu_cap[j] == 0 or cpu_fit[j] + cpu[i] <= cpu_cap[j]
                    fm = mem_cap[j] == 0 or mem_fit[j] + mem[i] <= mem_cap[j]
                    if fc and fm:
                        cpu_fit[j] += cpu[i]
                        mem_fit[j] += mem[i]
                    else:
                        over[j] = True
            return cpu_fit, mem_fit, over, cpu_used, mem_used, pods_used

        for a, b in zip(run(True), run(False)):
            assert np.array_equal(a, b)


class TestOrRows:
    def test_matches_fallback(self):
        rng = np.random.default_rng(2)
        A, N, W = 300, 20, 2
        node_idx = rng.integers(-1, N, size=A).astype(np.int32)
        pod_rows = rng.integers(0, 2**32, size=(A, W), dtype=np.uint32)
        got = np.zeros((N, W), np.uint32)
        native.or_rows_by_index(node_idx, pod_rows, got)
        want = np.zeros((N, W), np.uint32)
        for i, j in enumerate(node_idx):
            if j >= 0:
                want[j] |= pod_rows[i]
        assert np.array_equal(got, want)


class TestPause:
    def test_runs_and_terminates_cleanly(self):
        subprocess.run(
            ["make", "-C", os.path.join(os.path.dirname(native.__file__),
                                        "..", "..", "native"), "pause"],
            check=True, capture_output=True,
        )
        path = native.pause_binary()
        assert path is not None
        proc = subprocess.Popen([path])
        time.sleep(0.2)
        assert proc.poll() is None  # parked in pause(2)
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=5) == 0  # clean exit on TERM


class TestSnapshotUsesNative:
    def test_build_snapshot_parity_native_vs_fallback(self, monkeypatch):
        """build_snapshot must produce identical columns with and
        without the native lib."""
        from __graft_entry__ import _synthetic_objects
        from kubernetes_tpu.models.columnar import build_snapshot

        pods, nodes, services = _synthetic_objects(300, 40, seed=5)
        for p in pods[:150]:  # make some assigned
            p.spec.node_name = nodes[hash(p.metadata.name) % 40].metadata.name
        assigned, pending = pods[:150], pods[150:]
        with_native = build_snapshot(pending, nodes, assigned, services)
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_attempted", True)
        without = build_snapshot(pending, nodes, assigned, services)
        for field in ("cpu_cap", "cpu_fit_used", "mem_fit_used", "overcommitted",
                      "cpu_used", "mem_used", "pods_used", "used_port_bits",
                      "used_vol_any_bits", "used_vol_rw_bits"):
            assert np.array_equal(
                getattr(with_native.nodes, field), getattr(without.nodes, field)
            ), field
        for field in ("port_bits", "vol_any_bits", "vol_rw_bits"):
            assert np.array_equal(
                getattr(with_native.pods, field), getattr(without.pods, field)
            ), field
