"""Websocket watch, chaos-injected transports, and concurrency stress.

Reference: pkg/apiserver/watch.go:45-102 (websocket watch transport),
pkg/client/chaosclient/chaosclient.go (fault injection), and the Go
-race discipline (hack/test-go.sh KUBE_RACE) whose analog here is
hammering the threaded daemons from many writers (VERDICT r1 A2)."""

import json
import threading
import time
import urllib.parse

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.client.cache import Informer, Reflector
from kubernetes_tpu.client.chaos import ChaosPolicy, ChaosTransport
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Pod
from kubernetes_tpu.server.api import APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer
from kubernetes_tpu.utils.websocket import WebSocketClient


def wait_until(cond, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def pod_wire(name, ns="default"):
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {"containers": [{"name": "c", "image": "x"}]},
    }


class TestWebsocketWatch:
    @pytest.fixture
    def server(self):
        srv = APIHTTPServer(APIServer()).start()
        yield srv
        srv.stop()

    def test_watch_over_websocket(self, server):
        client = Client(LocalTransport(server.api))
        host, port = urllib.parse.urlparse(server.address).netloc.split(":")
        ws = WebSocketClient(
            host, int(port), "/api/v1/watch/namespaces/default/pods"
        )
        try:
            client.create("pods", pod_wire("w1"), namespace="default")
            frame = json.loads(ws.recv_text())
            assert frame["type"] == "ADDED"
            assert frame["object"]["metadata"]["name"] == "w1"
            client.delete("pods", "w1", namespace="default")
            types = [frame["type"]]
            while types[-1] != "DELETED":
                types.append(json.loads(ws.recv_text())["type"])
            assert "DELETED" in types
        finally:
            ws.close()

    def test_websocket_v1beta3_frames_convert(self, server):
        client = Client(LocalTransport(server.api))
        host, port = urllib.parse.urlparse(server.address).netloc.split(":")
        ws = WebSocketClient(
            host, int(port), "/api/v1beta3/watch/namespaces/default/pods"
        )
        try:
            wire = pod_wire("legacy-ws")
            wire["spec"]["nodeName"] = "n7"
            client.create("pods", wire, namespace="default")
            frame = json.loads(ws.recv_text())
            assert frame["object"]["spec"]["host"] == "n7"
            assert "nodeName" not in frame["object"]["spec"]
        finally:
            ws.close()

    def test_chunked_watch_still_works(self, server):
        """The default (no upgrade header) path stays chunked JSON."""
        import http.client

        client = Client(LocalTransport(server.api))
        host, port = urllib.parse.urlparse(server.address).netloc.split(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10)
        conn.request("GET", "/api/v1/watch/namespaces/default/pods")
        resp = conn.getresponse()
        assert resp.status == 200
        client.create("pods", pod_wire("c1"), namespace="default")
        line = resp.readline()
        assert json.loads(line)["type"] == "ADDED"
        conn.close()


def _decode_pod(wire):
    return serde.from_wire(Pod, wire)


class TestChaosClient:
    def test_informer_converges_through_injected_failures(self):
        """Retry/backoff must absorb a burst of transport failures —
        the chaosclient's whole reason to exist."""
        api = APIServer()
        healthy = Client(LocalTransport(api))
        for i in range(5):
            healthy.create("pods", pod_wire(f"pre{i}"), namespace="default")

        policy = ChaosPolicy(
            seed=7, p_error=0.3, p_network=0.3, max_failures=8
        )
        chaotic = Client(ChaosTransport(LocalTransport(api), policy))
        informer = Informer(chaotic, "pods", decode=_decode_pod)
        informer.start()
        try:
            assert wait_until(
                lambda: len(informer.store.list()) == 5, timeout=15
            ), f"informer never converged (failures={policy.failures})"
            assert policy.failures > 0, "chaos injected nothing"
            # Still tracks new objects after the failure burst (allow
            # for the reflector riding out its capped 5s backoff).
            healthy.create("pods", pod_wire("post"), namespace="default")
            assert wait_until(
                lambda: len(informer.store.list()) == 6, timeout=20
            )
        finally:
            informer.stop()

    def test_policy_budget(self):
        policy = ChaosPolicy(seed=1, p_error=1.0, max_failures=3)
        failures = 0
        for _ in range(10):
            try:
                policy.act()
            except Exception:
                failures += 1
        assert failures == 3  # budget exhausted, then passthrough


class TestConcurrencyStress:
    def test_many_writers_one_truth(self):
        """8 writer threads churn pods against the apiserver while an
        informer watches; the cache must converge exactly to the store
        with no deadlock or lost events."""
        api = APIServer()
        informer = Informer(
            Client(LocalTransport(api)), "pods", decode=_decode_pod
        )
        informer.start()
        informer.wait_for_sync()
        errors = []

        def writer(tid):
            c = Client(LocalTransport(api))
            try:
                for i in range(30):
                    name = f"stress-{tid}-{i}"
                    c.create("pods", pod_wire(name), namespace="default")
                    if i % 3 == 0:
                        c.delete("pods", name, namespace="default")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=writer, args=(t,)) for t in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        expected = {
            p.metadata.name
            for p in (
                Client(LocalTransport(api)).list("pods", namespace="default")
            )[0]
        }
        assert len(expected) == 8 * 20  # 30 created, every 3rd deleted
        assert wait_until(
            lambda: {
                p.metadata.name for p in informer.store.list()
            } == expected,
            timeout=10,
        )
        informer.stop()

    def test_watch_survives_server_restart(self):
        """Reflector relists after the HTTP server dies and a new one
        takes over the SAME store (apiserver restart drill)."""
        api = APIServer()
        srv = APIHTTPServer(api).start()
        from kubernetes_tpu.client.rest import HTTPTransport

        client = Client(HTTPTransport(srv.address))
        client.create("pods", pod_wire("stay"), namespace="default")
        informer = Informer(client, "pods", decode=_decode_pod)
        informer.start()
        assert wait_until(lambda: len(informer.store.list()) == 1)

        host, port = urllib.parse.urlparse(srv.address).netloc.split(":")
        srv.stop(release_store=False)  # state survives the listener
        # New server, same API state, same port.
        srv2 = APIHTTPServer(api, host=host, port=int(port)).start()
        try:
            Client(LocalTransport(api)).create(
                "pods", pod_wire("after-restart"), namespace="default"
            )
            assert wait_until(
                lambda: len(informer.store.list()) == 2, timeout=15
            ), "informer never recovered after apiserver restart"
        finally:
            informer.stop()
            srv2.stop()
