"""API server tests over both transports (reference: pkg/apiserver/,
pkg/registry/pod/etcd/etcd_test.go binding tests)."""

import pytest

from kubernetes_tpu.client import Client, HTTPTransport, LocalTransport
from kubernetes_tpu.server import APIError, APIServer
from kubernetes_tpu.server.httpserver import APIHTTPServer


def pod_wire(name, ns="default", node="", labels=None):
    return {
        "kind": "Pod",
        "apiVersion": "v1",
        "metadata": {"name": name, "namespace": ns, "labels": labels or {}},
        "spec": {
            "containers": [{"name": "c", "image": "nginx"}],
            **({"nodeName": node} if node else {}),
        },
    }


def node_wire(name):
    return {
        "kind": "Node",
        "apiVersion": "v1",
        "metadata": {"name": name},
        "status": {"capacity": {"cpu": "4", "memory": "8Gi"}},
    }


@pytest.fixture(params=["local", "http"])
def client(request):
    api = APIServer()
    if request.param == "local":
        yield Client(LocalTransport(api))
    else:
        server = APIHTTPServer(api).start()
        yield Client(HTTPTransport(server.address))
        server.stop()


class TestCRUD:
    def test_create_get_defaults(self, client):
        created = client.create("pods", pod_wire("p1"))
        assert created.metadata.uid
        assert created.metadata.creation_timestamp
        assert created.metadata.resource_version
        got = client.get("pods", "p1", namespace="default")
        assert got.metadata.name == "p1"

    def test_create_duplicate_conflict(self, client):
        client.create("pods", pod_wire("p1"))
        with pytest.raises(APIError) as e:
            client.create("pods", pod_wire("p1"))
        assert e.value.code == 409

    def test_create_invalid_422(self, client):
        bad = pod_wire("p1")
        bad["spec"]["containers"] = []
        with pytest.raises(APIError) as e:
            client.create("pods", bad)
        assert e.value.code == 422

    def test_get_missing_404(self, client):
        with pytest.raises(APIError) as e:
            client.get("pods", "nope", namespace="default")
        assert e.value.code == 404

    def test_list_with_selectors(self, client):
        client.create("pods", pod_wire("a", labels={"app": "web"}))
        client.create("pods", pod_wire("b", labels={"app": "db"}))
        client.create("pods", pod_wire("c", labels={"app": "web"}, node="n1"))
        items, version = client.list("pods", namespace="default")
        assert {p.metadata.name for p in items} == {"a", "b", "c"}
        assert version > 0
        items, _ = client.list("pods", namespace="default", label_selector="app=web")
        assert {p.metadata.name for p in items} == {"a", "c"}
        items, _ = client.list(
            "pods", namespace="default", field_selector="spec.nodeName="
        )
        assert {p.metadata.name for p in items} == {"a", "b"}

    def test_update_and_cas(self, client):
        client.create("pods", pod_wire("p1"))
        got = client.get("pods", "p1", namespace="default")
        got.metadata.labels = {"v": "2"}
        updated = client.update("pods", got, namespace="default")
        assert updated.metadata.labels == {"v": "2"}
        # Stale resourceVersion -> 409.
        got.metadata.labels = {"v": "3"}
        with pytest.raises(APIError) as e:
            client.update("pods", got, namespace="default")
        assert e.value.code == 409

    def test_delete(self, client):
        client.create("pods", pod_wire("p1"))
        client.delete("pods", "p1", namespace="default")
        with pytest.raises(APIError):
            client.get("pods", "p1", namespace="default")

    def test_cluster_scoped_nodes(self, client):
        client.create("nodes", node_wire("n1"))
        got = client.get("nodes", "n1")
        assert got.status.capacity["cpu"].milli_value() == 4000
        items, _ = client.list("nodes")
        assert [n.metadata.name for n in items] == ["n1"]

    def test_update_status_preserves_spec(self, client):
        client.create("pods", pod_wire("p1"))
        got = client.get("pods", "p1", namespace="default")
        got.status.phase = "Running"
        out = client.update_status("pods", got, namespace="default")
        assert out.status.phase == "Running"
        assert out.spec.containers[0].image == "nginx"


class TestBinding:
    def test_bind_sets_node_name(self, client):
        client.create("pods", pod_wire("p1"))
        client.bind("p1", "n1", namespace="default")
        got = client.get("pods", "p1", namespace="default")
        assert got.spec.node_name == "n1"

    def test_bind_twice_conflict(self, client):
        """The guarded write: nodeName set iff empty
        (pkg/registry/pod/etcd/etcd.go:140-167)."""
        client.create("pods", pod_wire("p1"))
        client.bind("p1", "n1", namespace="default")
        with pytest.raises(APIError) as e:
            client.bind("p1", "n2", namespace="default")
        assert e.value.code == 409
        assert client.get("pods", "p1", namespace="default").spec.node_name == "n1"

    def test_bind_missing_pod(self, client):
        with pytest.raises(APIError) as e:
            client.bind("ghost", "n1", namespace="default")
        assert e.value.code == 404


class TestBulkBindings:
    """bind_bulk semantics on both transports: the default per-item
    mode (partial failure isolated) and atomic=True (gang commit:
    reject-all on first conflict, nothing applied)."""

    def test_partial_mode_isolates_failures(self, client):
        client.create("pods", pod_wire("ok1"))
        client.create("pods", pod_wire("ok2"))
        client.create("pods", pod_wire("taken", node="n9"))
        results = client.bind_bulk(
            [("ok1", "n1"), ("taken", "n1"), ("ghost", "n1"), ("ok2", "n2")]
        )
        assert [r.get("status") for r in results] == [
            "Success", "Failure", "Failure", "Success",
        ]
        assert results[1]["code"] == 409
        assert results[2]["code"] == 404
        # No pod is ever half-bound: each either has its full target
        # nodeName or is untouched.
        assert client.get("pods", "ok1", namespace="default").spec.node_name == "n1"
        assert client.get("pods", "ok2", namespace="default").spec.node_name == "n2"
        assert client.get("pods", "taken", namespace="default").spec.node_name == "n9"

    def test_atomic_mode_rejects_all_on_conflict(self, client):
        client.create("pods", pod_wire("g1"))
        client.create("pods", pod_wire("g2", node="n9"))  # conflicts
        client.create("pods", pod_wire("g3"))
        results = client.bind_bulk(
            [("g1", "n1"), ("g2", "n1"), ("g3", "n2")], atomic=True
        )
        assert all(r.get("status") == "Failure" for r in results)
        # The conflicting item carries its real error; the rest abort.
        assert results[1]["code"] == 409 and results[1]["reason"] == "Conflict"
        assert results[0]["reason"] == "Aborted"
        assert results[2]["reason"] == "Aborted"
        # NOTHING was applied — the earlier-in-batch g1 stayed unbound.
        assert not client.get("pods", "g1", namespace="default").spec.node_name
        assert not client.get("pods", "g3", namespace="default").spec.node_name
        assert client.get("pods", "g2", namespace="default").spec.node_name == "n9"

    def test_atomic_mode_missing_pod_aborts_all(self, client):
        client.create("pods", pod_wire("g1"))
        results = client.bind_bulk(
            [("g1", "n1"), ("ghost", "n1")], atomic=True
        )
        assert results[0]["reason"] == "Aborted"
        assert results[1]["code"] == 404
        assert not client.get("pods", "g1", namespace="default").spec.node_name

    def test_atomic_mode_success_binds_all(self, client):
        client.create("pods", pod_wire("g1"))
        client.create("pods", pod_wire("g2"))
        results = client.bind_bulk(
            [("g1", "n1"), ("g2", "n2")], atomic=True
        )
        assert all(r.get("status") == "Success" for r in results)
        assert client.get("pods", "g1", namespace="default").spec.node_name == "n1"
        assert client.get("pods", "g2", namespace="default").spec.node_name == "n2"

    def test_atomic_mode_malformed_binding_aborts_before_store(self, client):
        client.create("pods", pod_wire("g1"))
        # Raw body path: one binding lacks a target name.
        results = client.t.request(
            "POST", "bind_bulk", ("default",),
            {
                "atomic": True,
                "bindings": [
                    {"metadata": {"name": "g1"}, "target": {"name": "n1"}},
                    {"metadata": {"name": "g1"}, "target": {}},
                ],
            },
        )
        if isinstance(results, dict):
            results = results["results"]
        assert results[0]["reason"] == "Aborted"
        assert results[1]["code"] == 400
        assert not client.get("pods", "g1", namespace="default").spec.node_name

    def test_atomic_watch_sees_no_rolled_back_binding(self, client):
        """Check-then-commit means a watcher never observes a binding
        that is later undone by the atomic abort."""
        client.create("pods", pod_wire("w1"))
        client.create("pods", pod_wire("w2", node="n9"))
        _, version = client.list("pods", namespace="default")
        stream = client.watch("pods", namespace="default", since=version)
        client.bind_bulk([("w1", "n1"), ("w2", "n1")], atomic=True)
        client.create("pods", pod_wire("sentinel"))
        seen = []
        while True:
            ev = stream.next(timeout=2)
            if ev is None:
                break
            seen.append(ev)
            if ev.object.get("metadata", {}).get("name") == "sentinel":
                break
        stream.close()
        assert all(
            not (ev.object.get("spec") or {}).get("nodeName")
            for ev in seen
            if ev.object.get("metadata", {}).get("name") == "w1"
        )
        assert any(
            ev.object.get("metadata", {}).get("name") == "sentinel"
            for ev in seen
        )


class TestWatch:
    def test_watch_stream(self, client):
        items, version = client.list("pods", namespace="default")
        stream = client.watch("pods", namespace="default", since=version)
        client.create("pods", pod_wire("w1"))
        client.bind("w1", "n1", namespace="default")
        ev1 = stream.next(timeout=2)
        ev2 = stream.next(timeout=2)
        assert ev1.type == "ADDED" and ev1.object["metadata"]["name"] == "w1"
        assert ev2.type == "MODIFIED"
        assert ev2.object["spec"]["nodeName"] == "n1"
        stream.close()

    def test_watch_field_selector_unassigned(self, client):
        """The scheduler's unassigned-pod watch (factory.go:226)."""
        _, version = client.list("pods", namespace="default")
        stream = client.watch(
            "pods", namespace="default", since=version, field_selector="spec.nodeName="
        )
        client.create("pods", pod_wire("u1"))
        client.create("pods", pod_wire("a1", node="n1"))
        ev = stream.next(timeout=2)
        assert ev.object["metadata"]["name"] == "u1"
        ev = stream.next(timeout=0.3)
        assert ev is None  # assigned pod filtered out
        stream.close()


def test_events_ttl_resource():
    import time as _time

    api = APIServer()
    client = Client(LocalTransport(api))
    # Recording is async through the broadcaster now: poll for arrival.
    client.record_event(pod_wire("p1"), "Scheduled", "ok", source="test")
    deadline = _time.monotonic() + 5
    items = []
    while _time.monotonic() < deadline:
        items, _ = client.list("events", namespace="default")
        if items:
            break
        _time.sleep(0.02)
    assert len(items) == 1
    assert items[0].reason == "Scheduled"


def test_healthz_metrics_version():
    import json
    import urllib.request

    api = APIServer()
    server = APIHTTPServer(api).start()
    try:
        base = server.address
        health = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert health["status"] == "ok"
        assert set(health["checks"]) == {
            "kvstore", "watchHub", "flightRecorder",
        }
        v = json.loads(urllib.request.urlopen(base + "/version").read())
        assert v["platform"] == "tpu"
        # Generate one request then check it shows up in metrics.
        Client(HTTPTransport(base)).create("pods", pod_wire("m1"))
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "apiserver_request_count" in text
    finally:
        server.stop()


class TestRegressionsFromReview:
    def test_default_namespace_symmetry(self):
        """create with empty ns must be reachable via get/update/delete
        with empty ns."""
        api = APIServer()
        c = Client(LocalTransport(api))
        c.create("pods", pod_wire("p1", ns=""))
        got = c.get("pods", "p1")
        assert got.metadata.namespace == "default"
        got.metadata.labels = {"a": "b"}
        c.update("pods", got)
        c.update_status("pods", got)
        c.delete("pods", "p1")

    def test_watch_event_mutation_does_not_corrupt_store(self):
        api = APIServer()
        c = Client(LocalTransport(api))
        c.create("pods", pod_wire("p1"))
        w = api.watch("pods", "default")
        c.bind("p1", "n1", namespace="default")
        ev = w.next(timeout=1)
        ev.object["spec"]["nodeName"] = "CORRUPTED"
        assert api.get("pods", "default", "p1")["spec"]["nodeName"] == "n1"
        w.close()

    def test_closed_watchers_pruned(self):
        import time

        api = APIServer()
        base = len(api.store._watchers)
        for _ in range(5):
            api.watch("pods", "default").close()
        api.store.create("/prune-trigger", {"metadata": {"name": "x"}})
        # Fan-out (and thus pruning) rides the dispatcher thread now.
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if len(api.store._watchers) == base:
                break
            time.sleep(0.01)
        assert len(api.store._watchers) == base

    def test_cluster_scoped_status_subresource_over_http(self):
        """PUT /api/v1/nodes/{name}/status — the kubelet heartbeat
        write. The router only handled the namespaced form, so every
        HTTP kubelet's heartbeat 404'd (silently, the kubelet swallows
        APIError) and the node controller evicted the whole cluster
        after the grace period."""
        import json as jsonmod
        import urllib.request

        api = APIServer()
        server = APIHTTPServer(api).start()
        try:
            api.create("nodes", "", {"metadata": {"name": "hb-n1"}})
            node = api.get("nodes", "", "hb-n1")
            node["status"] = {
                "conditions": [{"type": "Ready", "status": "True"}]
            }
            req = urllib.request.Request(
                server.address + "/api/v1/nodes/hb-n1/status",
                data=jsonmod.dumps(node).encode(),
                method="PUT",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req) as resp:
                assert resp.status == 200
            got = api.get("nodes", "", "hb-n1")
            assert got["status"]["conditions"][0]["status"] == "True"
        finally:
            server.stop()

    def test_watch_bad_resource_version_400(self):
        import urllib.error
        import urllib.request

        api = APIServer()
        server = APIHTTPServer(api).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(
                    server.address + "/api/v1/watch/pods?resourceVersion=abc"
                )
            assert e.value.code == 400
        finally:
            server.stop()

    def test_node_capacity_rounds_down(self):
        from kubernetes_tpu.models.columnar import build_snapshot
        from kubernetes_tpu.models.objects import Node, NodeStatus, ObjectMeta
        from kubernetes_tpu.models.quantity import parse_quantity

        node = Node(
            metadata=ObjectMeta(name="n"),
            status=NodeStatus(
                capacity={"memory": parse_quantity("100.5Mi"), "cpu": parse_quantity("1")}
            ),
        )
        snap = build_snapshot([], [node])
        assert snap.nodes.mem_cap[0] == 100  # floor, not ceil


class TestPatch:
    """PATCH verb: JSON merge patch (resthandler.go:446, RFC 7386)."""

    def _setup(self):
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        api = APIServer()
        client = Client(LocalTransport(api))
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {
                    "name": "p1",
                    "namespace": "default",
                    "labels": {"app": "web", "tier": "fe"},
                },
                "spec": {"containers": [{"name": "c", "image": "v1"}]},
            },
            namespace="default",
        )
        return api, client

    def test_merge_labels_and_null_delete(self):
        api, client = self._setup()
        out = client.patch(
            "pods",
            "p1",
            {"metadata": {"labels": {"app": "web2", "tier": None, "x": "1"}}},
            namespace="default",
        )
        assert out.metadata.labels == {"app": "web2", "x": "1"}
        assert out.spec.containers[0].image == "v1"  # untouched

    def test_lists_replace_not_merge(self):
        api, client = self._setup()
        out = client.patch(
            "pods",
            "p1",
            {"spec": {"containers": [{"name": "c", "image": "v2"}]}},
            namespace="default",
        )
        assert out.spec.containers[0].image == "v2"

    def test_identity_fields_ignored(self):
        api, client = self._setup()
        out = client.patch(
            "pods",
            "p1",
            {"metadata": {"name": "evil", "labels": {"y": "2"}}},
            namespace="default",
        )
        assert out.metadata.name == "p1"
        assert out.metadata.labels["y"] == "2"

    def test_patch_missing_object_404(self):
        import pytest as _pytest

        from kubernetes_tpu.server.api import APIError

        api, client = self._setup()
        with _pytest.raises(APIError) as e:
            client.patch("pods", "ghost", {"metadata": {}}, namespace="default")
        assert e.value.code == 404

    def test_patch_over_http(self):
        import json as _json
        import urllib.request

        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api, client = self._setup()
        srv = APIHTTPServer(api).start()
        try:
            req = urllib.request.Request(
                srv.address + "/api/v1/namespaces/default/pods/p1",
                method="PATCH",
                data=_json.dumps(
                    {"metadata": {"labels": {"patched": "yes"}}}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            out = _json.loads(urllib.request.urlopen(req).read())
            assert out["metadata"]["labels"]["patched"] == "yes"
        finally:
            srv.stop()


class TestHighLatencyGate:
    def test_detects_and_exempts(self):
        """HighLatencyRequests analog (test/e2e/util.go:1286): slow
        plain verbs are reported; long-running subresources (watch,
        proxy, exec, log) are exempt. Uses a private summary so the
        process-global registry (asserted clean by the density SLO
        gate) stays unpolluted."""
        from kubernetes_tpu.server.httpserver import high_latency_requests
        from kubernetes_tpu.utils import metrics

        summary = metrics.Summary(
            "test_latency_gate_seconds", "test", ("verb", "resource")
        )
        for _ in range(5):
            summary.observe(3.0, verb="GET", resource="slowthings")
            summary.observe(30.0, verb="GET", resource="slowthings/watch")
            summary.observe(30.0, verb="GET", resource="slowthings/proxy")
            summary.observe(0.01, verb="GET", resource="fastthings")
        slow = high_latency_requests(threshold=1.0, summary=summary)
        assert slow == [("GET", "slowthings", 3.0)]


class TestStaleKeepAliveReplay:
    """Replay policy on a reused keep-alive connection that dies at
    the read (RemoteDisconnected): idempotent verbs retry on a fresh
    connection; POST must NOT silently replay — the server may have
    applied the create before dying, and a replay would double-apply
    (surfacing a spurious 409 to a caller whose create succeeded).
    Matches urllib3 / Go net/http, which only auto-retry idempotent
    or body-less requests here."""

    @staticmethod
    def _flaky_server(die_after: int):
        """Socket server: serves `die_after` keep-alive requests with
        200s, then closes the connection after reading the next
        request without responding. Subsequent connections serve
        normally. Returns (port, served_list, stop)."""
        import socket
        import threading

        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        port = srv.getsockname()[1]
        served = []
        stopped = threading.Event()

        def read_request(conn) -> bytes:
            data = b""
            while b"\r\n\r\n" not in data:
                chunk = conn.recv(65536)
                if not chunk:
                    return b""
                data += chunk
            head, _, rest = data.partition(b"\r\n\r\n")
            clen = 0
            for line in head.split(b"\r\n"):
                if line.lower().startswith(b"content-length:"):
                    clen = int(line.split(b":")[1])
            while len(rest) < clen:
                rest += conn.recv(65536)
            return head

        def handle(conn):
            n = 0
            with conn:
                while not stopped.is_set():
                    head = read_request(conn)
                    if not head:
                        return
                    if n >= die_after:
                        served.append(b"DIED " + head.split(b"\r\n")[0])
                        conn.shutdown(socket.SHUT_RDWR)
                        return  # clean close, zero response bytes
                    served.append(head.split(b"\r\n")[0])
                    body = b"{}"
                    conn.sendall(
                        b"HTTP/1.1 200 OK\r\nContent-Type: application/json\r\n"
                        b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
                    )
                    n += 1

        def accept_loop():
            while not stopped.is_set():
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return
                threading.Thread(target=handle, args=(conn,), daemon=True).start()

        threading.Thread(target=accept_loop, daemon=True).start()

        def stop():
            stopped.set()
            srv.close()

        return port, served, stop

    def test_get_replays_on_fresh_connection(self):
        port, served, stop = self._flaky_server(die_after=1)
        try:
            t = HTTPTransport(f"http://127.0.0.1:{port}")
            t._do("GET", "/api/v1beta1/pods")  # pooled
            out = t._do("GET", "/api/v1beta1/pods")  # dies, replays
            assert out == {}
            assert sum(1 for s in served if s.startswith(b"DIED")) == 1
        finally:
            stop()

    def test_post_raises_unknown_outcome(self):
        from kubernetes_tpu.client.rest import UnknownOutcomeError

        port, served, stop = self._flaky_server(die_after=1)
        try:
            t = HTTPTransport(f"http://127.0.0.1:{port}")
            t._do("GET", "/api/v1beta1/pods")  # pooled
            with pytest.raises(UnknownOutcomeError, match="outcome unknown"):
                t._do("POST", "/api/v1beta1/pods", body={"kind": "Pod"})
            # The mutation was sent exactly once — never replayed.
            posts = [s for s in served if b"POST" in s]
            assert len(posts) == 1
        finally:
            stop()


def test_serialized_transport_one_connection_many_threads():
    """HTTPTransport(serialize=True): one shared keep-alive connection,
    requests serialized behind a lock — the kubelet's transport shape
    at 100-node scale (one connection per daemon, not per thread)."""
    import threading

    from kubernetes_tpu.client import Client, HTTPTransport

    api = APIServer()
    server = APIHTTPServer(api).start()
    try:
        t = HTTPTransport(server.address, serialize=True)
        client = Client(t)
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {"name": "ser-p", "namespace": "default"},
                "spec": {"containers": [{"name": "c", "image": "i"}]},
            },
        )
        errors = []

        def worker():
            try:
                for _ in range(10):
                    client.get("pods", "ser-p", namespace="default")
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        conn_before = t._shared_conn
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=10)
        assert not errors, errors
        # Same connection object throughout: per-thread conns would
        # populate thread-locals instead, and a reconnect would rebind.
        assert t._shared_conn is conn_before
        assert getattr(t._local, "conn", None) is None
    finally:
        server.stop()
