"""Sinkhorn-matched wave solver: validity, congestion-priced batching
(fewer waves than the plain wave solver), determinism, mesh execution.

The mode exists for the north star's "Hungarian/Sinkhorn matching"
framing (BASELINE.json): entropic assignment with capacity-capped
column prices steering each wave's choices. Placement VALIDITY is
non-negotiable and checked with the same replay as the wave tests."""

import numpy as np
import pytest

from kubernetes_tpu.models.columnar import build_snapshot
from kubernetes_tpu.ops import device_snapshot
from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments, solve_sinkhorn
from kubernetes_tpu.ops.wave import wave_assignments
from test_solver_parity import mk_node, mk_pod, random_cluster
from test_wave import check_validity


class TestSinkhornValidity:
    @pytest.mark.parametrize("seed", range(8))
    def test_fuzz_placements_valid(self, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        snap = build_snapshot(pods, nodes, assigned, services)
        d = device_snapshot(snap)
        assign, _ = sinkhorn_assignments(d, window=32)
        check_validity(snap, assign)

    def test_capacity_stress_places_exactly_what_fits(self):
        pods = [mk_pod(f"p{i}", cpu=600, mem_mib=64) for i in range(10)]
        nodes = [mk_node(f"n{j}", cpu=1000) for j in range(3)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        assign, _ = sinkhorn_assignments(d, window=8)
        check_validity(snap, assign)
        assert (assign >= 0).sum() == 3

    def test_host_port_conflicts_respected(self):
        pods = [mk_pod(f"hp{i}", host_port=8080) for i in range(4)]
        nodes = [mk_node("n0"), mk_node("n1")]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        assign, _ = sinkhorn_assignments(d, window=4)
        check_validity(snap, assign)
        assert (assign >= 0).sum() == 2

    def test_places_everything_when_capacity_allows(self):
        pods = [mk_pod(f"p{i}", cpu=100, mem_mib=64) for i in range(64)]
        nodes = [mk_node(f"n{j}", cpu=8000, mem_mib=8192) for j in range(8)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        assign, _ = sinkhorn_assignments(d, window=64)
        check_validity(snap, assign)
        assert (assign >= 0).sum() == 64

    def test_deterministic(self):
        pods, nodes, assigned, services = random_cluster(3)
        snap = build_snapshot(pods, nodes, assigned, services)
        d = device_snapshot(snap)
        a1, _ = sinkhorn_assignments(d, window=16)
        a2, _ = sinkhorn_assignments(d, window=16)
        assert (a1 == a2).all()


class TestCongestionPricing:
    def test_fewer_waves_than_plain_wave(self):
        """The mode's reason to exist: prices meter demand to capacity,
        so one wave lands many more pods than argmax + per-node-limit
        packing. Uniform fleet, everything fits."""
        pods = [
            mk_pod(f"p{i}", cpu=100 + 50 * (i % 4), mem_mib=64)
            for i in range(128)
        ]
        nodes = [mk_node(f"n{j}", cpu=8000, mem_mib=8192) for j in range(8)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        wave_a, wave_count = wave_assignments(d, window=128)
        sk_a, sk_count = sinkhorn_assignments(d, window=128)
        check_validity(snap, sk_a)
        assert (sk_a >= 0).sum() == 128
        assert (wave_a >= 0).sum() == 128
        assert sk_count < wave_count, (sk_count, wave_count)

    def test_prices_spread_load_across_equal_nodes(self):
        """With identical nodes and small pods, the settled placement
        should not pile onto a few nodes (balance, not just speed)."""
        pods = [mk_pod(f"p{i}", cpu=100, mem_mib=64) for i in range(64)]
        nodes = [mk_node(f"n{j}", cpu=16000, mem_mib=16384) for j in range(8)]
        snap = build_snapshot(pods, nodes)
        d = device_snapshot(snap)
        assign, _ = sinkhorn_assignments(d, window=64)
        counts = np.bincount(assign[assign >= 0], minlength=8)
        # Perfect balance is 8 per node; demand no node exceeds 2x it.
        assert counts.max() <= 16, counts

    def test_zero_capacity_nodes_priced_out(self):
        full = mk_node("full", pods=0)
        open_ = mk_node("open", pods=10)
        pods = [mk_pod(f"p{i}", cpu=10, mem_mib=8) for i in range(4)]
        snap = build_snapshot(pods, [full, open_])
        d = device_snapshot(snap)
        assign, _ = sinkhorn_assignments(d, window=4)
        check_validity(snap, assign)
        assert set(assign[assign >= 0]) == {1}


class TestSinkhornOnMesh:
    def test_sharded_matches_single_device(self, host_mesh):
        pods, nodes, assigned, services = random_cluster(5)
        snap = build_snapshot(pods, nodes, assigned, services)
        single = device_snapshot(snap)
        base, _ = sinkhorn_assignments(single, window=16)

        mesh = host_mesh(8)
        sharded = device_snapshot(snap, mesh=mesh, pad_to=8)
        with mesh:
            out, _ = solve_sinkhorn(sharded.pods, sharded.nodes, window=16)
            out.block_until_ready()
        a = np.asarray(out)[: sharded.n_pods]
        a = np.where(a >= sharded.n_nodes, -1, a)
        assert (a == base).all()
