"""ktmesh — static SPMD partitioning analyzer tests.

Four layers, mirroring the pass itself:

- KT009 fixtures: the AST half (mesh hygiene in ops/) on violating /
  passing / pragma'd snippets.
- Contract-surface units: symbolic PartitionSpecs, the HLO collective
  inventory walker, and the runtime COMM verdict — pure functions, no
  lowering.
- Drift injection: doctored contracts through the real partitioned
  lowering (tightened budget, phantom declared kind, replication that
  vanishes a declared collective, a deliberately mis-sharded wave
  solver that full-gathers the pod axis, coupling-class lies).
- The gates: the live tree must analyze clean in-process on conftest's
  8 forced devices, the CLI must round-trip JSON, and <2 devices must
  degrade to 'skipped' + exit 0 in a subprocess.
"""

import dataclasses
import json
import os
import pathlib
import subprocess
import sys
import textwrap

import pytest

from kubernetes_tpu.ops import contracts as C
from tools import ktlint
from tools.ktlint import ktmesh

pytestmark = pytest.mark.ktmesh

REPO = pathlib.Path(__file__).resolve().parent.parent

GANG = "matrices.gang_member_counts"


def _resharded(name, **changes):
    """CONTRACTS[name] with its sharding leaf fields replaced."""
    c = C.CONTRACTS[name]
    return dataclasses.replace(
        c, sharding=dataclasses.replace(c.sharding, **changes)
    )


def _check(name, contract):
    meta = {}
    findings = ktmesh.check_kernel(name, contract, 8, meta=meta)
    return findings, meta


# -- KT009: the AST half ------------------------------------------------


def _lint(tmp_path, source, filename="mod.py"):
    opsdir = tmp_path / "ops"
    opsdir.mkdir(exist_ok=True)
    f = opsdir / filename
    f.write_text(textwrap.dedent(source))
    return ktlint.lint([f], select=["KT009"], baseline_path=None)


class TestKT009Fixtures:
    def test_device_put_without_sharding_flagged(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax

            def stage(x):
                return jax.device_put(x)
            """,
        )
        assert len(report.findings) == 1
        assert report.findings[0].rule == "KT009"
        assert "device_put" in report.findings[0].message

    def test_device_put_with_placement_passes(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax

            def stage(x, sharding, dev):
                a = jax.device_put(x, sharding)
                b = jax.device_put(x, sharding=sharding)
                c = jax.device_put(x, device=dev)
                return a, b, c
            """,
        )
        assert report.findings == []

    def test_devices_indexing_flagged(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax

            def first():
                return jax.devices()[0]

            def window():
                return jax.local_devices()[:4]
            """,
        )
        assert len(report.findings) == 2
        assert all("topology" in f.message for f in report.findings)

    def test_pmap_flagged(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax

            def build(f):
                return jax.pmap(f)
            """,
        )
        assert len(report.findings) == 1
        assert "pmap" in report.findings[0].message

    def test_mesh_construction_outside_seam_flagged(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax
            import numpy as np
            from jax.sharding import Mesh

            def ad_hoc():
                a = Mesh(np.array(jax.devices()), ("nodes",))
                b = jax.sharding.Mesh(np.array(jax.devices()), ("x",))
                return a, b
            """,
        )
        assert len(report.findings) == 2
        assert all("seam" in f.message for f in report.findings)

    def test_mesh_construction_in_matrices_seam_passes(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax
            import numpy as np
            from jax.sharding import Mesh

            def host_mesh(n):
                return Mesh(np.array(jax.devices()), ("nodes",))
            """,
            filename="matrices.py",
        )
        assert report.findings == []

    def test_pragma_suppresses(self, tmp_path):
        report = _lint(
            tmp_path,
            """
            import jax

            def first():
                # ktlint: disable=KT009
                return jax.devices()[0]
            """,
        )
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_out_of_scope_module_ignored(self, tmp_path):
        pkg = tmp_path / "controllers"
        pkg.mkdir()
        f = pkg / "mod.py"
        f.write_text("import jax\nd = jax.devices()[0]\n")
        report = ktlint.lint([f], select=["KT009"], baseline_path=None)
        assert report.findings == []

    def test_live_tree_kt009_clean(self):
        report = ktlint.lint(select=["KT009"])
        assert report.findings == [], [f.render() for f in report.findings]


# -- contract-surface units --------------------------------------------


class TestPartitionSpecs:
    def test_leaf_spec_shards_only_the_declared_dim(self):
        leaf = C.ArraySpec(("N", "S"), "f32")
        sh = C.MeshSharding(dim="N", axis="nodes")
        assert C.partition_spec(leaf, sh) == ("nodes", None)
        assert C.partition_spec(C.ArraySpec(("P",), "f32"), sh) == (None,)

    def test_solver_specs_node_shard_nodes_replicate_pods(self):
        specs = C.partition_specs(C.CONTRACTS["solver._solve_xla"])
        assert specs["args"]["nodes"]["cpu_cap"] == ("nodes",)
        assert specs["args"]["nodes"]["svc_counts"] == ("nodes", None)
        assert specs["args"]["pods"]["cpu"] == (None,)
        assert specs["args"]["weights"] is None  # static
        assert specs["results"] == (None,)  # i32[P], replicated

    def test_every_contract_exposes_specs(self):
        for name, contract in C.CONTRACTS.items():
            specs = C.partition_specs(contract)
            assert set(specs) == {"args", "results"}, name


class TestCollectiveInventory:
    HLO = textwrap.dedent(
        """
        ENTRY %main {
          %p = f32[384,1]{1,0} parameter(0)
          %ag = f32[384,8]{1,0} all-gather(f32[384,1]{1,0} %p), channel_id=1, replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={1}
          %q = f32[256]{0} parameter(1)
          %ar = f32[256]{0} all-reduce(f32[256]{0} %q), to_apply=%add
          %b = pred[40]{0} parameter(2)
          %ar2 = pred[40]{0} all-reduce(pred[40]{0} %b), to_apply=%or
          %plain = f32[256]{0} add(f32[256]{0} %q, f32[256]{0} %q)
        }
        """
    )

    def test_counts_bytes_and_gather_dim(self):
        inv = C.collective_inventory(self.HLO)
        assert inv["counts"] == {"all-gather": 1, "all-reduce": 2}
        assert inv["total"] == 3
        # f32[384,8] = 12288 B; f32[256] = 1024 B + pred[40] = 40 B.
        assert inv["bytes"] == {"all-gather": 12288, "all-reduce": 1064}
        ag = [op for op in inv["ops"] if op["kind"] == "all-gather"][0]
        assert ag["gather_dim"] == 1
        assert ag["shape"] == [384, 8]

    def test_collective_free_module(self):
        inv = C.collective_inventory("%x = f32[8]{0} add(%a, %b)")
        assert inv == {"counts": {}, "bytes": {}, "total": 0, "ops": []}


class TestCommVerdict:
    def test_unknown_kernel_is_uncontracted(self):
        assert C.comm_verdict("nope.missing", {"all-reduce": 1}) == (
            "uncontracted"
        )

    def test_empty_inventory_is_ok(self):
        assert C.comm_verdict(GANG, {}) == "ok"

    def test_declared_kinds_any_count_ok(self):
        # Count-lenient: runtime buckets differ from the probe point.
        assert C.comm_verdict(GANG, {"all-reduce": 7}) == "ok"

    def test_undeclared_kind_is_drift(self):
        v = C.comm_verdict(GANG, {"all-reduce": 1, "all-gather": 2})
        assert v == "drift: undeclared all-gather"


# -- drift injection through the real lowering -------------------------


class TestDriftInjection:
    def test_pristine_contract_is_clean(self):
        findings, meta = _check(GANG, C.CONTRACTS[GANG])
        assert findings == []
        assert meta["status"] == "ok"
        assert meta["collectives"] == {"all-reduce": 1}

    def test_tightened_budget_is_finding(self):
        bad = _resharded(GANG, budget=C.CommBudget(all_reduce=2))
        findings, _ = _check(GANG, bad)
        assert [f.check for f in findings] == ["budget"]

    def test_phantom_declared_kind_is_finding(self):
        bad = _resharded(
            GANG, budget=C.CommBudget(all_reduce=1, collective_permute=3)
        )
        findings, _ = _check(GANG, bad)
        assert [f.check for f in findings] == ["budget"]

    def test_replication_vanishing_declared_collective_is_finding(self):
        # Mis-sharded leaf: full replication lowers collective-free,
        # contradicting the declared all_reduce=1.
        bad = _resharded(GANG, dim=None)
        findings, meta = _check(GANG, bad)
        assert meta["collectives"] == {}
        assert "budget" in [f.check for f in findings]

    def test_pod_sharded_wave_full_gathers_pod_axis(self):
        # The deliberately mis-sharded fixture kernel: wave couples
        # pods through windowed commits, so pod-axis sharding makes
        # GSPMD materialize the FULL pod axis — exactly the silent
        # scaling loss the pod-gather check exists for.
        bad = _resharded(
            "wave.solve_waves", dim="P", axis="pods",
            budget=C.CommBudget(),
        )
        findings, meta = _check("wave.solve_waves", bad)
        checks = {f.check for f in findings}
        assert "pod-gather" in checks
        assert "budget" in checks
        pod = [f for f in findings if f.check == "pod-gather"]
        assert any("P=384" in f.message for f in pod)

    def test_shardable_with_collectives_fails_coupling_xcheck(self):
        # Lie about the coupling class: gang reduces over the pod axis
        # (one psum), so claiming 'shardable' must trip the cross-check
        # even though the budget itself matches.
        c = _resharded(GANG)  # pristine sharding
        bad = dataclasses.replace(c, pod_axis="shardable")
        findings, _ = _check(GANG, bad)
        assert [f.check for f in findings] == ["coupling-xcheck"]

    def test_reduces_with_empty_inventory_fails_coupling_xcheck(self):
        # explain_rows is genuinely collective-free under pod sharding;
        # claiming it 'reduces' contradicts that.
        c = C.CONTRACTS["solver.explain_rows"]
        bad = dataclasses.replace(c, pod_axis="reduces")
        findings, _ = _check("solver.explain_rows", bad)
        assert [f.check for f in findings] == ["coupling-xcheck"]

    def test_missing_sharding_leaf_is_completeness_finding(self):
        bad = dataclasses.replace(C.CONTRACTS[GANG], sharding=None)
        findings, meta = _check(GANG, bad)
        assert [f.check for f in findings] == ["completeness"]
        assert meta["status"] == "error"

    def test_bogus_axis_is_completeness_finding(self):
        bad = _resharded(GANG, axis="rings")
        findings, _ = _check(GANG, bad)
        assert [f.check for f in findings] == ["completeness"]

    def test_unknown_dim_is_completeness_finding(self):
        bad = _resharded(GANG, dim="ZZ")
        findings, _ = _check(GANG, bad)
        assert [f.check for f in findings] == ["completeness"]

    def test_analyze_surfaces_drift_and_fails(self, monkeypatch):
        monkeypatch.setitem(
            C.CONTRACTS, GANG,
            _resharded(GANG, budget=C.CommBudget(all_reduce=2)),
        )
        report = ktmesh.analyze(devices=8, kernels=[GANG])
        assert report.exit_code == 1
        assert [f.check for f in report.findings] == ["budget"]


# -- the runtime join: ledger COMM verdict ------------------------------


class TestRuntimeCommVerdict:
    def _dispatch(self):
        import jax.numpy as jnp

        from kubernetes_tpu.ops import ledger
        from kubernetes_tpu.ops.matrices import gang_member_counts

        out = gang_member_counts(
            jnp.ones(16, dtype=bool), jnp.zeros(16, dtype=jnp.int32), 8
        )
        out.block_until_ready()
        assert ledger.DEFAULT.wait_pending(60)
        return ledger

    def test_ledger_rows_carry_collective_inventory(self):
        ledger = self._dispatch()
        rows = {r["kernel"]: r for r in ledger.DEFAULT.rows()}
        shapes = rows[GANG]["shapes"]
        # Unsharded dispatch: empty inventory, verdict trivially ok.
        assert any(
            s.get("collectives") == {}
            and s.get("collectives_verdict") == "ok"
            for s in shapes
        ), [(s["signature"], s.get("collectives_verdict")) for s in shapes]

    def test_ktctl_profile_renders_comm_column(self, capsys):
        from kubernetes_tpu.cli import ktctl
        from kubernetes_tpu.client import Client, LocalTransport
        from kubernetes_tpu.server.api import APIServer

        self._dispatch()
        rc = ktctl.main(
            ["profile", "kernels"],
            client=Client(LocalTransport(APIServer())),
        )
        out = capsys.readouterr().out
        assert rc == 0
        assert "COMM" in out
        gang_line = [ln for ln in out.splitlines() if GANG in ln][0]
        assert gang_line.rstrip().endswith("ok")


# -- the gates ----------------------------------------------------------


class TestLiveTreeGate:
    def test_live_tree_analyzes_clean(self):
        report = ktmesh.analyze(devices=8)
        assert report.errors == []
        assert report.findings == [], [
            f.render() for f in report.findings
        ]
        assert report.exit_code == 0
        assert len(report.kernels) == len(C.CONTRACTS)
        assert all(k["status"] == "ok" for k in report.kernels)
        # The budgets are evidence, not decoration: the node-sharded
        # solvers DO communicate, and explain_rows does NOT.
        assert report.collectives_total > 0
        by_name = {k["kernel"]: k for k in report.kernels}
        assert by_name["solver.explain_rows"]["collectives"] == {}
        assert by_name["solver._solve_xla"]["collectives_total"] > 0

    def test_to_json_schema(self):
        report = ktmesh.analyze(devices=8, kernels=[GANG])
        data = report.to_json()
        assert set(data) == {
            "devices", "kernels_checked", "kernels", "collectives_total",
            "collective_bytes_total", "skipped", "findings", "errors",
        }
        assert data["kernels_checked"] == 1
        assert data["kernels"][0]["budget"] == {"all-reduce": 1}


class TestCLI:
    def test_single_kernel_json_roundtrip(self, mesh_subprocess_env):
        proc = subprocess.run(
            [
                sys.executable, "-m", "tools.ktlint", "--mesh-analysis",
                "--format=json", GANG,
            ],
            cwd=REPO, env=mesh_subprocess_env,
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        data = json.loads(proc.stdout)
        assert data["kernels_checked"] == 1
        assert data["kernels"][0]["status"] == "ok"
        assert data["findings"] == []

    def test_unknown_kernel_key_is_usage_error(self, mesh_subprocess_env):
        proc = subprocess.run(
            [
                sys.executable, "-m", "tools.ktlint", "--mesh-analysis",
                "kubernetes_tpu/ops/solver.py",
            ],
            cwd=REPO, env=mesh_subprocess_env,
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 2
        assert "kernel keys" in proc.stderr

    def test_off_mesh_degrades_to_skipped_exit_zero(self):
        # A host without the forced multi-device platform cannot add
        # evidence but must not fail CI: every kernel 'skipped', exit 0.
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [
                sys.executable, "-m", "tools.ktlint", "--mesh-analysis",
                "--devices", "1", "--format=json",
            ],
            cwd=REPO, env=env,
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        data = json.loads(proc.stdout)
        assert data["devices"] == 1
        assert data["kernels_checked"] == len(C.CONTRACTS)
        assert data["skipped"] == len(C.CONTRACTS)
        assert all(
            k["status"] == "skipped" and "skip_reason" in k
            for k in data["kernels"]
        )
        assert data["findings"] == []
