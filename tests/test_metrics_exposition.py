"""Prometheus text exposition format: golden output, label escaping,
histogram bucket invariants, summary quantile rendering — plus the
metric-name lint (ktlint pass KT005; tools/lint_metrics.py is now a
deprecation shim onto it) over the live package."""

import pathlib
import subprocess
import sys

from kubernetes_tpu.utils import metrics


class TestCounterGauge:
    def test_counter_golden(self):
        c = metrics.Counter("widgets_total", "Widgets made", ("kind",))
        c.inc(kind="round")
        c.inc(2, kind="square")
        c.inc(kind="square")
        assert c.render() == [
            "# HELP widgets_total Widgets made",
            "# TYPE widgets_total counter",
            'widgets_total{kind="round"} 1.0',
            'widgets_total{kind="square"} 3.0',
        ]

    def test_gauge_golden(self):
        g = metrics.Gauge("queue_depth_bytes", "Depth")
        g.set(7)
        assert g.render() == [
            "# HELP queue_depth_bytes Depth",
            "# TYPE queue_depth_bytes gauge",
            "queue_depth_bytes 7",
        ]

    def test_label_value_escaping(self):
        """Backslash, double-quote, and newline must be escaped per the
        text exposition format — a pod name carrying '"' used to
        corrupt the /metrics output."""
        c = metrics.Counter("pods_total", "by pod", ("pod",))
        c.inc(pod='we"ird\\name\nx')
        line = c.render()[-1]
        assert line == 'pods_total{pod="we\\"ird\\\\name\\nx"} 1.0'
        # The exposition line stays one physical line — the raw newline
        # never leaks into the output.
        assert "\n" not in line

    def test_help_escaping(self):
        c = metrics.Counter("x_total", "line1\nline2")
        assert c.render()[0] == "# HELP x_total line1\\nline2"


class TestHistogram:
    def test_default_bucket_ladder_golden(self):
        """The ladder the latency SLOs read, pinned (ISSUE 12): sub-
        100ms resolution (0.01/0.025/0.05/0.075/0.1) so the 100ms
        pod-to-bind objective has quantile resolution UNDER its
        target, and a 30/60/120 tail past client_golang's 10s cap so a
        saturated series reports a real (interpolated) p99 instead of
        a value clamped to exactly 10.0 — BENCH_r06's
        solve_phase_latency 'p99 10.0' was the clamp, not a
        measurement."""
        assert metrics.DEFAULT_BUCKETS == (
            0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.25, 0.5, 1.0, 2.5,
            5.0, 10.0, 30.0, 60.0, 120.0,
        )
        # Sub-100ms band: four finite bounds strictly below 0.1.
        assert [b for b in metrics.DEFAULT_BUCKETS if b < 0.1] == [
            0.005, 0.01, 0.025, 0.05, 0.075,
        ]
        # A 12s-heavy series interpolates INSIDE (10, 30], not at the
        # old clamp.
        h = metrics.Histogram("ladder_seconds", "x")
        for _ in range(100):
            h.observe(12.0)
        q = h.quantile(0.99)
        assert 10.0 < q <= 30.0
        # Rendered exposition carries the new bounds.
        text = "\n".join(h.render())
        assert 'le="0.075"' in text and 'le="30"' in text
        assert 'le="120"' in text

    def test_type_line_and_buckets(self):
        h = metrics.Histogram(
            "req_seconds", "Request latency", buckets=(0.1, 1.0, 10.0)
        )
        for v in (0.05, 0.5, 0.5, 5.0, 50.0):
            h.observe(v)
        lines = h.render()
        assert lines[0] == "# HELP req_seconds Request latency"
        assert lines[1] == "# TYPE req_seconds histogram"
        assert lines[2:] == [
            'req_seconds_bucket{le="0.1"} 1',
            'req_seconds_bucket{le="1"} 3',
            'req_seconds_bucket{le="10"} 4',
            'req_seconds_bucket{le="+Inf"} 5',
            "req_seconds_sum 56.05",
            "req_seconds_count 5",
        ]

    def test_bucket_monotonicity_and_inf_equals_count(self):
        h = metrics.Histogram("lat_seconds", "x", ("phase",))
        import random

        rng = random.Random(7)
        for _ in range(500):
            h.observe(rng.expovariate(2.0), phase="solve")
        cums = []
        inf_val = count_val = None
        for line in h.render():
            if line.startswith("lat_seconds_bucket"):
                v = int(line.rsplit(" ", 1)[1])
                if 'le="+Inf"' in line:
                    inf_val = v
                else:
                    cums.append(v)
            elif line.startswith("lat_seconds_count"):
                count_val = int(line.rsplit(" ", 1)[1])
        assert cums == sorted(cums), "bucket counts must be cumulative"
        assert inf_val == count_val == 500
        assert cums[-1] <= inf_val

    def test_quantile_interpolation(self):
        h = metrics.Histogram("q_seconds", "x", buckets=(1.0, 2.0, 4.0))
        for v in (0.5,) * 50 + (1.5,) * 50:
            h.observe(v)
        # Median sits at the boundary of the first bucket.
        assert h.quantile(0.5) == 1.0
        # p99 interpolates inside the (1, 2] bucket.
        assert 1.9 < h.quantile(0.99) <= 2.0
        # Values beyond the top bound report the top finite bound.
        h2 = metrics.Histogram("q2_seconds", "x", buckets=(1.0,))
        h2.observe(100.0)
        assert h2.quantile(0.99) == 1.0
        # Empty series: NaN.
        import math

        assert math.isnan(metrics.Histogram("q3_seconds", "x").quantile(0.5))

    def test_registry_histogram_in_default_render(self):
        h = metrics.DEFAULT.histogram(
            "exposition_test_seconds", "temp", ("k",)
        )
        h.observe(0.2, k="v")
        text = metrics.DEFAULT.render()
        assert "# TYPE exposition_test_seconds histogram" in text
        assert 'exposition_test_seconds_bucket{k="v",le="+Inf"} 1' in text


class TestSummary:
    def test_quantile_rendering(self):
        s = metrics.Summary("sum_seconds", "x", quantiles=(0.5, 0.99))
        for v in range(1, 101):
            s.observe(float(v))
        lines = s.render()
        assert lines[1] == "# TYPE sum_seconds summary"
        assert 'sum_seconds{quantile="0.5"} 50.0' in lines
        assert 'sum_seconds{quantile="0.99"} 99.0' in lines
        assert "sum_seconds_sum 5050.0" in lines
        assert "sum_seconds_count 100" in lines

    def test_reservoir_seedable(self):
        """Reservoir sampling draws from the module-level RNG, so tests
        can seed it for reproducible eviction patterns (and observe()
        no longer imports random on the hot path)."""

        def run():
            metrics._RNG.seed(42)
            s = metrics.Summary("seed_seconds", "x")
            for v in range(5000):
                s.observe(float(v))
            return sorted(s._stats[()]["res"])

        assert run() == run()


def _ktlint_kt005(root, target):
    """Run the KT005 pass the way CI does (baseline-free)."""
    return subprocess.run(
        [sys.executable, "-m", "tools.ktlint", "--select", "KT005",
         "--baseline=", str(target)],
        capture_output=True, text=True, timeout=120, cwd=str(root),
    )


def test_lint_metrics_clean():
    """ktlint KT005 over the live package: every registered metric is
    snake_case, unit-suffixed, and on metrics.DEFAULT."""
    root = pathlib.Path(__file__).resolve().parent.parent
    proc = _ktlint_kt005(root, root / "kubernetes_tpu")
    assert proc.returncode == 0, proc.stderr


def test_lint_metrics_catches_violations(tmp_path):
    root = pathlib.Path(__file__).resolve().parent.parent
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from kubernetes_tpu.utils import metrics\n"
        "from kubernetes_tpu.utils.metrics import Counter\n"
        'A = metrics.DEFAULT.counter("CamelCase", "x")\n'
        'B = metrics.DEFAULT.gauge("no_unit_suffix", "x")\n'
        'C = metrics.Summary("rogue_seconds", "x")\n'
        'D = Counter("imported_bypass_seconds", "x")\n'
    )
    proc = _ktlint_kt005(root, tmp_path)
    assert proc.returncode == 1
    assert "not snake_case" in proc.stderr
    assert "lacks a unit suffix" in proc.stderr
    assert "bypasses metrics.DEFAULT" in proc.stderr
    # Both bypass shapes are caught: metrics.Summary(...) AND the
    # from-import form Counter(...).
    assert proc.stderr.count("bypasses metrics.DEFAULT") == 2


def test_lint_metrics_shim_still_works(tmp_path):
    """The deprecated tools/lint_metrics.py entry point execs the
    KT005 pass with the historical output format."""
    root = pathlib.Path(__file__).resolve().parent.parent
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("no_unit_suffix", "x")\n'
    )
    proc = subprocess.run(
        [sys.executable, str(root / "tools" / "lint_metrics.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr
    assert "1 metric lint problem(s)" in proc.stderr


def test_lint_metrics_knows_gang_names(tmp_path):
    """The gang_* metric family (scheduler/gang.py, controllers/
    gangs.py) is known to the linter: the suffixed counters pass the
    standard rule, the unitless gang_pending_groups gauge is
    explicitly allowlisted, and a novel suffix-less gang name still
    fails (the allowlist names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import GANG_METRICS

    assert GANG_METRICS == {
        "gang_solve_outcomes_total",
        "gang_controller_syncs_total",
        "gang_pending_groups",
    }
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.counter("gang_solve_outcomes_total", "x", ("outcome",))\n'
        'B = metrics.DEFAULT.counter("gang_controller_syncs_total", "x", ("result",))\n'
        'C = metrics.DEFAULT.gauge("gang_pending_groups", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("gang_stuck", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_lint_metrics_knows_explain_names(tmp_path):
    """The explainability/convergence family (utils/flightrecorder.py)
    is known to the linter: scheduler_decisions_total passes the
    standard _total rule on its own, the unit-less residual gauge and
    iterations histogram are explicitly allowlisted, and a novel
    suffix-less scheduler_* name still fails (the allowlist names
    metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, EXPLAIN_METRICS

    assert EXPLAIN_METRICS == {
        "scheduler_decisions_total",
        "scheduler_sinkhorn_residual",
        "scheduler_solve_iterations",
    }
    assert EXPLAIN_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.counter('
        '"scheduler_decisions_total", "x", ("outcome",))\n'
        'B = metrics.DEFAULT.gauge("scheduler_sinkhorn_residual", "x")\n'
        'C = metrics.DEFAULT.histogram('
        '"scheduler_solve_iterations", "x", ("mode",))\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("scheduler_explain_lag", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_decision_and_convergence_metrics_exposed():
    """Exposition golden for the flight-recorder family: the decision
    counter, the residual gauge, and the iterations histogram all
    render on metrics.DEFAULT with their declared types (they are
    registered at flightrecorder import, so a scrape can never miss
    the family)."""
    from kubernetes_tpu.utils import flightrecorder as fr

    fr.DECISIONS_TOTAL.inc(outcome="exposition_test")
    fr.observe_solve_telemetry("exposition_test_mode", 7, residual=0.25)
    text = metrics.DEFAULT.render()
    assert "# TYPE scheduler_decisions_total counter" in text
    assert 'scheduler_decisions_total{outcome="exposition_test"} 1.0' in text
    assert "# TYPE scheduler_sinkhorn_residual gauge" in text
    assert "scheduler_sinkhorn_residual 0.25" in text
    assert "# TYPE scheduler_solve_iterations histogram" in text
    # 7 iterations lands in the le=8 bucket of the power-of-two ladder.
    assert (
        'scheduler_solve_iterations_bucket{mode="exposition_test_mode",'
        'le="8"} 1' in text
    )
    assert (
        'scheduler_solve_iterations_count{mode="exposition_test_mode"} 1'
        in text
    )


def test_lint_metrics_knows_sli_names(tmp_path):
    """The SLI/SLO telemetry-plane family (utils/sli.py,
    store/watch.py, scheduler/daemon.py) is known to the linter: the
    suffixed series pass the standard rule on their own, the unit-less
    ones (queue depth, version lag, compile-cache entries) are
    explicitly allowlisted, and a novel suffix-less name still fails
    (the allowlist names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, SLI_METRICS

    assert SLI_METRICS == {
        "pod_startup_latency_seconds",
        "watch_streams_dropped_total",
        "watch_stream_queue_depth",
        "watch_fanout_lag_versions",
        "scheduler_informer_staleness_seconds",
        "solver_device_transfer_bytes_total",
        "solver_xla_compiles_total",
        "solver_xla_compile_cache_entries",
        "device_memory_bytes",
    }
    assert SLI_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.histogram('
        '"pod_startup_latency_seconds", "x", ("milestone",))\n'
        'B = metrics.DEFAULT.counter('
        '"watch_streams_dropped_total", "x", ("resource",))\n'
        'C = metrics.DEFAULT.gauge('
        '"watch_stream_queue_depth", "x", ("resource",))\n'
        'D = metrics.DEFAULT.histogram('
        '"watch_fanout_lag_versions", "x", ("resource",))\n'
        'E = metrics.DEFAULT.gauge("solver_xla_compile_cache_entries", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("watch_backlog", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_sli_metrics_exposed():
    """Exposition golden for the telemetry-plane family: the milestone
    histogram renders cumulative +le buckets, the drop counter escapes
    hostile label values, and the lag/depth/device series render on
    metrics.DEFAULT with their declared types."""
    from kubernetes_tpu.store import watch as watchmod
    from kubernetes_tpu.utils import sli

    sli.STARTUP_LATENCY.observe(0.007, milestone="exposition_m")
    sli.STARTUP_LATENCY.observe(0.2, milestone="exposition_m")
    watchmod.STREAMS_DROPPED.inc(resource='we"ird\\res\nx')
    watchmod.QUEUE_DEPTH.set(3, resource="exposition_r")
    sli.observe_watch_lag("exposition_r", 5)
    sli.TRANSFER_BYTES.inc(1024, direction="exposition_d")
    text = metrics.DEFAULT.render()
    assert "# TYPE pod_startup_latency_seconds histogram" in text
    # Cumulative buckets: the 0.2 observation lands at le=0.25 and the
    # 0.007 one at le=0.01 — the +Inf bucket equals the count.
    assert (
        'pod_startup_latency_seconds_bucket{milestone="exposition_m",'
        'le="0.01"} 1' in text
    )
    assert (
        'pod_startup_latency_seconds_bucket{milestone="exposition_m",'
        'le="+Inf"} 2' in text
    )
    assert (
        'pod_startup_latency_seconds_count{milestone="exposition_m"} 2'
        in text
    )
    # Label escaping at the drop counter (a resource label can never
    # corrupt the exposition).
    assert (
        'watch_streams_dropped_total{resource="we\\"ird\\\\res\\nx"} 1.0'
        in text
    )
    assert "# TYPE watch_stream_queue_depth gauge" in text
    assert "# TYPE watch_fanout_lag_versions histogram" in text
    assert (
        'watch_fanout_lag_versions_bucket{resource="exposition_r",le="8"}'
        in text
    )
    assert "# TYPE solver_device_transfer_bytes_total counter" in text
    assert "# TYPE solver_xla_compile_cache_entries gauge" in text


def test_lint_metrics_knows_profiler_names(tmp_path):
    """The device-time profiling-plane family (ops/ledger.py,
    utils/profiler.py) is known to the linter: the _total-suffixed
    counters pass the standard rule on their own, the unit-less
    duty-cycle/overlap ratio histograms are explicitly allowlisted,
    and a novel suffix-less profiler name still fails (the allowlist
    names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, PROFILER_METRICS

    assert PROFILER_METRICS == {
        "solver_compile_seconds_total",
        "scheduler_device_busy_seconds_total",
        "scheduler_device_duty_cycle",
        "scheduler_overlap_efficiency",
    }
    assert PROFILER_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.counter('
        '"solver_compile_seconds_total", "x", ("kernel",))\n'
        'B = metrics.DEFAULT.histogram("scheduler_device_duty_cycle", "x")\n'
        'C = metrics.DEFAULT.histogram("scheduler_overlap_efficiency", "x")\n'
        'D = metrics.DEFAULT.counter('
        '"scheduler_device_busy_seconds_total", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.histogram("scheduler_device_idle", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_profiler_metrics_exposed():
    """Exposition golden for the profiling-plane family: the duty/
    overlap ratio histograms render cumulative +le buckets on their
    ratio ladder, and the compile-seconds counter escapes hostile
    kernel label values."""
    from kubernetes_tpu.utils import profiler

    profiler.observe_tick(device_s=0.004, wall_s=0.01, blocked_s=0.001)
    from kubernetes_tpu.ops import ledger

    ledger.COMPILE_SECONDS.inc(1.5, kernel='we"ird\\kern\nx')
    text = metrics.DEFAULT.render()
    assert "# TYPE scheduler_device_duty_cycle histogram" in text
    # 0.4 duty lands at le=0.4 of the ratio ladder; buckets cumulate
    # to the +Inf == count invariant.
    assert 'scheduler_device_duty_cycle_bucket{le="0.4"}' in text
    assert 'scheduler_device_duty_cycle_bucket{le="+Inf"}' in text
    assert "# TYPE scheduler_overlap_efficiency histogram" in text
    assert 'scheduler_overlap_efficiency_bucket{le="0.8"}' in text
    assert "# TYPE scheduler_device_busy_seconds_total counter" in text
    assert "# TYPE solver_compile_seconds_total counter" in text
    # Label escaping: a hostile kernel name can never corrupt the
    # exposition.
    assert (
        'solver_compile_seconds_total{kernel="we\\"ird\\\\kern\\nx"} 1.5'
        in text
    )


def test_lint_metrics_knows_capacity_names(tmp_path):
    """The capacity & fragmentation plane family (utils/capacity.py) is
    known to the linter: node_utilization_ratio and the zero-headroom
    _total counter pass the standard rule on their own, the unit-less
    score/rate/headroom/pressure series are explicitly allowlisted, and
    a novel suffix-less capacity name still fails (the allowlist names
    metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, CAPACITY_METRICS

    assert CAPACITY_METRICS == {
        "cluster_fragmentation_score",
        "cluster_headroom_pods",
        "slice_alloc_success_rate",
        "scheduler_backlog_pressure",
    }
    assert CAPACITY_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.histogram("cluster_fragmentation_score", "x")\n'
        'B = metrics.DEFAULT.histogram('
        '"node_utilization_ratio", "x", ("resource",))\n'
        'C = metrics.DEFAULT.gauge("cluster_headroom_pods", "x", ("shape",))\n'
        'D = metrics.DEFAULT.histogram("slice_alloc_success_rate", "x")\n'
        'E = metrics.DEFAULT.gauge("scheduler_backlog_pressure", "x")\n'
        'F = metrics.DEFAULT.counter('
        '"capacity_zero_headroom_ticks_total", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("cluster_stranded", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_capacity_metrics_exposed():
    """Exposition golden for the capacity-plane family: the score/rate
    histograms render cumulative +le buckets on the ratio ladder, the
    pressure gauge and zero-headroom counter carry their declared
    types, and the per-shape headroom gauge escapes hostile shape
    label values (an operator-configured probe name can never corrupt
    the exposition)."""
    from kubernetes_tpu.utils import capacity as capmod

    capmod.FRAG_SCORE.observe(0.35)
    capmod.SLICE_ALLOC.observe(0.75)
    capmod.HEADROOM.set(12.0, shape='we"ird\\shape\nx')
    capmod.BACKLOG_PRESSURE.set(2.5)
    capmod.NODE_UTIL.observe(0.55, resource="cpu")
    capmod.ZERO_HEADROOM.inc()
    text = metrics.DEFAULT.render()
    assert "# TYPE cluster_fragmentation_score histogram" in text
    assert 'cluster_fragmentation_score_bucket{le="0.4"}' in text
    assert 'cluster_fragmentation_score_bucket{le="+Inf"}' in text
    assert "# TYPE slice_alloc_success_rate histogram" in text
    assert 'slice_alloc_success_rate_bucket{le="0.8"}' in text
    assert "# TYPE node_utilization_ratio histogram" in text
    assert 'node_utilization_ratio_bucket{resource="cpu",le="0.6"}' in text
    assert "# TYPE cluster_headroom_pods gauge" in text
    # Label escaping on the shape label.
    assert (
        'cluster_headroom_pods{shape="we\\"ird\\\\shape\\nx"} 12.0' in text
    )
    assert "# TYPE scheduler_backlog_pressure gauge" in text
    assert "scheduler_backlog_pressure 2.5" in text
    assert "# TYPE capacity_zero_headroom_ticks_total counter" in text


def test_lint_metrics_knows_preemption_names(tmp_path):
    """The preemption_* family (scheduler/daemon.py) is known to the
    linter: the _total counters pass the standard rule, the unitless
    preemption_active_nominations gauge is explicitly allowlisted, and
    a novel suffix-less preemption name still fails (the allowlist
    names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, PREEMPTION_METRICS

    assert PREEMPTION_METRICS == {
        "preemption_victims_total",
        "preemption_solve_outcomes_total",
        "preemption_active_nominations",
    }
    assert PREEMPTION_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.counter("preemption_victims_total", "x")\n'
        'B = metrics.DEFAULT.counter('
        '"preemption_solve_outcomes_total", "x", ("outcome",))\n'
        'C = metrics.DEFAULT.gauge("preemption_active_nominations", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("preemption_backlog", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_lint_metrics_knows_rebalance_names(tmp_path):
    """The rebalance plane family (utils/rebalance.py) is known to the
    linter: the _total counters pass the standard rule on their own,
    the unitless improvement/efficiency histograms are explicitly
    allowlisted, and a novel suffix-less rebalance name still fails
    (the allowlist names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, REBALANCE_METRICS

    assert REBALANCE_METRICS == {
        "rebalance_moves_total",
        "rebalance_score_improvement",
        "rebalance_moves_per_improvement",
        "rebalance_stranded_pods_total",
    }
    assert REBALANCE_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.counter('
        '"rebalance_moves_total", "x", ("outcome",))\n'
        'B = metrics.DEFAULT.histogram("rebalance_score_improvement", "x")\n'
        'C = metrics.DEFAULT.histogram('
        '"rebalance_moves_per_improvement", "x")\n'
        'D = metrics.DEFAULT.counter("rebalance_stranded_pods_total", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("rebalance_churn", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_lint_metrics_knows_autoscaler_names(tmp_path):
    """The autoscaler family (controllers/autoscaler.py) is known to
    the linter: autoscaler_scale_events_total passes the standard rule
    on its own, the unitless per-pool size gauge is explicitly
    allowlisted, and a novel suffix-less autoscaler name still fails."""
    from tools.ktlint.rules_metrics import ALLOWLIST, AUTOSCALER_METRICS

    assert AUTOSCALER_METRICS == {
        "autoscaler_pool_size",
        "autoscaler_scale_events_total",
    }
    assert AUTOSCALER_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("autoscaler_pool_size", "x", ("pool",))\n'
        'B = metrics.DEFAULT.counter('
        '"autoscaler_scale_events_total", "x", ("direction",))\n'
        'C = metrics.DEFAULT.counter('
        '"autoscaler_syncs_total", "x", ("result",))\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("autoscaler_backlog", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_rebalance_metrics_exposed():
    """Exposition golden for the rebalance-plane family: the
    improvement histogram renders cumulative +le buckets on the ratio
    ladder, the moves-per-improvement efficiency histogram lands on
    the default ladder, and the move counter carries its outcome
    label with declared type."""
    from kubernetes_tpu.utils import rebalance as rebmod

    rebmod.MOVES.inc(outcome="evicted")
    rebmod.MOVES.inc(outcome="rebound")
    rebmod.IMPROVEMENT.observe(0.35)
    rebmod.MOVES_PER_IMPROVEMENT.observe(7.0)
    rebmod.STRANDED.inc()
    text = metrics.DEFAULT.render()
    assert "# TYPE rebalance_moves_total counter" in text
    assert 'rebalance_moves_total{outcome="evicted"} 1.0' in text
    assert 'rebalance_moves_total{outcome="rebound"} 1.0' in text
    assert "# TYPE rebalance_score_improvement histogram" in text
    assert 'rebalance_score_improvement_bucket{le="0.4"}' in text
    assert 'rebalance_score_improvement_bucket{le="+Inf"}' in text
    assert "# TYPE rebalance_moves_per_improvement histogram" in text
    assert 'rebalance_moves_per_improvement_bucket{le="10"}' in text
    assert "# TYPE rebalance_stranded_pods_total counter" in text


def test_autoscaler_metrics_exposed():
    """Exposition golden for the autoscaler family: the per-pool size
    gauge escapes hostile pool label values (an operator-named pool
    can never corrupt the exposition) and the scale-events counter
    carries its direction label with declared type."""
    from kubernetes_tpu.controllers.autoscaler import (
        POOL_SIZE,
        SCALE_EVENTS,
    )

    POOL_SIZE.set(3.0, pool='we"ird\\pool\nx')
    SCALE_EVENTS.inc(direction="up")
    SCALE_EVENTS.inc(direction="down")
    text = metrics.DEFAULT.render()
    assert "# TYPE autoscaler_pool_size gauge" in text
    # Label escaping on the pool label.
    assert 'autoscaler_pool_size{pool="we\\"ird\\\\pool\\nx"} 3.0' in text
    assert "# TYPE autoscaler_scale_events_total counter" in text
    assert 'autoscaler_scale_events_total{direction="up"} 1.0' in text
    assert 'autoscaler_scale_events_total{direction="down"} 1.0' in text


def test_lint_metrics_knows_replication_names(tmp_path):
    """The HA control-plane family (store/replication.py,
    utils/lease.py, scheduler/standby.py) is known to the linter:
    leader_elections_total and the activation summary pass the
    standard rule on their own, the unitless commit-index watermark
    and follower-lag version count are explicitly allowlisted, and a
    novel suffix-less replication name still fails (the allowlist
    names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, REPLICATION_METRICS

    assert REPLICATION_METRICS == {
        "replication_commit_index",
        "replication_follower_lag_versions",
        "leader_elections_total",
        "scheduler_standby_activation_seconds",
    }
    assert REPLICATION_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge('
        '"replication_commit_index", "x", ("role",))\n'
        'B = metrics.DEFAULT.gauge('
        '"replication_follower_lag_versions", "x", ("follower",))\n'
        'C = metrics.DEFAULT.counter('
        '"leader_elections_total", "x", ("tier",))\n'
        'D = metrics.DEFAULT.summary('
        '"scheduler_standby_activation_seconds", "x")\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("replication_backlog", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_replication_metrics_exposed():
    """Exposition golden for the HA control-plane family: commit
    index renders per role with declared gauge type, the follower-lag
    gauge escapes hostile follower names (a link name can never
    corrupt the exposition), and the per-tier election counter
    renders with declared counter type."""
    from kubernetes_tpu.store.replication import COMMIT_INDEX, FOLLOWER_LAG
    from kubernetes_tpu.utils.lease import ELECTIONS

    COMMIT_INDEX.set(42.0, role="leader")
    COMMIT_INDEX.set(40.0, role="follower:f1")
    FOLLOWER_LAG.set(2.0, follower='f"1\\x\ny')
    # The counter is process-global: earlier elector tests in the
    # suite may have counted real elections already — golden on the
    # delta, not an absolute.
    sched_base = ELECTIONS.value(tier="scheduler")
    kv_base = ELECTIONS.value(tier="kvstore")
    ELECTIONS.inc(tier="scheduler")
    ELECTIONS.inc(tier="kvstore")
    text = metrics.DEFAULT.render()
    assert "# TYPE replication_commit_index gauge" in text
    assert 'replication_commit_index{role="leader"} 42.0' in text
    assert 'replication_commit_index{role="follower:f1"} 40.0' in text
    assert "# TYPE replication_follower_lag_versions gauge" in text
    # Label escaping on the follower label.
    assert (
        'replication_follower_lag_versions{follower="f\\"1\\\\x\\ny"} 2.0'
        in text
    )
    assert "# TYPE leader_elections_total counter" in text
    assert (
        f'leader_elections_total{{tier="scheduler"}} {sched_base + 1.0}'
        in text
    )
    assert (
        f'leader_elections_total{{tier="kvstore"}} {kv_base + 1.0}' in text
    )


def test_lint_metrics_knows_health_names(tmp_path):
    """The health-plane family (utils/timeseries.py, utils/alerts.py,
    utils/lease.py) is known to the linter: the sample counter /
    sample-latency histogram / transition counter / renew-latency
    histogram pass the standard rule on their own, the unitless
    retained-series gauge and per-rule firing state gauge are
    explicitly allowlisted, and a novel suffix-less alert name still
    fails (the allowlist names metrics, not a prefix)."""
    from tools.ktlint.rules_metrics import ALLOWLIST, HEALTH_METRICS

    assert HEALTH_METRICS == {
        "timeseries_samples_total",
        "timeseries_retained_series",
        "timeseries_sample_seconds",
        "alerts_firing",
        "alert_transitions_total",
        "lease_renew_latency_seconds",
    }
    assert HEALTH_METRICS <= ALLOWLIST
    root = pathlib.Path(__file__).resolve().parent.parent
    good = tmp_path / "good"
    good.mkdir()
    (good / "g.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.counter("timeseries_samples_total", "x")\n'
        'B = metrics.DEFAULT.gauge("timeseries_retained_series", "x")\n'
        'C = metrics.DEFAULT.histogram("timeseries_sample_seconds", "x")\n'
        'D = metrics.DEFAULT.gauge("alerts_firing", "x", ("rule",))\n'
        'E = metrics.DEFAULT.counter('
        '"alert_transitions_total", "x", ("rule", "state"))\n'
        'F = metrics.DEFAULT.histogram('
        '"lease_renew_latency_seconds", "x", ("op",))\n'
    )
    proc = _ktlint_kt005(root, good)
    assert proc.returncode == 0, proc.stderr
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / "b.py").write_text(
        "from kubernetes_tpu.utils import metrics\n"
        'A = metrics.DEFAULT.gauge("alerts_pending", "x")\n'
    )
    proc = _ktlint_kt005(root, bad)
    assert proc.returncode == 1
    assert "lacks a unit suffix" in proc.stderr


def test_health_metrics_exposed():
    """Exposition golden for the health-plane family: the sampler's
    counter/gauge/histogram render with declared types, the per-rule
    firing gauge escapes hostile rule names, and the transition
    counter renders its (rule, state) pair. Process-global counters
    may have been moved by earlier suites — golden on deltas."""
    from kubernetes_tpu.utils.alerts import FIRING, TRANSITIONS
    from kubernetes_tpu.utils.lease import RENEW_LATENCY
    from kubernetes_tpu.utils.timeseries import (
        RETAINED,
        SAMPLE_SECONDS,
        SAMPLES,
    )

    samples_base = SAMPLES.value()
    SAMPLES.inc()
    RETAINED.set(7.0)
    SAMPLE_SECONDS.observe(0.002)
    FIRING.set(1.0, rule='r"1\\x\ny')
    trans_base = TRANSITIONS.value(rule="bind_latency_burn", state="firing")
    TRANSITIONS.inc(rule="bind_latency_burn", state="firing")
    RENEW_LATENCY.observe(0.01, op="renew")
    text = metrics.DEFAULT.render()
    assert "# TYPE timeseries_samples_total counter" in text
    assert f"timeseries_samples_total {samples_base + 1.0}" in text
    assert "# TYPE timeseries_retained_series gauge" in text
    assert "timeseries_retained_series 7.0" in text
    assert "# TYPE timeseries_sample_seconds histogram" in text
    assert "timeseries_sample_seconds_bucket" in text
    assert "# TYPE alerts_firing gauge" in text
    # Label escaping on the rule label.
    assert 'alerts_firing{rule="r\\"1\\\\x\\ny"} 1.0' in text
    assert "# TYPE alert_transitions_total counter" in text
    assert (
        f'alert_transitions_total{{rule="bind_latency_burn",'
        f'state="firing"}} {trans_base + 1.0}' in text
    )
    assert "# TYPE lease_renew_latency_seconds histogram" in text
    assert 'lease_renew_latency_seconds_count{op="renew"}' in text
