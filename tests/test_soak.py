"""Soak: the serve_hostnames drill, shortened.

Reference: test/soak/serve_hostnames — N pods each serve their own
name behind one service; a driver repeatedly queries through the
service dataplane and every reply must be a live pod's name, with all
pods eventually answering (round-robin coverage) and zero errors.

This runs the FULL stack: real apiserver + scheduler + kubelet with
the process runtime (pods are real HTTP servers), endpoints controller
resolving per-pod NAMED target ports into separate subsets, and the
userspace proxier carrying real TCP.
"""

import json
import socket
import sys
import time
import urllib.request

import pytest

from kubernetes_tpu.client import Client, LocalTransport
from kubernetes_tpu.cmd.localup import LocalCluster, build_parser
from kubernetes_tpu.proxy.config import ProxyServer

SERVE = (
    "import http.server,os\n"
    "name=os.environ['KUBERNETES_POD_NAME'].encode()\n"
    "class H(http.server.BaseHTTPRequestHandler):\n"
    "    def do_GET(self):\n"
    "        self.send_response(200)\n"
    "        self.send_header('Content-Length',str(len(name)))\n"
    "        self.end_headers()\n"
    "        self.wfile.write(name)\n"
    "    def log_message(self,*a): pass\n"
    "http.server.HTTPServer(('127.0.0.1',int(os.environ['SERVE_PORT'])),H)"
    ".serve_forever()\n"
)


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def wait_until(cond, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def hostname_pod(name, port):
    return {
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            "labels": {"app": "hostnames"},
        },
        "spec": {
            "containers": [
                {
                    "name": "server",
                    "image": "serve-hostname",
                    "command": [sys.executable, "-c", SERVE],
                    "env": [{"name": "SERVE_PORT", "value": str(port)}],
                    "ports": [{"name": "http", "containerPort": port}],
                }
            ]
        },
    }


@pytest.mark.slow
def test_serve_hostnames_soak(tmp_path):
    n_pods, n_queries = 3, 60
    args = build_parser().parse_args(
        ["--port", "0", "--nodes", "2", "--process-runtime"]
    )
    cluster = LocalCluster(args).start()
    proxy = None
    try:
        client = Client(LocalTransport(cluster.api))
        ports = free_ports(n_pods)
        names = [f"hostnames-{i}" for i in range(n_pods)]
        for name, port in zip(names, ports):
            client.create("pods", hostname_pod(name, port), namespace="default")
        svc = client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "hostnames", "namespace": "default"},
                "spec": {
                    "selector": {"app": "hostnames"},
                    "ports": [
                        {"name": "web", "port": 8000, "targetPort": "http"}
                    ],
                },
            },
            namespace="default",
        )
        cluster_ip = svc.spec.cluster_ip

        def all_running():
            pods, _ = client.list(
                "pods", namespace="default", label_selector="app=hostnames"
            )
            return sum(1 for p in pods if p.status.phase == "Running") == n_pods

        assert wait_until(all_running, timeout=60), "pods never all Running"

        # Named targetPort resolves per pod -> one subset per distinct
        # resolved port; all three must be present.
        def endpoints_complete():
            try:
                ep = client.get("endpoints", "hostnames", namespace="default")
            except Exception:
                return False
            got = {
                (a.ip, p.port)
                for s in ep.subsets
                for a in s.addresses
                for p in s.ports
            }
            return got == {("127.0.0.1", port) for port in ports}

        assert wait_until(endpoints_complete, timeout=30), "endpoints incomplete"

        proxy = ProxyServer(client).start()

        def portal_ready():
            return proxy.resolve_portal(cluster_ip, 8000) is not None and len(
                set(proxy.lb.endpoints_for(("default", "hostnames", "web")))
            ) == n_pods

        assert wait_until(portal_ready, timeout=30), "portal never ready"
        target = proxy.resolve_portal(cluster_ip, 8000)

        # "Running" means the process started, not that it bound its
        # socket yet — warm each backend directly before the timed loop
        # (the reference soak also waits for pods to respond first).
        def backend_up(port):
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/", timeout=2
                ) as resp:
                    return resp.status == 200
            except Exception:
                return False

        for port in ports:
            assert wait_until(
                lambda: backend_up(port), timeout=30
            ), f"backend :{port} never answered"

        # The soak loop: every reply must be a pod name; every pod must
        # answer at least once; zero errors tolerated (serve_hostnames'
        # pass bar).
        seen = {}
        for i in range(n_queries):
            url = f"http://{target[0]}:{target[1]}/"
            with urllib.request.urlopen(url, timeout=10) as resp:
                body = resp.read().decode()
            assert body in names, f"query {i}: unexpected reply {body!r}"
            seen[body] = seen.get(body, 0) + 1
        assert set(seen) == set(names), f"round-robin missed pods: {seen}"
    finally:
        if proxy is not None:
            proxy.stop()
        cluster.stop()
