"""CRUD + validation tests for the registry resources added for parity
with the reference's pkg/registry/ set: serviceaccounts, limitranges,
resourcequotas, persistentvolumes, persistentvolumeclaims, podtemplates,
componentstatuses (pkg/master/master.go:460-494)."""

import pytest

from kubernetes_tpu.models import objects as O
from kubernetes_tpu.models.serde import from_wire, to_wire
from kubernetes_tpu.models.validation import ValidationError
from kubernetes_tpu.server.api import APIError, APIServer


@pytest.fixture
def api():
    return APIServer()


def test_serviceaccount_crud(api):
    sa = {"kind": "ServiceAccount", "metadata": {"name": "default"}}
    created = api.create("serviceaccounts", "default", sa)
    assert created["metadata"]["uid"]
    got = api.get("serviceaccounts", "default", "default")
    assert got["metadata"]["name"] == "default"
    lst = api.list("serviceaccounts", "default")
    assert len(lst["items"]) == 1


def test_limitrange_crud_and_validation(api):
    lr = {
        "kind": "LimitRange",
        "metadata": {"name": "limits"},
        "spec": {
            "limits": [
                {
                    "type": "Container",
                    "max": {"cpu": "2", "memory": "1Gi"},
                    "min": {"cpu": "100m"},
                    "default": {"cpu": "500m", "memory": "256Mi"},
                }
            ]
        },
    }
    api.create("limitranges", "default", lr)
    got = api.get("limitranges", "default", "limits")
    assert got["spec"]["limits"][0]["max"]["cpu"] == "2"

    bad = {
        "kind": "LimitRange",
        "metadata": {"name": "bad"},
        "spec": {"limits": [{"type": "Container", "min": {"cpu": "4"}, "max": {"cpu": "1"}}]},
    }
    with pytest.raises(APIError):
        api.create("limitranges", "default", bad)


def test_resourcequota_crud(api):
    rq = {
        "kind": "ResourceQuota",
        "metadata": {"name": "quota"},
        "spec": {"hard": {"cpu": "20", "memory": "64Gi", "pods": "10"}},
    }
    api.create("resourcequotas", "default", rq)
    got = api.get("resourcequotas", "default", "quota")
    assert got["spec"]["hard"]["pods"] == "10"
    # alias
    assert api.list("quota", "default")["items"]


def test_persistentvolume_validation_and_crud(api):
    pv = {
        "kind": "PersistentVolume",
        "metadata": {"name": "pv0001"},
        "spec": {
            "capacity": {"storage": "10Gi"},
            "accessModes": ["ReadWriteOnce"],
            "persistentVolumeSource": {"hostPath": {"path": "/tmp/pv0001"}},
        },
    }
    api.create("persistentvolumes", "", pv)
    got = api.get("persistentvolumes", "", "pv0001")
    assert got["spec"]["capacity"]["storage"] == "10Gi"

    with pytest.raises(APIError):
        # no source set
        api.create(
            "persistentvolumes",
            "",
            {
                "kind": "PersistentVolume",
                "metadata": {"name": "pv-bad"},
                "spec": {"capacity": {"storage": "1Gi"}, "accessModes": ["ReadWriteOnce"]},
            },
        )


def test_pvc_crud(api):
    pvc = {
        "kind": "PersistentVolumeClaim",
        "metadata": {"name": "claim1"},
        "spec": {
            "accessModes": ["ReadWriteOnce"],
            "resources": {"requests": {"storage": "3Gi"}},
        },
    }
    api.create("persistentvolumeclaims", "default", pvc)
    got = api.get("persistentvolumeclaims", "default", "claim1")
    assert got.get("status", {}).get("phase", "Pending") == "Pending"


def test_podtemplate_and_componentstatus(api):
    tmpl = {
        "kind": "PodTemplate",
        "metadata": {"name": "web-template"},
        "template": {
            "metadata": {"labels": {"app": "web"}},
            "spec": {"containers": [{"name": "c", "image": "nginx"}]},
        },
    }
    api.create("podtemplates", "default", tmpl)
    assert api.get("podtemplates", "default", "web-template")

    cs = {
        "kind": "ComponentStatus",
        "metadata": {"name": "scheduler"},
        "conditions": [{"type": "Healthy", "status": "True"}],
    }
    api.create("componentstatuses", "", cs)
    got = api.get("componentstatuses", "", "scheduler")
    assert got["conditions"][0]["status"] == "True"


def test_validate_endpoint(api):
    """GET /validate probes every registered component and reports
    per-component health, 500 when any is down (pkg/apiserver/
    validator.go)."""
    import json
    import urllib.error
    import urllib.request

    from kubernetes_tpu.server.httpserver import APIHTTPServer

    api.register_component("scheduler", lambda: (True, "ok"))
    api.register_component("controller-manager", lambda: (False, "dead"))
    srv = APIHTTPServer(api).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(f"{srv.address}/validate", timeout=5)
        assert e.value.code == 500
        report = json.load(e.value)["validate"]
        byname = {r["component"]: r for r in report}
        assert byname["scheduler"]["health"] == "ok"
        assert byname["controller-manager"]["health"] == "unhealthy"

        api.register_component("controller-manager", lambda: (True, "ok"))
        with urllib.request.urlopen(f"{srv.address}/validate", timeout=5) as resp:
            assert resp.status == 200
    finally:
        srv.stop()


def test_watch_new_resources(api):
    stream = api.watch("resourcequotas", "default")
    api.create(
        "resourcequotas",
        "default",
        {"kind": "ResourceQuota", "metadata": {"name": "q"}, "spec": {"hard": {"pods": "5"}}},
    )
    ev = stream.next(timeout=2.0)
    assert ev is not None and ev.type == "ADDED"
    assert ev.object["metadata"]["name"] == "q"
    stream.close()


def test_roundtrip_typed_objects():
    pv = O.PersistentVolume(
        metadata=O.ObjectMeta(name="pv1"),
        spec=O.PersistentVolumeSpec(
            capacity={"storage": O.Quantity.from_int(10 * 1024**3)},
            access_modes=["ReadWriteOnce"],
            persistent_volume_source=O.PersistentVolumeSource(
                host_path=O.HostPathVolumeSource(path="/tmp/x")
            ),
        ),
    )
    wire = to_wire(pv)
    back = from_wire(O.PersistentVolume, wire)
    assert isinstance(back, O.PersistentVolume)
    assert back.spec.persistent_volume_source.host_path.path == "/tmp/x"

    lr = O.LimitRange(
        metadata=O.ObjectMeta(name="lr", namespace="default"),
        spec=O.LimitRangeSpec(
            limits=[
                O.LimitRangeItem(
                    type="Container",
                    max={"cpu": O.Quantity.from_milli(2000)},
                )
            ]
        ),
    )
    back = from_wire(O.LimitRange, to_wire(lr))
    assert back.spec.limits[0].max["cpu"].milli_value() == 2000


def test_validation_error_collects():
    with pytest.raises(ValidationError) as ei:
        from kubernetes_tpu.models import validation as V

        V.validate_persistent_volume(
            O.PersistentVolume(metadata=O.ObjectMeta(name="Bad_Name"))
        )
    assert len(ei.value.errors) >= 2
