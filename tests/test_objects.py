"""Object model codec + validation tests (reference: pkg/api/)."""

import pytest

from kubernetes_tpu.models import (
    Container,
    ContainerPort,
    Node,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    ReplicationController,
    ReplicationControllerSpec,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.models.objects import (
    KINDS,
    PodTemplateSpec,
    ResourceRequirements,
)
from kubernetes_tpu.models.quantity import parse_quantity
from kubernetes_tpu.models.serde import from_wire, to_wire
from kubernetes_tpu.models.validation import (
    ValidationError,
    validate_pod,
    validate_replication_controller,
    validate_service,
)


def make_pod(name="p1", ns="default", cpu="100m", mem="64Mi", **spec_kw):
    return Pod(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=PodSpec(
            containers=[
                Container(
                    name="main",
                    image="nginx",
                    resources=ResourceRequirements(
                        requests={"cpu": parse_quantity(cpu), "memory": parse_quantity(mem)}
                    ),
                )
            ],
            **spec_kw,
        ),
    )


def test_pod_wire_roundtrip():
    pod = make_pod(node_selector={"disk": "ssd"})
    wire = to_wire(pod)
    assert wire["kind"] == "Pod"
    assert wire["metadata"]["name"] == "p1"
    assert wire["spec"]["nodeSelector"] == {"disk": "ssd"}
    assert wire["spec"]["containers"][0]["resources"]["requests"]["cpu"] == "100m"
    back = from_wire(Pod, wire)
    assert back.metadata.name == "p1"
    assert back.spec.node_selector == {"disk": "ssd"}
    assert back.spec.containers[0].resources.requests["cpu"].milli_value() == 100
    assert back.spec.containers[0].resources.requests["memory"].value() == 64 * 1024**2


def test_unknown_fields_ignored():
    pod = from_wire(Pod, {"metadata": {"name": "x", "futureField": 1}, "spec": {}})
    assert pod.metadata.name == "x"


def test_omit_empty():
    wire = to_wire(Pod(metadata=ObjectMeta(name="x")))
    assert "nodeName" not in wire.get("spec", {})
    assert "labels" not in wire["metadata"]


def test_node_capacity_roundtrip():
    node = Node(
        metadata=ObjectMeta(name="n1"),
        status=NodeStatus(
            capacity={"cpu": parse_quantity("4"), "memory": parse_quantity("8Gi")}
        ),
    )
    back = from_wire(Node, to_wire(node))
    assert back.status.capacity["cpu"].milli_value() == 4000
    assert back.status.capacity["memory"].value() == 8 * 1024**3


def test_kind_registry():
    assert KINDS["Pod"] is Pod
    assert KINDS["Minion"] is Node  # legacy alias


def test_validate_pod_ok():
    validate_pod(make_pod())


def test_validate_pod_errors():
    bad = Pod(metadata=ObjectMeta(name="UPPER", namespace="default"))
    with pytest.raises(ValidationError) as exc:
        validate_pod(bad)
    msgs = " ".join(exc.value.errors)
    assert "invalid name" in msgs
    assert "containers" in msgs


def test_validate_pod_duplicate_ports_container_names():
    pod = make_pod()
    pod.spec.containers.append(
        Container(name="main", image="x", ports=[ContainerPort(container_port=0)])
    )
    with pytest.raises(ValidationError) as exc:
        validate_pod(pod)
    assert any("duplicate" in e for e in exc.value.errors)


def test_validate_service():
    svc = Service(
        metadata=ObjectMeta(name="s1", namespace="default"),
        spec=ServiceSpec(ports=[ServicePort(port=80)], selector={"app": "web"}),
    )
    validate_service(svc)
    svc.spec.ports = []
    with pytest.raises(ValidationError):
        validate_service(svc)


def test_validate_rc():
    pod = make_pod()
    rc = ReplicationController(
        metadata=ObjectMeta(name="rc1", namespace="default"),
        spec=ReplicationControllerSpec(
            replicas=3,
            selector={"app": "web"},
            template=PodTemplateSpec(
                metadata=ObjectMeta(labels={"app": "web"}), spec=pod.spec
            ),
        ),
    )
    validate_replication_controller(rc)
    rc.spec.selector = {"app": "other"}
    with pytest.raises(ValidationError):
        validate_replication_controller(rc)
