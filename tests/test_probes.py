"""Probe transports (exec/HTTP/TCP) + readiness gating Endpoints.

Reference: pkg/probe/{exec,http,tcp}/, pkg/kubelet/prober/prober.go,
readiness feeding the endpoints controller (VERDICT r1 #9: a failing
readiness probe must remove the pod from Endpoints WITHOUT restarting
it)."""

import http.server
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.client.cache import Informer
from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.controllers.endpoints import EndpointsController
from kubernetes_tpu.kubelet.agent import Kubelet
from kubernetes_tpu.kubelet.probes import (
    ProbeTracker,
    probe_http,
    probe_tcp,
    run_probe,
)
from kubernetes_tpu.kubelet.runtime import FakeRuntime
from kubernetes_tpu.models.objects import (
    Container,
    HTTPGetAction,
    ObjectMeta,
    Pod,
    PodSpec,
    Probe,
    TCPSocketAction,
)
from kubernetes_tpu.server.api import APIServer


def wait_for(cond, timeout=6.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


@pytest.fixture
def http_server():
    class Handler(http.server.BaseHTTPRequestHandler):
        healthy = True

        def log_message(self, *a):
            pass

        def do_GET(self):
            code = 200 if (Handler.healthy or self.path != "/healthz") else 503
            body = b"ok" if code == 200 else b"sick"
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv, Handler
    srv.shutdown()
    srv.server_close()


class TestProbeTransports:
    def test_http_probe_2xx_healthy(self, http_server):
        srv, handler = http_server
        assert probe_http("127.0.0.1", srv.server_address[1], "/healthz", 1.0)

    def test_http_probe_5xx_unhealthy(self, http_server):
        srv, handler = http_server
        handler.healthy = False
        assert not probe_http("127.0.0.1", srv.server_address[1], "/healthz", 1.0)
        handler.healthy = True

    def test_http_probe_connection_refused(self):
        # Grab a port and close it -> nothing listens there.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        assert not probe_http("127.0.0.1", port, "/", 0.5)

    def test_tcp_probe(self, http_server):
        srv, _ = http_server
        assert probe_tcp("127.0.0.1", srv.server_address[1], 1.0)
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        assert not probe_tcp("127.0.0.1", port, 0.5)

    def test_run_probe_dispatch(self, http_server):
        srv, _ = http_server
        pod = Pod(metadata=ObjectMeta(name="p", uid="p"))
        rt = FakeRuntime()
        http_probe = Probe(
            http_get=HTTPGetAction(port=srv.server_address[1], path="/")
        )
        tcp_probe = Probe(tcp_socket=TCPSocketAction(port=srv.server_address[1]))
        assert run_probe(http_probe, pod, "c", rt)
        assert run_probe(tcp_probe, pod, "c", rt)
        assert run_probe(Probe(), pod, "c", rt)  # no action = success


class TestProbeTracker:
    def test_liveness_threshold(self):
        t = ProbeTracker()
        assert not t.liveness("k", False)
        assert not t.liveness("k", False)
        assert t.liveness("k", False)  # third consecutive failure
        assert not t.liveness("k", False)  # counter reset after kill

    def test_liveness_resets_on_success(self):
        t = ProbeTracker()
        t.liveness("k", False)
        t.liveness("k", False)
        t.liveness("k", True)
        assert not t.liveness("k", False)
        assert not t.liveness("k", False)

    def test_initial_delay(self):
        t = ProbeTracker()
        t.note_started("k", time.monotonic())
        assert t.in_initial_delay("k", Probe(initial_delay_seconds=60))
        assert not t.in_initial_delay("k", Probe(initial_delay_seconds=0))
        t.note_started("k", time.monotonic() - 120)
        assert not t.in_initial_delay("k", Probe(initial_delay_seconds=60))


# ---------------------------------------------------------------------------
# apiserver /healthz: JSON subchecks with per-check status
# ---------------------------------------------------------------------------


class TestApiserverHealthz:
    """/healthz upgraded from a bare "ok" to JSON subchecks — kvstore,
    watch hub, flight-recorder ring — so an operator (or a probe that
    parses bodies) sees WHICH dependency is sick, not just that one
    is."""

    def test_healthz_json_subchecks_all_ok(self):
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        srv = APIHTTPServer(api).start()
        try:
            with urllib.request.urlopen(
                srv.address + "/healthz", timeout=10
            ) as resp:
                assert resp.status == 200
                body = json.loads(resp.read())
        finally:
            srv.stop()
        assert body["kind"] == "Health"
        assert body["status"] == "ok"
        checks = body["checks"]
        assert set(checks) == {"kvstore", "watchHub", "flightRecorder"}
        for check in checks.values():
            assert check["status"] == "ok"
        assert checks["kvstore"]["resourceVersion"] >= 0
        fr = checks["flightRecorder"]
        assert 0 <= fr["decisions"] <= fr["capacity"]

    def test_healthz_unhealthy_store_is_503(self):
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        api = APIServer()
        srv = APIHTTPServer(api).start()
        try:
            api.store.close()  # degrade: the kvstore subcheck must trip
            with pytest.raises(urllib.error.HTTPError) as excinfo:
                urllib.request.urlopen(srv.address + "/healthz", timeout=10)
            assert excinfo.value.code == 503
            body = json.loads(excinfo.value.read())
        finally:
            srv.stop()
        assert body["status"] == "unhealthy"
        assert body["checks"]["kvstore"]["status"] == "unhealthy"


# ---------------------------------------------------------------------------
# Readiness gates Endpoints without restarting the pod
# ---------------------------------------------------------------------------


@pytest.fixture
def cluster():
    api = APIServer()
    client = Client(LocalTransport(api))
    runtime = FakeRuntime()
    kubelet = Kubelet(
        Client(LocalTransport(api)),
        node_name="node-1",
        runtime=runtime,
        heartbeat_period=0.5,
        sync_period=0.2,
    ).start()
    endpoints = EndpointsController(
        Client(LocalTransport(api)), sync_period=0.2
    ).start()
    yield api, client, kubelet, runtime
    endpoints.stop()
    kubelet.stop()


class TestReadinessGatesEndpoints:
    def test_failing_readiness_removes_from_endpoints_without_restart(
        self, cluster
    ):
        api, client, kubelet, runtime = cluster
        client.create(
            "services",
            {
                "kind": "Service",
                "metadata": {"name": "web", "namespace": "default"},
                "spec": {
                    "selector": {"app": "web"},
                    "ports": [{"name": "http", "port": 80}],
                    "clusterIP": "10.0.0.10",
                },
            },
            namespace="default",
        )
        client.create(
            "pods",
            {
                "kind": "Pod",
                "metadata": {
                    "name": "w1",
                    "namespace": "default",
                    "labels": {"app": "web"},
                },
                "spec": {
                    "nodeName": "node-1",
                    "containers": [
                        {
                            "name": "main",
                            "image": "web",
                            "readinessProbe": {
                                "exec": {"command": ["/bin/check"]}
                            },
                        }
                    ],
                },
            },
            namespace="default",
        )

        def endpoint_count():
            try:
                ep = client.get("endpoints", "web", namespace="default")
            except Exception:
                return -1
            return sum(
                len(s.addresses) for s in ep.subsets
            ) if ep.subsets else 0

        # Probe passes (FakeRuntime default) -> pod becomes ready and
        # lands in Endpoints.
        assert wait_for(lambda: endpoint_count() == 1)
        pod = client.get("pods", "w1", namespace="default")
        uid = pod.metadata.uid
        restarts_before = runtime.list_pods()[uid][0].restart_count

        # Readiness starts failing: pod leaves Endpoints but is NOT
        # restarted (readiness never kills; prober.go).
        runtime.set_probe_result(uid, "main", False)
        assert wait_for(lambda: endpoint_count() == 0)
        pod = client.get("pods", "w1", namespace="default")
        assert pod.status.phase == "Running"
        assert runtime.list_pods()[uid][0].restart_count == restarts_before
        assert runtime.list_pods()[uid][0].state == "running"

        # Recovers: back into Endpoints.
        runtime.set_probe_result(uid, "main", True)
        assert wait_for(lambda: endpoint_count() == 1)
