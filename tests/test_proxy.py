"""Service dataplane tests (reference behaviors: pkg/proxy/
proxier_test.go, roundrobin_test.go) — real sockets end to end."""

import socket
import socketserver
import threading
import time

import pytest

from kubernetes_tpu.client.rest import Client, LocalTransport
from kubernetes_tpu.models.objects import (
    EndpointAddress,
    EndpointPort,
    Endpoints,
    EndpointSubset,
    ObjectMeta,
    Service,
    ServicePort,
    ServiceSpec,
)
from kubernetes_tpu.models import serde
from kubernetes_tpu.proxy import (
    EndpointsConfig,
    LoadBalancerRR,
    Proxier,
    ProxyServer,
    ServiceConfig,
)
from kubernetes_tpu.proxy.roundrobin import (
    ErrMissingEndpoints,
    ErrMissingServiceEntry,
)
from kubernetes_tpu.server.api import APIServer


# -- backends ---------------------------------------------------------


class _EchoTCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _TCPHandler(socketserver.BaseRequestHandler):
    def handle(self):
        while True:
            data = self.request.recv(4096)
            if not data:
                return
            self.request.sendall(self.server.tag + data)


@pytest.fixture
def tcp_backends():
    servers = []
    for tag in (b"A:", b"B:"):
        srv = _EchoTCP(("127.0.0.1", 0), _TCPHandler)
        srv.tag = tag
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        servers.append(srv)
    yield servers
    for srv in servers:
        srv.shutdown()
        srv.server_close()


def _endpoints(name, ports_addrs, ns="default", portname=""):
    return Endpoints(
        metadata=ObjectMeta(name=name, namespace=ns),
        subsets=[
            EndpointSubset(
                addresses=[EndpointAddress(ip=ip) for ip, _ in ports_addrs],
                ports=[EndpointPort(name=portname, port=ports_addrs[0][1])],
            )
        ]
        if ports_addrs and len({p for _, p in ports_addrs}) == 1
        else [
            EndpointSubset(
                addresses=[EndpointAddress(ip=ip)],
                ports=[EndpointPort(name=portname, port=port)],
            )
            for ip, port in ports_addrs
        ],
    )


def _service(name, cluster_ip, port, ns="default", affinity="None", portname=""):
    return Service(
        metadata=ObjectMeta(name=name, namespace=ns),
        spec=ServiceSpec(
            cluster_ip=cluster_ip,
            session_affinity=affinity,
            ports=[ServicePort(name=portname, protocol="TCP", port=port)],
        ),
    )


def _roundtrip(addr, payload=b"hi"):
    with socket.create_connection(addr, timeout=5) as s:
        s.sendall(payload)
        return s.recv(4096)


# -- LoadBalancerRR ---------------------------------------------------


class TestLoadBalancerRR:
    def test_missing_service(self):
        lb = LoadBalancerRR()
        with pytest.raises(ErrMissingServiceEntry):
            lb.next_endpoint(("default", "svc", ""))

    def test_missing_endpoints(self):
        lb = LoadBalancerRR()
        lb.new_service(("default", "svc", ""))
        with pytest.raises(ErrMissingEndpoints):
            lb.next_endpoint(("default", "svc", ""))

    def test_round_robin_rotation(self):
        lb = LoadBalancerRR()
        lb.on_update([_endpoints("svc", [("1.1.1.1", 1), ("2.2.2.2", 2)])])
        key = ("default", "svc", "")
        got = [lb.next_endpoint(key) for _ in range(4)]
        assert got == ["1.1.1.1:1", "2.2.2.2:2", "1.1.1.1:1", "2.2.2.2:2"]

    def test_client_ip_affinity(self):
        lb = LoadBalancerRR()
        lb.new_service(("default", "svc", ""), affinity_type="ClientIP")
        lb.on_update([_endpoints("svc", [("1.1.1.1", 1), ("2.2.2.2", 2)])])
        key = ("default", "svc", "")
        first = lb.next_endpoint(key, client_ip="9.9.9.9")
        # Same client sticks; another client rotates.
        assert lb.next_endpoint(key, client_ip="9.9.9.9") == first
        other = lb.next_endpoint(key, client_ip="8.8.8.8")
        assert other != first
        assert lb.next_endpoint(key, client_ip="9.9.9.9") == first

    def test_endpoints_removed_on_delete(self):
        lb = LoadBalancerRR()
        lb.on_update([_endpoints("svc", [("1.1.1.1", 1)])])
        lb.on_update([])  # endpoints object deleted
        with pytest.raises(ErrMissingEndpoints):
            lb.next_endpoint(("default", "svc", ""))

    def test_dropped_named_port_clears_its_endpoints(self):
        """Removing one named port from an Endpoints object clears that
        port's list even though the object still carries other ports."""
        lb = LoadBalancerRR()
        both = Endpoints(
            metadata=ObjectMeta(name="svc", namespace="default"),
            subsets=[
                EndpointSubset(
                    addresses=[EndpointAddress(ip="1.1.1.1")],
                    ports=[EndpointPort(name="http", port=80),
                           EndpointPort(name="metrics", port=9090)],
                )
            ],
        )
        lb.on_update([both])
        assert lb.next_endpoint(("default", "svc", "metrics")) == "1.1.1.1:9090"
        only_http = Endpoints(
            metadata=ObjectMeta(name="svc", namespace="default"),
            subsets=[
                EndpointSubset(
                    addresses=[EndpointAddress(ip="1.1.1.1")],
                    ports=[EndpointPort(name="http", port=80)],
                )
            ],
        )
        lb.on_update([only_http])
        with pytest.raises(ErrMissingEndpoints):
            lb.next_endpoint(("default", "svc", "metrics"))
        assert lb.next_endpoint(("default", "svc", "http")) == "1.1.1.1:80"


# -- Proxier over real TCP -------------------------------------------


class TestProxierTCP:
    def test_portal_roundtrip_and_rotation(self, tcp_backends):
        proxier = Proxier()
        eps = [
            ("127.0.0.1", srv.server_address[1]) for srv in tcp_backends
        ]
        proxier.lb.on_update([_endpoints("web", eps)])
        proxier.on_update([_service("web", "10.0.0.1", 80)])
        try:
            target = proxier.rules.resolve("10.0.0.1", 80, "TCP")
            assert target is not None
            replies = {_roundtrip(target) for _ in range(4)}
            assert replies == {b"A:hi", b"B:hi"}  # both backends hit
        finally:
            proxier.stop()

    def test_dead_backend_retry(self, tcp_backends):
        """A connection-refused endpoint is skipped for the session
        (reference: proxysocket.go tryConnect)."""
        proxier = Proxier()
        live = ("127.0.0.1", tcp_backends[0].server_address[1])
        dead_sock = socket.socket()
        dead_sock.bind(("127.0.0.1", 0))
        dead_port = dead_sock.getsockname()[1]
        dead_sock.close()  # now nothing listens there
        proxier.lb.on_update(
            [_endpoints("web", [("127.0.0.1", dead_port), live])]
        )
        proxier.on_update([_service("web", "10.0.0.1", 80)])
        try:
            target = proxier.rules.resolve("10.0.0.1", 80, "TCP")
            for _ in range(3):
                assert _roundtrip(target) == b"A:hi"
        finally:
            proxier.stop()

    def test_service_removal_closes_portal(self, tcp_backends):
        proxier = Proxier()
        eps = [("127.0.0.1", tcp_backends[0].server_address[1])]
        proxier.lb.on_update([_endpoints("web", eps)])
        proxier.on_update([_service("web", "10.0.0.1", 80)])
        target = proxier.rules.resolve("10.0.0.1", 80, "TCP")
        assert target is not None
        proxier.on_update([])  # service deleted
        try:
            assert proxier.rules.resolve("10.0.0.1", 80, "TCP") is None
            # The listener is gone. A raw connect may still "succeed"
            # via Linux's ephemeral-port self-connect quirk, but the
            # backend can no longer be reached through it.
            try:
                reply = _roundtrip(target)
                assert not reply.startswith(b"A:")
            except OSError:
                pass
        finally:
            proxier.stop()

    def test_session_affinity_sticks(self, tcp_backends):
        proxier = Proxier()
        eps = [
            ("127.0.0.1", srv.server_address[1]) for srv in tcp_backends
        ]
        proxier.lb.on_update([_endpoints("web", eps)])
        proxier.on_update(
            [_service("web", "10.0.0.1", 80, affinity="ClientIP")]
        )
        try:
            target = proxier.rules.resolve("10.0.0.1", 80, "TCP")
            tags = {_roundtrip(target)[:2] for _ in range(4)}
            assert len(tags) == 1  # same client ip -> same backend
        finally:
            proxier.stop()


class TestProxierUDP:
    def test_udp_echo(self):
        backend = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        backend.bind(("127.0.0.1", 0))
        backend.settimeout(5)

        def udp_echo():
            while True:
                try:
                    data, addr = backend.recvfrom(4096)
                except OSError:
                    return
                backend.sendto(b"U:" + data, addr)

        threading.Thread(target=udp_echo, daemon=True).start()
        proxier = Proxier()
        port = backend.getsockname()[1]
        svc = _service("dns", "10.0.0.2", 53)
        svc.spec.ports[0].protocol = "UDP"
        ep = _endpoints("dns", [("127.0.0.1", port)])
        ep.subsets[0].ports[0].protocol = "UDP"
        proxier.lb.on_update([ep])
        proxier.on_update([svc])
        try:
            target = proxier.rules.resolve("10.0.0.2", 53, "UDP")
            assert target is not None
            c = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            c.settimeout(5)
            c.sendto(b"ping", target)
            data, _ = c.recvfrom(4096)
            assert data == b"U:ping"
            c.close()
        finally:
            proxier.stop()
            backend.close()


# -- Full daemon against in-process apiserver ------------------------


class TestProxyServer:
    def test_watch_driven_dataplane(self, tcp_backends):
        api = APIServer()
        client = Client(LocalTransport(api))
        server = ProxyServer(client).start()
        try:
            svc = _service("web", "10.0.0.201", 80)
            client.create("services", serde.to_wire(svc))
            eps = _endpoints(
                "web",
                [("127.0.0.1", s.server_address[1]) for s in tcp_backends],
            )
            client.create("endpoints", serde.to_wire(eps))
            deadline = time.monotonic() + 5
            target = None
            while time.monotonic() < deadline:
                target = server.resolve_portal("10.0.0.201", 80)
                if target and server.lb.endpoints_for(("default", "web", "")):
                    break
                time.sleep(0.05)
            assert target is not None
            replies = {_roundtrip(target) for _ in range(4)}
            assert replies == {b"A:hi", b"B:hi"}
            # Deleting the service tears the portal down via watch.
            client.delete("services", "web", namespace="default")
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if server.resolve_portal("10.0.0.201", 80) is None:
                    break
                time.sleep(0.05)
            assert server.resolve_portal("10.0.0.201", 80) is None
        finally:
            server.stop()


class TestRealPortals:
    """VIP-bound portals (proxy/portal.py): the service cluster IP is
    installed on loopback and the listener binds clusterIP:port, so a
    plain socket dial of the VIP reaches the backends — the
    openPortal/iptables analog made literal."""

    @pytest.fixture(autouse=True)
    def _need_netadmin(self):
        from kubernetes_tpu.proxy.portal import LoopbackPortals

        if not LoopbackPortals.supported():
            pytest.skip("needs CAP_NET_ADMIN to install lo addresses")

    def test_dial_the_vip_directly(self, tcp_backends):
        api = APIServer()
        client = Client(LocalTransport(api))
        server = ProxyServer(client, real_portals=True).start()
        vip = "10.0.0.222"
        try:
            svc = _service("real", vip, 7080)
            client.create("services", serde.to_wire(svc))
            eps = _endpoints(
                "real",
                [("127.0.0.1", s.server_address[1]) for s in tcp_backends],
            )
            client.create("endpoints", serde.to_wire(eps))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                info = server.proxier.service_info(("default", "real", ""))
                if info is not None and server.lb.endpoints_for(
                    ("default", "real", "")
                ):
                    break
                time.sleep(0.05)
            assert info is not None and info.real, "portal not real-bound"
            # THE point: dial the VIP itself.
            replies = {_roundtrip((vip, 7080)) for _ in range(4)}
            assert replies == {b"A:hi", b"B:hi"}
        finally:
            server.stop()
        # Teardown removed the VIP from loopback. (No negative dial
        # check: this sandbox's egress gateway transparently accepts
        # arbitrary connects, so only the interface state is ours.)
        import subprocess

        show = subprocess.run(
            ["ip", "addr", "show", "dev", "lo"], capture_output=True, text=True
        )
        assert vip not in show.stdout

    def test_fallback_when_vip_port_taken(self, tcp_backends):
        """A bind failure degrades to the rule-table portal, not a
        dead service."""
        from kubernetes_tpu.proxy.portal import LoopbackPortals

        api = APIServer()
        client = Client(LocalTransport(api))
        vip = "10.0.0.223"
        portals = LoopbackPortals()
        assert portals.acquire(vip)
        squatter = socket.socket()
        squatter.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            squatter.bind((vip, 7081))
            squatter.listen(1)
            server = ProxyServer(client, real_portals=True).start()
            try:
                svc = _service("fb", vip, 7081)
                client.create("services", serde.to_wire(svc))
                eps = _endpoints(
                    "fb",
                    [("127.0.0.1", s.server_address[1]) for s in tcp_backends],
                )
                client.create("endpoints", serde.to_wire(eps))
                deadline = time.monotonic() + 5
                target = info = None
                while time.monotonic() < deadline:
                    target = server.resolve_portal(vip, 7081)
                    if target and server.lb.endpoints_for(("default", "fb", "")):
                        info = server.proxier.service_info(("default", "fb", ""))
                        if info is not None:
                            break
                    time.sleep(0.05)
                assert target is not None and info is not None
                assert not info.real
                assert _roundtrip(target) in (b"A:hi", b"B:hi")
            finally:
                server.stop()
        finally:
            squatter.close()
            portals.release(vip)


class TestNodePortListener:
    """NodePort services get a REAL listener at nodeAddr:nodePort (the
    analog of the reference's openNodePort iptables redirect), not just
    a rule-table entry."""

    def test_node_port_accepts_traffic(self, tcp_backends):
        api = APIServer()
        client = Client(LocalTransport(api))
        server = ProxyServer(client).start()
        try:
            svc = _service("np", "10.0.0.230", 80)
            svc.spec.type = "NodePort"
            svc.spec.ports[0].node_port = 31234
            client.create("services", serde.to_wire(svc))
            eps = _endpoints(
                "np",
                [("127.0.0.1", s.server_address[1]) for s in tcp_backends],
            )
            client.create("endpoints", serde.to_wire(eps))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                info = server.proxier.service_info(("default", "np", ""))
                if (
                    info is not None
                    and info.node_socket is not None
                    and server.lb.endpoints_for(("default", "np", ""))
                ):
                    break
                time.sleep(0.05)
            assert info is not None and info.node_socket is not None
            replies = {_roundtrip(("127.0.0.1", 31234)) for _ in range(4)}
            assert replies == {b"A:hi", b"B:hi"}
        finally:
            server.stop()
        # Listener released with the service (lingering TIME_WAIT
        # client connections can defeat an immediate rebind probe, so
        # assert on the socket object itself).
        assert info.node_socket.fileno() == -1

    def test_node_port_bind_heals_after_squatter_exits(self, tcp_backends):
        squatter = socket.socket()
        squatter.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        squatter.bind(("127.0.0.1", 31235))
        squatter.listen(1)
        api = APIServer()
        client = Client(LocalTransport(api))
        server = ProxyServer(client).start()
        try:
            svc = _service("heal", "10.0.0.231", 80)
            svc.spec.type = "NodePort"
            svc.spec.ports[0].node_port = 31235
            client.create("services", serde.to_wire(svc))
            eps = _endpoints(
                "heal",
                [("127.0.0.1", tcp_backends[0].server_address[1])],
            )
            client.create("endpoints", serde.to_wire(eps))
            deadline = time.monotonic() + 5
            info = None
            while time.monotonic() < deadline:
                info = server.proxier.service_info(("default", "heal", ""))
                if info is not None:
                    break
                time.sleep(0.05)
            assert info is not None and info.node_socket is None  # degraded
            squatter.close()  # port frees up
            # The periodic service resync retries the bind; force one.
            server.proxier.on_update(
                server.service_config.informer.store.list()
            )
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                info = server.proxier.service_info(("default", "heal", ""))
                if info is not None and info.node_socket is not None:
                    break
                time.sleep(0.05)
            assert info.node_socket is not None
            assert _roundtrip(("127.0.0.1", 31235)) == b"A:hi"
        finally:
            server.stop()
