"""Sharded-solve correctness over a multi-device mesh.

conftest.py forces an 8-device virtual CPU platform, so every test
here exercises real jax.sharding.Mesh partitioning: the node axis of
the solver state is sharded, XLA SPMD inserts the argmax reduce +
all-gather collectives, and the assignment must BIT-MATCH the
single-device solve (and the scalar oracle) on identical snapshots.
Meshes come from the session `host_mesh` fixture — the sanctioned
ops.matrices.host_mesh seam, the same one sessions and the
KT_MESH_DEVICES hatch use.

Reference seam being validated: the scheduler hot loop
(plugin/pkg/scheduler/generic_scheduler.go:106-171) re-expressed as a
node-sharded scan — SURVEY.md §2.15 / §7 step 7.
"""

import jax
import numpy as np
import pytest

from kubernetes_tpu.models.columnar import build_snapshot
from kubernetes_tpu.ops import device_snapshot
from kubernetes_tpu.ops.solver import solve_assignments
from kubernetes_tpu.scheduler.batch import parity_report, schedule_backlog_scalar

from tests.test_solver_parity import random_cluster


def _solve_on_mesh(snap, mesh):
    n_devices = mesh.devices.size
    dsnap = device_snapshot(snap, mesh=mesh, pad_to=max(8, n_devices))
    with mesh:
        return solve_assignments(dsnap)


class TestShardedBitParity:
    """Sharded solve must equal the unsharded solve exactly."""

    @pytest.mark.parametrize("n_devices", [2, 4, 8])
    @pytest.mark.parametrize("seed", range(4))
    def test_mesh_matches_single_device(self, n_devices, seed, host_mesh):
        pods, nodes, assigned, services = random_cluster(seed)
        snap = build_snapshot(pods, nodes, assigned_pods=assigned, services=services)
        single = solve_assignments(device_snapshot(snap))
        sharded = _solve_on_mesh(snap, host_mesh(n_devices))
        np.testing.assert_array_equal(single, sharded)

    @pytest.mark.parametrize("seed", range(4))
    def test_mesh_matches_scalar_oracle(self, seed, host_mesh):
        """End-to-end: 8-way sharded solve vs the Go-semantics oracle."""
        pods, nodes, assigned, services = random_cluster(100 + seed)
        scalar = schedule_backlog_scalar(pods, nodes, assigned, services)
        snap = build_snapshot(pods, nodes, assigned_pods=assigned, services=services)
        assignment = _solve_on_mesh(snap, host_mesh(8))
        node_names = [n.metadata.name for n in nodes]
        batch = [node_names[a] if a >= 0 else None for a in assignment]
        parity, mismatches = parity_report(scalar, batch)
        assert parity == 1.0, f"mismatches: {mismatches[:5]}"


@pytest.mark.ktmesh
class TestRuntimeStaticCrossCheck:
    """The executed module's collective inventory must equal ktmesh's
    static prediction for the same kernel at the same bucket — the
    bridge between `--mesh-analysis` (compile-only, abstract avals) and
    what a real sharded solve actually runs. If GSPMD partitions real
    staged arrays differently from the contract-sharded avals, the
    static budgets are fiction; this test is what makes them evidence.
    """

    def test_solver_inventory_matches_static_prediction(self, host_mesh):
        from kubernetes_tpu.ops import contracts as C
        from tools.ktlint import ktmesh

        mesh = host_mesh(8)
        pods, nodes, _assigned, _services = random_cluster(7)
        snap = build_snapshot(pods, nodes)
        dsnap = device_snapshot(snap, mesh=mesh)

        # AOT-lower the REAL staged (sharded) arrays, execute the very
        # module whose text we inventory, and sanity-check its output
        # against the dispatch-path solve.
        kern = C.resolve_kernel("solver._solve_xla")
        with mesh:
            compiled = kern.lower(
                dsnap.pods, dsnap.nodes, dsnap.weights, dsnap.lowered
            ).compile()
            out = compiled(dsnap.pods, dsnap.nodes)
            out.block_until_ready()
            reference = solve_assignments(dsnap)
        np.testing.assert_array_equal(
            np.asarray(out)[: dsnap.n_pods], reference
        )
        observed = C.collective_inventory(compiled.as_text())

        # ktmesh's prediction at the bucket we ACTUALLY executed:
        # bindings read off the staged shapes, not the probe defaults.
        bindings = {
            "P": dsnap.pods["cpu"].shape[0],
            "N": dsnap.nodes["cpu_cap"].shape[0],
            "LW": dsnap.pods["sel"].shape[1],
            "PW": dsnap.pods["port"].shape[1],
            "VW": dsnap.pods["vol_any"].shape[1],
            "K": dsnap.pods["svc_ids"].shape[1],
            "S": dsnap.nodes["svc_counts"].shape[1],
        }
        predicted = ktmesh.static_inventory(
            "solver._solve_xla", mesh, bindings
        )
        assert observed["counts"] == predicted["counts"], (
            f"runtime inventory {observed['counts']} != static "
            f"prediction {predicted['counts']} at {bindings}"
        )
        assert observed["bytes"] == predicted["bytes"]
        # A node-sharded scan is not collective-free: the cross-check
        # must be comparing real communication, not two empty dicts.
        assert observed["total"] > 0


class TestDryrunEntrypoints:
    def test_dryrun_multichip_inproc(self):
        """The driver-visible entry point, on the in-process path
        (enough virtual devices exist under conftest)."""
        import __graft_entry__ as g

        g.dryrun_multichip(8)

    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        out.block_until_ready()
        assert np.asarray(out).ndim == 1


@pytest.mark.slow
class TestShardedParityAtScale:
    """VERDICT r3 next #4: sharded evidence above toy shapes. 5000
    pods x 1037 nodes on the 8-device mesh — node count deliberately
    NOT divisible by the mesh (padding rows live on the last shard),
    the synthetic workload's 64 distinct hostPorts cross the 32-bit
    bitset word boundary, and an extra volume-carrying cohort pushes
    the exclusive-volume vocab past one word too."""

    N_PODS = 5000
    N_NODES = 1037  # prime-ish: 1037 = 17 * 61, not divisible by 8

    @pytest.fixture(scope="class")
    def big_snap(self):
        from __graft_entry__ import _synthetic_objects
        from kubernetes_tpu.models.objects import (
            GCEPersistentDiskVolumeSource, Volume,
        )

        pods, nodes, services = _synthetic_objects(
            self.N_PODS, self.N_NODES, seed=77
        )
        # Volume cohort: 40 distinct exclusive disks (> one 32-bit
        # word) spread over the last 200 pods, some read-write.
        for i, pod in enumerate(pods[-200:]):
            pod.spec.volumes = [
                Volume(
                    name="data",
                    gce_persistent_disk=GCEPersistentDiskVolumeSource(
                        pd_name=f"disk-{i % 40}", read_only=(i % 3 != 0)
                    ),
                )
            ]
        return build_snapshot(pods, nodes, services=services)

    def test_scan_bit_parity_at_scale(self, big_snap, host_mesh):
        single = solve_assignments(device_snapshot(big_snap))
        sharded = _solve_on_mesh(big_snap, host_mesh(8))
        np.testing.assert_array_equal(single, sharded)
        assert int((single >= 0).sum()) == self.N_PODS

    def test_wave_deterministic_and_matches_single_at_scale(
        self, big_snap, host_mesh
    ):
        from kubernetes_tpu.ops.wave import solve_waves

        mesh = host_mesh(8)
        dsnap = device_snapshot(big_snap, mesh=mesh, pad_to=8)
        with mesh:
            out1, w1 = solve_waves(dsnap.pods, dsnap.nodes)
            out1.block_until_ready()
            out2, _ = solve_waves(dsnap.pods, dsnap.nodes)
            out2.block_until_ready()
        a1 = np.asarray(out1)[: dsnap.n_pods]
        np.testing.assert_array_equal(a1, np.asarray(out2)[: dsnap.n_pods])
        from kubernetes_tpu.ops.wave import wave_assignments

        single, _ = wave_assignments(device_snapshot(big_snap))
        a1 = np.where(a1 >= dsnap.n_nodes, -1, a1)
        np.testing.assert_array_equal(single, a1)

    def test_sinkhorn_deterministic_and_matches_single_at_scale(
        self, big_snap, host_mesh
    ):
        """Sinkhorn at the same realistic sharded shape as scan/wave
        (closing the last toy-shape-only mode): deterministic across
        runs and identical to the single-device solve."""
        from kubernetes_tpu.ops.sinkhorn import (
            sinkhorn_assignments,
            solve_sinkhorn,
        )

        mesh = host_mesh(8)
        dsnap = device_snapshot(big_snap, mesh=mesh, pad_to=8)
        with mesh:
            out1, _ = solve_sinkhorn(dsnap.pods, dsnap.nodes)
            out1.block_until_ready()
            out2, _ = solve_sinkhorn(dsnap.pods, dsnap.nodes)
            out2.block_until_ready()
        a1 = np.asarray(out1)[: dsnap.n_pods]
        np.testing.assert_array_equal(a1, np.asarray(out2)[: dsnap.n_pods])
        single, _ = sinkhorn_assignments(device_snapshot(big_snap))
        a1 = np.where(a1 >= dsnap.n_nodes, -1, a1)
        np.testing.assert_array_equal(single, a1)


@pytest.mark.slow
class TestShardedNorthStar:
    """VERDICT r4 #5: the north-star shape itself, sharded. 50k pods x
    5k nodes on the 8-device mesh for the wave and sinkhorn solvers
    (and the scan when the host can afford it), asserting equality
    with the single-device solve — kills the last 'proven only at a
    smaller shape' asterisk in the multi-chip story (the reference's
    analog is its density/load ladder, test/e2e/load.go)."""

    N_PODS = 50_000
    N_NODES = 5_000

    @pytest.fixture(scope="class")
    def star_snap(self):
        from __graft_entry__ import _synthetic_objects

        pods, nodes, services = _synthetic_objects(
            self.N_PODS, self.N_NODES, seed=5
        )
        return build_snapshot(pods, nodes, services=services)

    def test_wave_matches_single_device(self, star_snap, host_mesh):
        from kubernetes_tpu.ops.wave import solve_waves, wave_assignments

        mesh = host_mesh(8)
        dsnap = device_snapshot(star_snap, mesh=mesh, pad_to=8)
        with mesh:
            out, _waves = solve_waves(dsnap.pods, dsnap.nodes)
            out.block_until_ready()
        sharded = np.asarray(out)[: dsnap.n_pods]
        sharded = np.where(sharded >= dsnap.n_nodes, -1, sharded)
        single, _ = wave_assignments(device_snapshot(star_snap))
        np.testing.assert_array_equal(single, sharded)
        assert int((sharded >= 0).sum()) == self.N_PODS

    def test_sinkhorn_matches_single_device(self, star_snap, host_mesh):
        from kubernetes_tpu.ops.sinkhorn import (
            sinkhorn_assignments,
            solve_sinkhorn,
        )

        mesh = host_mesh(8)
        dsnap = device_snapshot(star_snap, mesh=mesh, pad_to=8)
        with mesh:
            out, _waves = solve_sinkhorn(dsnap.pods, dsnap.nodes)
            out.block_until_ready()
        sharded = np.asarray(out)[: dsnap.n_pods]
        sharded = np.where(sharded >= dsnap.n_nodes, -1, sharded)
        single, _ = sinkhorn_assignments(device_snapshot(star_snap))
        np.testing.assert_array_equal(single, sharded)
        assert int((sharded >= 0).sum()) == self.N_PODS
