"""Sharded-solve correctness over a multi-device mesh.

conftest.py forces an 8-device virtual CPU platform, so every test
here exercises real jax.sharding.Mesh partitioning: the node axis of
the solver state is sharded, XLA SPMD inserts the argmax reduce +
all-gather collectives, and the assignment must BIT-MATCH the
single-device solve (and the scalar oracle) on identical snapshots.

Reference seam being validated: the scheduler hot loop
(plugin/pkg/scheduler/generic_scheduler.go:106-171) re-expressed as a
node-sharded scan — SURVEY.md §2.15 / §7 step 7.
"""

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from kubernetes_tpu.models.columnar import build_snapshot
from kubernetes_tpu.ops import device_snapshot
from kubernetes_tpu.ops.solver import solve_assignments
from kubernetes_tpu.scheduler.batch import parity_report, schedule_backlog_scalar

from tests.test_solver_parity import random_cluster


def _mesh(n):
    devs = jax.devices()
    assert len(devs) >= n, f"conftest should provide 8 devices, saw {len(devs)}"
    return Mesh(np.array(devs[:n]), axis_names=("nodes",))


def _solve_on_mesh(snap, n_devices):
    mesh = _mesh(n_devices)
    dsnap = device_snapshot(snap, mesh=mesh, pad_to=max(8, n_devices))
    with mesh:
        return solve_assignments(dsnap)


class TestShardedBitParity:
    """Sharded solve must equal the unsharded solve exactly."""

    @pytest.mark.parametrize("n_devices", [2, 4, 8])
    @pytest.mark.parametrize("seed", range(4))
    def test_mesh_matches_single_device(self, n_devices, seed):
        pods, nodes, assigned, services = random_cluster(seed)
        snap = build_snapshot(pods, nodes, assigned_pods=assigned, services=services)
        single = solve_assignments(device_snapshot(snap))
        sharded = _solve_on_mesh(snap, n_devices)
        np.testing.assert_array_equal(single, sharded)

    @pytest.mark.parametrize("seed", range(4))
    def test_mesh_matches_scalar_oracle(self, seed):
        """End-to-end: 8-way sharded solve vs the Go-semantics oracle."""
        pods, nodes, assigned, services = random_cluster(100 + seed)
        scalar = schedule_backlog_scalar(pods, nodes, assigned, services)
        snap = build_snapshot(pods, nodes, assigned_pods=assigned, services=services)
        assignment = _solve_on_mesh(snap, 8)
        node_names = [n.metadata.name for n in nodes]
        batch = [node_names[a] if a >= 0 else None for a in assignment]
        parity, mismatches = parity_report(scalar, batch)
        assert parity == 1.0, f"mismatches: {mismatches[:5]}"


class TestDryrunEntrypoints:
    def test_dryrun_multichip_inproc(self):
        """The driver-visible entry point, on the in-process path
        (enough virtual devices exist under conftest)."""
        import __graft_entry__ as g

        g.dryrun_multichip(8)

    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        out.block_until_ready()
        assert np.asarray(out).ndim == 1
