// Host-side columnar lowering kernels (C ABI, loaded via ctypes).
//
// The reference is pure Go (SURVEY.md §2.14); this framework's native
// runtime layer accelerates the host half of the TPU pipeline: turning
// tens of thousands of API objects into the dense column arrays the
// solver consumes (kubernetes_tpu/models/columnar.py). Python prepares
// flat CSR-style id streams (cheap list appends); these kernels do the
// tight per-row packing/accumulation loops that dominate at 50k pods.
//
// Build: `make lib` -> build/libkubetpu.so. Python binding + fallback:
// kubernetes_tpu/native/__init__.py.

#include <cstdint>

extern "C" {

// Pack per-row id lists (CSR: counts[i] ids starting at offsets[i])
// into uint32 bitset rows: out[n_rows][words].
void pack_bitsets(int64_t n_rows, int64_t words, const int64_t* offsets,
                  const int32_t* ids, uint32_t* out) {
    for (int64_t i = 0; i < n_rows; ++i) {
        uint32_t* row = out + i * words;
        for (int64_t k = offsets[i]; k < offsets[i + 1]; ++k) {
            const int32_t id = ids[k];
            row[id >> 5] |= (uint32_t)1 << (id & 31);
        }
    }
}

// OR per-pod bitset rows into their node's row:
// node_rows[node_idx[i]] |= pod_rows[i] (skips node_idx < 0).
void or_rows_by_index(int64_t n_pods, int64_t words, const int32_t* node_idx,
                      const uint32_t* pod_rows, uint32_t* node_rows) {
    for (int64_t i = 0; i < n_pods; ++i) {
        const int32_t j = node_idx[i];
        if (j < 0) continue;
        const uint32_t* src = pod_rows + i * words;
        uint32_t* dst = node_rows + (int64_t)j * words;
        for (int64_t w = 0; w < words; ++w) dst[w] |= src[w];
    }
}

// The assigned-pod occupancy sweep (reference MapPodsToMachines /
// CheckPodsExceedingCapacity semantics, predicates.go:116-136 +
// calculateOccupancy, priorities.go:44-58): greedy feasibility sums in
// list order with an overcommit flag, plus full scoring sums.
void greedy_fit(int64_t n_pods, const int32_t* node_idx, const float* cpu,
                const float* mem, const float* cpu_cap, const float* mem_cap,
                float* cpu_fit, float* mem_fit, uint8_t* over, float* cpu_used,
                float* mem_used, float* pods_used) {
    for (int64_t i = 0; i < n_pods; ++i) {
        const int32_t j = node_idx[i];
        if (j < 0) continue;
        const float c = cpu[i], m = mem[i];
        cpu_used[j] += c;
        mem_used[j] += m;
        pods_used[j] += 1.0f;
        const bool fits_cpu = cpu_cap[j] == 0.0f || cpu_fit[j] + c <= cpu_cap[j];
        const bool fits_mem = mem_cap[j] == 0.0f || mem_fit[j] + m <= mem_cap[j];
        if (fits_cpu && fits_mem) {
            cpu_fit[j] += c;
            mem_fit[j] += m;
        } else {
            over[j] = 1;
        }
    }
}

}  // extern "C"
