/* Pod anchor ("pause") process.
 *
 * Equivalent of the reference's third_party/pause/pause.asm (57-line
 * x86-64 NASM, built into a 127-byte static ELF): the infra container
 * every pod starts first, holding the pod's namespaces/cgroup alive
 * while real containers come and go (invoked from
 * pkg/kubelet/dockertools/manager.go:1201-1202).
 *
 * Behavior: block forever in pause(2); exit cleanly on SIGINT/SIGTERM
 * so pod teardown is prompt. Build: `make pause` (static, -Os).
 */

#include <signal.h>
#include <unistd.h>

static void on_signal(int sig) {
    (void)sig;
    _exit(0);
}

int main(void) {
    struct sigaction sa = {0};
    sa.sa_handler = on_signal;
    sigaction(SIGINT, &sa, 0);
    sigaction(SIGTERM, &sa, 0);
    for (;;) {
        pause();
    }
}
