#!/usr/bin/env python3
"""Tear a kube-up cluster down (cluster/kube-down.sh analog)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from kubernetes_tpu.cmd.clusterup import down_main  # noqa: E402

sys.exit(down_main())
