#!/usr/bin/env python3
"""Bring a cluster up from an inventory (cluster/kube-up.sh analog)."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from kubernetes_tpu.cmd.clusterup import up_main  # noqa: E402

sys.exit(up_main())
