"""Scheduler benchmark — prints ONE JSON line for the driver.

Headline (BASELINE.md north star): schedule a 50k-pending-pod backlog
onto 5k nodes in < 2s wall-clock, vs the reference's sequential
~15 bindings/s ceiling (scheduler bind rate limit, factory.go:43-46).

Measures the full pipeline: columnar lowering (host) -> upload ->
jitted sequential-parity solve (device) -> assignment readback.
Compile time is excluded via a warmup solve on identical shapes.

Env overrides: BENCH_PODS, BENCH_NODES, BENCH_REPEATS,
BENCH_MODE=backlog|churn (churn = BASELINE config 5: sustained
create/delete stream against a device-resident SolverSession).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 15.0  # reference bind rate limit ceiling


def churn_main() -> None:
    """BASELINE config 5: 1k pods/s create/delete churn with
    incremental device updates (no re-lowering the cluster)."""
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    rate = int(os.environ.get("BENCH_CHURN_RATE", "1000"))  # pods/s each way
    ticks = int(os.environ.get("BENCH_CHURN_TICKS", "10"))

    import random

    from __graft_entry__ import _synthetic_problem  # noqa: F401 (warms imports)
    from kubernetes_tpu.ops import SolverSession
    from kubernetes_tpu.models.objects import (
        Container, Node, NodeCondition, NodeStatus, ObjectMeta, Pod, PodSpec,
        ResourceRequirements,
    )
    from kubernetes_tpu.models.quantity import Quantity, parse_quantity

    rng = random.Random(0)
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"n{j}"),
            status=NodeStatus(
                capacity={
                    "cpu": Quantity.from_milli(rng.choice([8000, 16000, 32000])),
                    "memory": parse_quantity(f"{rng.choice([16, 32, 64])}Gi"),
                    "pods": Quantity.from_int(110),
                },
                conditions=[NodeCondition(type="Ready", status="True")],
            ),
        )
        for j in range(n_nodes)
    ]

    def mkpod(i):
        return Pod(
            metadata=ObjectMeta(name=f"p{i}", namespace="default"),
            spec=PodSpec(
                containers=[
                    Container(
                        name="c", image="app",
                        resources=ResourceRequirements(
                            limits={
                                "cpu": Quantity.from_milli(
                                    rng.choice([100, 250, 500])
                                ),
                                "memory": parse_quantity(
                                    f"{rng.choice([64, 128, 256])}Mi"
                                ),
                            }
                        ),
                    )
                ]
            ),
        )

    session = SolverSession(nodes)
    # Warm-up tick compiles the solve + scatter executables.
    counter = 0
    live = []  # O(1) deletes via swap-with-last (don't time bookkeeping)
    for _ in range(rate):
        counter += 1
        session.add_pending(mkpod(counter))
    for key, dest in session.solve():
        if dest is not None:
            live.append(key)

    t0 = time.perf_counter()
    scheduled = 0
    for _ in range(ticks):
        for _ in range(rate):
            counter += 1
            session.add_pending(mkpod(counter))
        for _ in range(min(rate, len(live))):
            i = rng.randrange(len(live))
            live[i], live[-1] = live[-1], live[i]
            session.delete_assigned(live.pop())
        for key, dest in session.solve():
            if dest is not None:
                live.append(key)
                scheduled += 1
    elapsed = time.perf_counter() - t0
    pods_per_sec = scheduled / elapsed
    print(
        json.dumps(
            {
                "metric": f"churn_scheduled_per_sec_{n_nodes}nodes",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 1),
            }
        )
    )
    print(
        f"# churn: {ticks} ticks x {rate} create+delete/s, {scheduled} "
        f"scheduled in {elapsed:.2f}s ({len(live)} live)",
        file=sys.stderr,
    )


def main() -> None:
    n_pods = int(os.environ.get("BENCH_PODS", "50000"))
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))

    import numpy as np

    from __graft_entry__ import _synthetic_objects
    from kubernetes_tpu.models.columnar import build_snapshot
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.solver import solve

    # Warmup: compile on identical shapes (fail fast on lowering errors).
    pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=1)
    snap = build_snapshot(pods, nodes, services=services)
    d = device_snapshot(snap)
    solve(d.pods, d.nodes).block_until_ready()

    # Fixtures per repeat, built OUTSIDE the timed region: creating the
    # synthetic workload objects is test scaffolding, not framework
    # work. The timed region is the framework's full pipeline from API
    # objects to bound assignments: columnar lowering -> upload ->
    # jitted solve -> readback.
    fixtures = [
        _synthetic_objects(n_pods, n_nodes, seed=2 + r) for r in range(repeats)
    ]
    times = []
    placed = 0
    for pods, nodes, services in fixtures:
        t0 = time.perf_counter()
        snap = build_snapshot(pods, nodes, services=services)
        d = device_snapshot(snap)
        out = solve(d.pods, d.nodes)
        assignment = np.asarray(out)[: d.n_pods]
        t1 = time.perf_counter()
        times.append(t1 - t0)
        placed = int((assignment >= 0).sum())

    best = min(times)
    pods_per_sec = n_pods / best
    print(
        json.dumps(
            {
                "metric": f"pods_scheduled_per_sec_{n_pods//1000}kx{n_nodes}",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 1),
            }
        )
    )
    print(
        f"# wall {best:.3f}s for {n_pods} pods x {n_nodes} nodes "
        f"({placed} placed); times={['%.3f' % t for t in times]}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    try:
        from kubernetes_tpu import native as _native

        _native.ensure_built()  # best-effort; NumPy fallback otherwise
    except Exception:
        pass
    if os.environ.get("BENCH_MODE", "backlog") == "churn":
        churn_main()
    else:
        main()
