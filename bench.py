"""Scheduler benchmark — prints ONE JSON line for the driver.

Headline (BASELINE.md north star): schedule a 50k-pending-pod backlog
onto 5k nodes in < 2s wall-clock, vs the reference's sequential
~15 bindings/s ceiling (scheduler bind rate limit, factory.go:43-46).

Measures the full pipeline: columnar lowering (host) -> upload ->
jitted sequential-parity solve (device) -> assignment readback.
Compile time is excluded via a warmup solve on identical shapes.

Env overrides: BENCH_PODS, BENCH_NODES, BENCH_REPEATS.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 15.0  # reference bind rate limit ceiling


def main() -> None:
    n_pods = int(os.environ.get("BENCH_PODS", "50000"))
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))

    import numpy as np

    from __graft_entry__ import _synthetic_problem
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.solver import solve

    # Warmup: compile on identical shapes (cheap tiny problem first to
    # fail fast on any lowering error, then the real shape).
    snap = _synthetic_problem(n_pods, n_nodes, seed=1)
    d = device_snapshot(snap)
    solve(d.pods, d.nodes).block_until_ready()

    times = []
    placed = 0
    for r in range(repeats):
        t0 = time.perf_counter()
        snap = _synthetic_problem(n_pods, n_nodes, seed=2 + r)
        d = device_snapshot(snap)
        out = solve(d.pods, d.nodes)
        assignment = np.asarray(out)[: d.n_pods]
        t1 = time.perf_counter()
        times.append(t1 - t0)
        placed = int((assignment >= 0).sum())

    best = min(times)
    pods_per_sec = n_pods / best
    print(
        json.dumps(
            {
                "metric": f"pods_scheduled_per_sec_{n_pods//1000}kx{n_nodes}",
                "value": round(pods_per_sec, 1),
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 1),
            }
        )
    )
    print(
        f"# wall {best:.3f}s for {n_pods} pods x {n_nodes} nodes "
        f"({placed} placed); times={['%.3f' % t for t in times]}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
