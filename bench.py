"""Scheduler benchmark — prints ONE JSON line for the driver.

Headline (BASELINE.md north star): schedule a 50k-pending-pod backlog
onto 5k nodes in < 2s wall-clock, vs the reference's sequential
~15 bindings/s ceiling (scheduler bind rate limit, factory.go:43-46).

Measures the full pipeline: columnar lowering (host) -> upload ->
jitted sequential-parity solve (device) -> assignment readback.
Compile time is excluded via a warmup solve on identical shapes.

Env overrides: BENCH_PODS, BENCH_NODES, BENCH_REPEATS,
BENCH_MODE=backlog|churn (churn = BASELINE config 5: sustained
create/delete stream against a device-resident SolverSession).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_PODS_PER_SEC = 15.0  # reference bind rate limit ceiling


def _mp_context():
    """Process context for the load-generator children. NEVER fork:
    the parent runs JAX plus a dozen reflector/daemon threads, and
    os.fork() from a multithreaded process is exactly what the
    'os.fork() is incompatible with multithreaded code' RuntimeWarning
    (and the latent post-fork deadlock it warns about) is for. The
    children only do sockets/json, so a fresh interpreter via
    forkserver (spawn where unavailable) is cheap and clean."""
    import multiprocessing as mp

    try:
        return mp.get_context("forkserver")
    except ValueError:  # pragma: no cover - non-POSIX fallback
        return mp.get_context("spawn")


def _churn_figure(n_nodes: int, rate: int, ticks: int, mode: str) -> dict:
    """BASELINE config 5 measured: sustained create/delete churn with
    incremental device updates (no re-lowering the cluster). Returns
    {"churn_scheduled_per_sec": ..., ...} for embedding in any record."""
    import random

    from __graft_entry__ import _synthetic_problem  # noqa: F401 (warms imports)
    from kubernetes_tpu.ops import SolverSession
    from kubernetes_tpu.models.objects import (
        Container, Node, NodeCondition, NodeStatus, ObjectMeta, Pod, PodSpec,
        ResourceRequirements,
    )
    from kubernetes_tpu.models.quantity import Quantity, parse_quantity

    rng = random.Random(0)
    nodes = [
        Node(
            metadata=ObjectMeta(name=f"n{j}"),
            status=NodeStatus(
                capacity={
                    "cpu": Quantity.from_milli(rng.choice([8000, 16000, 32000])),
                    "memory": parse_quantity(f"{rng.choice([16, 32, 64])}Gi"),
                    "pods": Quantity.from_int(110),
                },
                conditions=[NodeCondition(type="Ready", status="True")],
            ),
        )
        for j in range(n_nodes)
    ]

    def mkpod(i):
        return Pod(
            metadata=ObjectMeta(name=f"p{i}", namespace="default"),
            spec=PodSpec(
                containers=[
                    Container(
                        name="c", image="app",
                        resources=ResourceRequirements(
                            limits={
                                "cpu": Quantity.from_milli(
                                    rng.choice([100, 250, 500])
                                ),
                                "memory": parse_quantity(
                                    f"{rng.choice([64, 128, 256])}Mi"
                                ),
                            }
                        ),
                    )
                ]
            ),
        )

    session = SolverSession(nodes, mode=mode)
    # Warm-up must compile EVERY executable the timed ticks hit: the
    # solve itself AND the delete-path row scatter at the same dirty-
    # row bucket width the ticks produce (a cold scatter compile was
    # costing ~2.4s on the first timed tick).
    counter = 0
    live = []  # O(1) deletes via swap-with-last (don't time bookkeeping)
    for warm_tick in range(2):
        for _ in range(rate):
            counter += 1
            session.add_pending(mkpod(counter))
        for _ in range(min(rate, len(live))):
            i = rng.randrange(len(live))
            live[i], live[-1] = live[-1], live[i]
            session.delete_assigned(live.pop())
        for key, dest in session.solve():
            if dest is not None:
                live.append(key)

    t0 = time.perf_counter()
    scheduled = 0
    for _ in range(ticks):
        for _ in range(rate):
            counter += 1
            session.add_pending(mkpod(counter))
        for _ in range(min(rate, len(live))):
            i = rng.randrange(len(live))
            live[i], live[-1] = live[-1], live[i]
            session.delete_assigned(live.pop())
        for key, dest in session.solve():
            if dest is not None:
                live.append(key)
                scheduled += 1
    elapsed = time.perf_counter() - t0
    pods_per_sec = scheduled / elapsed
    print(
        f"# churn: {ticks} ticks x {rate} create+delete/s, {scheduled} "
        f"scheduled in {elapsed:.2f}s ({len(live)} live)",
        file=sys.stderr,
    )
    return {
        "churn_scheduled_per_sec": round(pods_per_sec, 1),
        "churn_tick_mode": mode,
        "churn_nodes": n_nodes,
    }


class _LeanHTTP:
    """Minimal keep-alive HTTP/1.1 load driver (the wrk/hey role:
    stdlib http.client costs ~120us/op in pure-Python parsing, which
    on a 1-core host becomes the load generator starving the system
    under test). Server-side handling is unchanged — this only strips
    CLIENT-side stdlib overhead. Not a general client: no chunked
    responses, no redirects; exactly what the apiserver sends on the
    CRUD paths used here."""

    def __init__(self, address: str):
        host, port = address.split("//")[1].split(":")
        self.addr = (host, int(port))
        self.sock = None
        self.buf = b""

    def _connect(self):
        import socket

        self.sock = socket.create_connection(self.addr)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.buf = b""

    def request(self, verb: str, path: str, body: bytes = b"") -> int:
        head = (
            f"{verb} {path} HTTP/1.1\r\nHost: b\r\n"
            f"Content-Length: {len(body)}\r\n"
            + ("Content-Type: application/json\r\n" if body else "")
            + "\r\n"
        ).encode()
        for attempt in (0, 1):
            if self.sock is None:
                self._connect()
            try:
                self.sock.sendall(head + body)
                status, _rbody = self._read_response()
                return status
            except OSError:
                self.sock = None  # stale keep-alive: one retry
                if attempt:
                    raise
        raise OSError("unreachable")

    def _read_response(self):
        while b"\r\n\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise OSError("connection closed")
            self.buf += chunk
        head, self.buf = self.buf.split(b"\r\n\r\n", 1)
        lines = head.split(b"\r\n")
        status = int(lines[0].split(b" ", 2)[1])
        clen = 0
        for ln in lines[1:]:
            if ln[:15].lower() == b"content-length:":
                clen = int(ln[15:])
                break
        while len(self.buf) < clen:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise OSError("connection closed")
            self.buf += chunk
        body, self.buf = self.buf[:clen], self.buf[clen:]
        return status, body

    def close(self):
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass


def _churn_node_wire(j: int) -> dict:
    """Deterministic per-index node (same values in every process)."""
    return {
        "kind": "Node",
        "metadata": {"name": f"n{j}"},
        "status": {
            "capacity": {
                "cpu": str((8, 16, 32)[j % 3]),
                "memory": f"{(16, 32, 64)[j % 3]}Gi",
                "pods": "110",
            },
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


def _churn_pod_wire(name: str) -> dict:
    import zlib

    h = zlib.crc32(name.encode())  # deterministic across processes/runs
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "containers": [
                {
                    "name": "c",
                    "image": "app",
                    "resources": {
                        "limits": {
                            "cpu": f"{(100, 250, 500)[h % 3]}m",
                            "memory": f"{(64, 128, 256)[h // 3 % 3]}Mi",
                        }
                    },
                }
            ]
        },
    }


def _churn_load(
    address: str,
    rate: int,
    creators: int,
    warmup_s: float,
    duration_s: float,
    conn,
) -> None:
    """Load-generator process body: paced creators + deleter over lean
    HTTP, a watch stream timestamping binding visibility. Sends a
    result dict (sorted latencies for the measurement window, created
    count, window seconds) through `conn`."""
    import threading

    from kubernetes_tpu.client import Client, HTTPTransport

    stats_lock = threading.Lock()
    t_create: dict = {}
    t_bound: dict = {}
    bound_q: list = []  # names available for deletion, FIFO
    stop = threading.Event()
    errors: list = []
    path = "/api/v1/namespaces/default/pods"

    def watcher():
        client = Client(HTTPTransport(address))
        _, version = client.list("pods", namespace="default")
        stream = client.watch(
            "pods",
            namespace="default",
            since=version,
            field_selector="spec.nodeName!=",
        )
        try:
            while not stop.is_set():
                ev = stream.next(timeout=0.2)
                if ev is None:
                    if stream.closed:
                        return
                    continue
                obj = ev.object
                if not isinstance(obj, dict):
                    continue
                name = obj.get("metadata", {}).get("name")
                if not name or not obj.get("spec", {}).get("nodeName"):
                    continue
                now = time.perf_counter()
                with stats_lock:
                    if name not in t_bound:
                        t_bound[name] = now
                        bound_q.append(name)
        finally:
            stream.close()

    seq_lock = threading.Lock()
    seq = [0]

    def creator(wid):
        c = _LeanHTTP(address)
        interval = creators / rate
        next_t = time.perf_counter()
        while not stop.is_set():
            with seq_lock:
                seq[0] += 1
                name = f"c{seq[0]}"
            body = json.dumps(_churn_pod_wire(name)).encode()
            t0 = time.perf_counter()
            with stats_lock:
                t_create[name] = t0
            try:
                status = c.request("POST", path, body)
                # 409 = our own stale-keep-alive resend raced a create
                # the server already applied (names are unique per run):
                # the pod exists, which is what we wanted.
                if status >= 400 and status != 409:
                    raise RuntimeError(f"create {name}: HTTP {status}")
            except Exception as e:  # pragma: no cover
                errors.append(repr(e))
                with stats_lock:
                    t_create.pop(name, None)
                if len(errors) > 50:
                    return
            next_t += interval
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            elif delay < -2.0:
                next_t = time.perf_counter()  # fell behind: re-anchor
        c.close()

    def deleter():
        c = _LeanHTTP(address)
        interval = 1.0 / rate
        next_t = time.perf_counter()
        while not stop.is_set():
            name = None
            with stats_lock:
                # Keep a cushion of live pods so deletes never outpace
                # binds (steady-state live size ~= cushion).
                if len(bound_q) > 200:
                    name = bound_q.pop(0)
            if name is not None:
                try:
                    c.request("DELETE", f"{path}/{name}")
                except Exception:
                    pass
            next_t += interval
            delay = next_t - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            elif delay < -2.0:
                next_t = time.perf_counter()
        c.close()

    threads = [threading.Thread(target=watcher, daemon=True)]
    threads += [
        threading.Thread(target=creator, args=(w,), daemon=True)
        for w in range(creators)
    ]
    threads += [threading.Thread(target=deleter, daemon=True)]
    try:
        for t in threads:
            t.start()
        time.sleep(warmup_s)
        t_start = time.perf_counter()
        time.sleep(duration_s)
        t_end = time.perf_counter()
        # Drain: give in-flight pods a grace window to bind.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with stats_lock:
                missing = any(
                    t_start <= t0 < t_end and n not in t_bound
                    for n, t0 in t_create.items()
                )
            if not missing:
                break
            time.sleep(0.1)
        with stats_lock:
            lats = sorted(
                t_bound[n] - t0
                for n, t0 in t_create.items()
                if t_start <= t0 < t_end and n in t_bound
            )
            created = sum(
                1 for t0 in t_create.values() if t_start <= t0 < t_end
            )
        if errors:
            conn.send({"error": errors[0]})
        else:
            conn.send(
                {"lats": lats, "created": created, "window": t_end - t_start}
            )
    except Exception as e:  # pragma: no cover
        try:
            conn.send({"error": repr(e)})
        except Exception:
            pass
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=3)


def _api_churn_figure(
    n_nodes: int,
    rate: int,
    duration_s: float,
    mode: str = "scan",
    warmup_s: float = 6.0,
    creators: int = 2,
    gate_s: float = 0.0,
    microticks: bool = True,
) -> dict:
    """The OTHER half of the headline metric (VERDICT r4 #1): p99
    pod-to-bind latency + churn throughput THROUGH the real control
    plane. Pods are created/deleted over the HTTP API against a live
    apiserver; the incremental batch scheduler (its own HTTP client)
    watches, solves on-device, and commits via bulk bindings; a watch
    stream on a third HTTP connection timestamps when each binding
    becomes VISIBLE to a client. Latency = create-call-start ->
    binding-visible-via-watch, the reference's e2e definition
    (test/e2e/util.go:1286-1301); SLO: 99% < 1s (docs/roadmap.md:66).
    """
    from kubernetes_tpu.client import Client, LocalTransport, HTTPTransport
    from kubernetes_tpu.scheduler.daemon import (
        IncrementalBatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.server.httpserver import APIHTTPServer

    node_wire, pod_wire = _churn_node_wire, _churn_pod_wire

    api = APIServer()
    setup = Client(LocalTransport(api))  # fixture only, not measured
    for j in range(n_nodes):
        setup.create("nodes", node_wire(j))

    import gc

    srv = APIHTTPServer(api, max_in_flight=800).start()

    sched_client = Client(HTTPTransport(srv.address))
    config = SchedulerConfig(sched_client, raw_scheduled_cache=True).start()
    config.wait_for_sync(30.0)
    # prewarm_buckets=1024 + prewarm(): the daemon builds its session
    # and compiles every pod-bucket solve and dirty-row scatter width
    # the timed window can hit BEFORE traffic starts — a fresh bucket
    # must never stall an SLO-gated tick (SolverSession.prewarm).
    # microticks=False is the fixed-tick baseline leg: the PR-11-era
    # cadence (blocking drain window, inline commits) measured on the
    # same box for the before/after comparison BENCH artifacts record.
    sched = IncrementalBatchScheduler(
        config, mode=mode, max_batch=1024, prewarm_buckets=1024,
        microticks=microticks,
    )
    sched.prewarm()
    sched.start()

    # The load generator runs in its OWN process (the reference's e2e
    # shape: the driver is outside the system under test). On a 1-core
    # host this also keeps the driver's Python work off the control
    # plane's GIL.
    ctx = _mp_context()  # child only does sockets/json, no jax
    parent_conn, child_conn = ctx.Pipe(duplex=False)
    child = ctx.Process(
        target=_churn_load,
        args=(srv.address, rate, creators, warmup_s, duration_s, child_conn),
        daemon=True,
    )
    try:
        # The backlog phases (and the control plane just built — 5k
        # nodes of reflector caches + the daemon's session) are a
        # multi-GB heap; a gen2 GC pass over it mid-window lands
        # straight in the bind-latency p99. Freeze it all out of
        # collection consideration for the measured phase. Inside the
        # try: every exit path below unfreezes.
        gc.collect()
        gc.freeze()
        child.start()
        child_conn.close()
        if not parent_conn.poll(warmup_s + duration_s + 60):
            raise RuntimeError("load generator produced no result")
        result = parent_conn.recv()
    finally:
        child.join(timeout=10)
        if child.is_alive():
            child.terminate()
        sched.stop()
        srv.stop()
        gc.unfreeze()
    if "error" in result:
        raise RuntimeError(f"load generator failed: {result['error']}")

    lats = result["lats"]
    unbound = result["created"] - len(lats)
    window = result["window"]
    if not lats:
        raise RuntimeError("no pods bound during the measurement window")

    def pct(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))]

    p50, p99 = pct(0.50), pct(0.99)
    fig = {
        # Full-loop figure (create -> solve -> bind -> watch-visible);
        # the API-plane ingestion figure is churn_api_pods_per_sec from
        # the bulk churn drill (_bulk_churn_figure).
        "churn_bound_pods_per_sec": round(len(lats) / window, 1),
        "bind_latency_p50_s": round(p50, 4),
        "bind_latency_p99_s": round(p99, 4),
        "bind_latency_max_s": round(lats[-1], 4),
        "bind_latency_pods": len(lats),
        "bind_latency_unbound": unbound,
        "bind_latency_nodes": n_nodes,
        "bind_rate_requested": rate,
        "bind_tick_mode": mode,
        # Engine verdict (utils/slo.py BENCH_OBJECTIVES — the 100ms
        # always-resident-loop gate; gate_s>0 overrides the target):
        # the p99 gate, worsened to "burn" outright when any created
        # pod never bound — a cluster that sheds pods cannot pass its
        # latency SLO on the survivors.
        "bind_latency_slo": _slo.worst(
            _slo.verdict_for_value(
                _slo.with_target(
                    _slo.BENCH_OBJECTIVES["bind_latency_slo"], gate_s
                )
                if gate_s
                else _slo.BENCH_OBJECTIVES["bind_latency_slo"],
                p99,
            ),
            "burn" if unbound else "pass",
        ),
        "bind_latency_slo_target": (
            gate_s or _slo.BENCH_OBJECTIVES["bind_latency_slo"].target
        ),
        "bind_microticks": microticks,
    }
    # The production SLO engine's own report over this drill: the
    # apiserver ran in THIS process, so the always-on SLI collector
    # (utils/sli.py) watched every create/bind through the same
    # dispatcher feed production uses. Embedding it proves bench and
    # /debug/slo read one truth.
    report = _slo.evaluate()
    fig["slo_verdict"] = report["verdict"]
    fig["slo_report"] = {
        o["name"]: {
            k: o[k] for k in ("p50", "p99", "value", "samples", "verdict")
            if k in o
        }
        for o in report["objectives"]
    }
    print(
        f"# api-churn: {len(lats)} pods bound through HTTP control plane "
        f"in {window:.1f}s at {n_nodes} nodes — p50 {p50 * 1000:.0f}ms, "
        f"p99 {p99 * 1000:.0f}ms, max {lats[-1] * 1000:.0f}ms, "
        f"{unbound} unbound",
        file=sys.stderr,
    )
    return fig


def _bulk_churn_figure(duration_s: float = 8.0, batch: int = 1024) -> dict:
    """API-plane ingestion under sustained churn (ISSUE 6 headline):
    bulk-create and bulk-delete pods over real HTTP as fast as the
    plane accepts them, each batch one WAL group commit, with a live
    watch connection confirming every create becomes a visible ADDED
    event (counted at the byte level so the load generator, not the
    server, stays out of the measurement's way) and a final LIST
    consistency check. This measures the API/storage plane itself —
    create -> store -> watch fan-out -> delete; the solve-and-bind
    loop has its own drill (_api_churn_figure: bind latency +
    churn_bound_pods_per_sec)."""
    import multiprocessing as mp

    from kubernetes_tpu.client import Client, HTTPTransport
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.server.httpserver import APIHTTPServer

    api = APIServer()
    api.list("pods", "default")  # build the pods watch cache up front
    srv = APIHTTPServer(api, max_in_flight=800).start()
    ctx = _mp_context()
    parent_conn, child_conn = ctx.Pipe(duplex=False)
    child = ctx.Process(
        target=_bulk_churn_load,
        args=(srv.address, duration_s, batch, child_conn),
        daemon=True,
    )
    try:
        child.start()
        child_conn.close()
        if not parent_conn.poll(duration_s + 60):
            raise RuntimeError("bulk churn load generator produced no result")
        result = parent_conn.recv()
    finally:
        child.join(timeout=10)
        if child.is_alive():
            child.terminate()
    if "error" in result:
        srv.stop()
        raise RuntimeError(f"bulk churn load failed: {result['error']}")
    # Consistency: the survivors the driver didn't delete must all be
    # LISTable (read-your-writes through the watch cache).
    live = len(
        Client(HTTPTransport(srv.address)).list("pods", namespace="default")[0]
    )
    srv.stop()
    created, deleted = result["created"], result["deleted"]
    if live != created - deleted:
        raise RuntimeError(
            f"churn consistency: {created} created - {deleted} deleted "
            f"!= {live} listed"
        )
    rate = created / result["window"]
    fig = {
        "churn_api_pods_per_sec": round(rate, 1),
        "churn_api_created": created,
        "churn_api_deleted": deleted,
        "churn_api_batch": batch,
        "churn_api_watch_added_seen": result["watch_added_seen"],
        # False = the watch was dropped mid-drill (slow consumer): the
        # rate then excludes fan-out cost and must not be trusted.
        "churn_api_watch_complete": result["watch_added_seen"] >= created,
        "churn_api_slo_target": CHURN_API_SLO_PODS_PER_SEC,
        # Engine verdict (utils/slo.py). An incomplete watch means the
        # rate excludes fan-out cost — the figure can't be trusted, so
        # the verdict is at best "warn" regardless of the rate.
        "churn_api_slo": _slo.worst(
            _slo.verdict_for_value(
                _slo.BENCH_OBJECTIVES["churn_api_slo"], rate
            ),
            "pass" if result["watch_added_seen"] >= created else "warn",
        ),
    }
    print(
        f"# bulk-churn: {created} pods created + {deleted} deleted over "
        f"HTTP in {result['window']:.1f}s ({rate:.0f} pods/s each way), "
        f"{result['watch_added_seen']} ADDED frames watched, "
        f"{live} live at drain",
        file=sys.stderr,
    )
    return fig


#: Wire-form pod as a %-template: the churn load generator emits
#: request bodies by string formatting instead of dict building +
#: json.dumps — at bulk rates the driver's own serialization was
#: starving the server under test (sampled stacks showed the apiserver
#: idle in accept/readinto).
_POD_JSON_TMPL = (
    '{"kind": "Pod", "metadata": {"name": "%s", "namespace": "default"}, '
    '"spec": {"containers": [{"name": "c", "image": "app", '
    '"resources": {"limits": {"cpu": "250m", "memory": "128Mi"}}}]}}'
)


def _bulk_churn_load(address: str, duration_s: float, batch: int, conn) -> None:
    """Load-generator process body for _bulk_churn_figure: two bulk
    creator connections pipelined against one bulk deleter, plus a
    raw-socket watch counting ADDED frames on the wire (no per-event
    JSON parse — at bulk rates the stdlib client would be the
    bottleneck, not the server under test)."""
    import socket
    import threading

    host, port = address.split("//")[1].split(":")
    addr = (host, int(port))
    added = [0]
    stop = threading.Event()
    ready = threading.Event()

    def watcher():
        s = socket.create_connection(addr)
        # Deep server-side buffer (?maxsize=): one 1024-pod group
        # commit bursts 2048 events into the queue faster than any
        # consumer can be scheduled; the default 4096 bound would drop
        # this watch mid-drill.
        s.sendall(
            b"GET /api/v1/watch/namespaces/default/pods?maxsize=65536 "
            b"HTTP/1.1\r\nHost: bench\r\n\r\n"
        )
        s.settimeout(0.3)
        pattern = b'{"type": "ADDED"'
        keep = len(pattern) - 1  # tail >= pattern length double-counts
        tail = b""
        n = 0
        # Ready only once the server ANSWERED (headers parsed): the
        # watch is registered before the 200 is sent, so creators
        # released now cannot out-race registration and lose frames.
        hdr = b""
        while b"\r\n\r\n" not in hdr:
            try:
                chunk = s.recv(65536)
            except socket.timeout:
                continue
            if not chunk:
                break
            hdr += chunk
        tail = hdr.split(b"\r\n\r\n", 1)[-1][-keep:] if hdr else b""
        n += hdr.split(b"\r\n\r\n", 1)[-1].count(pattern) if hdr else 0
        added[0] = n
        ready.set()
        try:
            while not stop.is_set():
                try:
                    chunk = s.recv(1 << 20)
                except socket.timeout:
                    continue
                if not chunk:
                    break
                data = tail + chunk
                n += data.count(pattern)
                tail = data[-keep:]
                added[0] = n
        finally:
            s.close()

    wt = threading.Thread(target=watcher, daemon=True)
    wt.start()
    ready.wait(timeout=5)
    path = "/api/v1/namespaces/default/pods"
    seq_lock = threading.Lock()
    seq = [0]
    created = [0]
    deleted = [0]
    delq: list = []
    errors: list = []
    t_end = [0.0]

    def creator():
        c = _LeanHTTP(address)
        try:
            while not stop.is_set() and time.perf_counter() < t_end[0]:
                with seq_lock:
                    s0 = seq[0]
                    seq[0] += batch
                names = [f"bc{s0 + i}" for i in range(batch)]
                body = (
                    '{"items": ['
                    + ",".join(_POD_JSON_TMPL % x for x in names)
                    + "]}"
                ).encode()
                status = c.request("POST", path + ":bulk", body)
                if status != 200:
                    raise RuntimeError(f"bulk create: HTTP {status}")
                created[0] += batch
                with seq_lock:
                    delq.append(names)
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))
        finally:
            c.close()

    def deleter():
        c = _LeanHTTP(address)
        try:
            while not stop.is_set():
                names = None
                with seq_lock:
                    if len(delq) > 2:  # keep a live cushion
                        names = delq.pop(0)
                if names is None:
                    if time.perf_counter() >= t_end[0]:
                        return
                    time.sleep(0.002)
                    continue
                body = (
                    '{"names": ['
                    + ",".join(f'"{x}"' for x in names)
                    + "]}"
                ).encode()
                status = c.request("POST", path + ":bulkdelete", body)
                if status != 200:
                    raise RuntimeError(f"bulk delete: HTTP {status}")
                deleted[0] += len(names)
        except Exception as e:  # pragma: no cover
            errors.append(repr(e))
        finally:
            c.close()

    t0 = time.perf_counter()
    t_end[0] = t0 + duration_s
    threads = [threading.Thread(target=creator, daemon=True) for _ in range(2)]
    threads.append(threading.Thread(target=deleter, daemon=True))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    window = time.perf_counter() - t0
    # Watch drain: every created pod must surface as an ADDED frame.
    deadline = time.monotonic() + 10.0
    while added[0] < created[0] and time.monotonic() < deadline:
        time.sleep(0.05)
    stop.set()
    wt.join(timeout=3)
    if errors:
        conn.send({"error": errors[0]})
    else:
        conn.send(
            {
                "created": created[0],
                "deleted": deleted[0],
                "window": window,
                "watch_added_seen": added[0],
            }
        )


def apichurn_main() -> None:
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    rate = int(os.environ.get("BENCH_CHURN_RATE", "1000"))
    duration = float(os.environ.get("BENCH_CHURN_SECONDS", "10"))
    mode = os.environ.get("BENCH_CHURN_MODE", "scan")
    fig = _api_churn_figure(n_nodes, rate, duration, mode=mode)
    if os.environ.get("BENCH_BASELINE", "0") == "1":
        # Before/after leg: the SAME drill with micro-ticks off (fixed
        # drain window, inline commits, no pipeline) — the fixed-tick
        # cadence this PR replaced, measured on the same box so the
        # artifact records the comparison the acceptance gate asks for.
        base = _api_churn_figure(
            n_nodes, rate, duration, mode=mode, microticks=False
        )
        fig["fixed_tick_baseline"] = {
            k: base[k]
            for k in (
                "bind_latency_p50_s", "bind_latency_p99_s",
                "bind_latency_max_s", "churn_bound_pods_per_sec",
                "bind_latency_pods", "bind_latency_unbound",
            )
        }
    fig.update(_bulk_churn_figure())
    print(
        json.dumps(
            {
                "metric": f"churn_api_pods_per_sec_{n_nodes}nodes",
                "value": fig["churn_api_pods_per_sec"],
                "unit": "pods/s",
                "vs_baseline": round(
                    fig["churn_api_pods_per_sec"] / BASELINE_PODS_PER_SEC, 1
                ),
                **fig,
            }
        )
    )


def _soak_figure(n_nodes: int = 64, seed: int = 7) -> dict:
    """ISSUE 15: a miniature chaos soak (tools/soak.py) inside the
    bench run — hollow-node fleet, one apiserver kill -9 with WAL
    replay, one abrupt daemon kill mid-gang, then a clean measurement
    wave. The artifact carries the chaos plane's acceptance triple:
    faults injected, invariant violations (must chart at ZERO), and
    the post-fault bind p99."""
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.soak import run_soak

    artifact = run_soak(
        n_nodes=n_nodes, seed=seed,
        epochs=[
            "baseline", "apiserver_restart",
            "daemon_restart_mid_gang", "final",
        ],
        verbose=False,
    )
    fired = sum(
        s["fired"] for s in artifact["faults_injected"].values()
    )
    return {
        "soak": {
            "nodes": n_nodes,
            "seed": seed,
            "epochs": [e["epoch"] for e in artifact["epochs"]],
            "faults_injected": fired,
            "restarts": artifact["restarts"],
            "pods_bound": artifact["pods_bound"],
            "invariant_violations": len(artifact["invariant_violations"]),
            "violation_detail": artifact["invariant_violations"][:5],
            "post_fault_bind_p50_s": artifact["post_fault_bind_p50_s"],
            "post_fault_bind_p99_s": artifact["post_fault_bind_p99_s"],
            "wall_s": artifact["wall_s"],
        }
    }


def _alerts_overhead_figure() -> dict:
    """ISSUE 20: the bulk-churn drill re-run with the health plane
    LIVE — retention sampler snapshotting every registry series plus
    the burn-rate alert engine evaluating every rule as a sampler
    hook, at a cadence 10x production (0.5s vs 5s). The figure is the
    plane's own measured cost over the drill's wall, gated at <5%:
    ``timeseries_sample_seconds`` times the whole sweep INCLUDING
    hooks, so the fraction covers retention + evaluation together."""
    import time as _time

    from kubernetes_tpu.utils import alerts as _alerts
    from kubernetes_tpu.utils import timeseries as _ts

    def sample_wall() -> float:
        return sum(
            s for (_c, s, _b) in _ts.SAMPLE_SECONDS.snapshot().values()
        )

    interval_s = 0.5
    _alerts.ensure_started(interval_s=interval_s)
    wall0 = sample_wall()
    trans0 = len(_alerts.DEFAULT.transitions())
    t0 = _time.monotonic()
    try:
        fig = _bulk_churn_figure()
    finally:
        _ts.SAMPLER.stop()
    drill_wall = max(_time.monotonic() - t0, 1e-9)
    overhead = (sample_wall() - wall0) / drill_wall
    snap = _alerts.DEFAULT.snapshot()
    fig["alerts"] = {
        "rules_evaluated": len(_alerts.DEFAULT.rules),
        "evaluations": snap["evaluations"],
        "firing": snap["firing"],
        "transitions": len(_alerts.DEFAULT.transitions()) - trans0,
        "sampler_interval_s": interval_s,
        "retained_series": int(_ts.RETAINED.value()),
        "sampler_overhead_fraction": round(overhead, 5),
        "overhead_gate_fraction": 0.05,
        # The acceptance gate: the health plane must cost <5% of the
        # drill it observes (at 10x the production cadence, so the
        # production fraction is ~an order of magnitude lower still).
        "overhead_ok": overhead < 0.05,
    }
    return fig


def _failover_figure(n_nodes: int = 8, rounds: int = 5) -> dict:
    """ISSUE 19: the failover drill behind failover_to_first_bind_s —
    with a pod already trickled in, kill the active scheduler abruptly,
    activate the PREWARMED standby (informers hot, SolverSession
    built), and clock kill -> that pod's bind becoming visible.
    p50/p99 over `rounds` drills; the 1 s p99 gate is the warm-standby
    budget. Lease-expiry wait is deliberately excluded here (it is a
    configured duration, not a performance property — check.sh's
    failover smoke and tier-1 cover the e2e lease path)."""
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.scheduler.standby import WarmStandbyScheduler
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.utils import slo as _slo

    api = APIServer()

    def client():
        return Client(LocalTransport(api))

    c = client()
    for j in range(n_nodes):
        c.create("nodes", _churn_node_wire(j))
    samples = []
    active = WarmStandbyScheduler(client(), sync_timeout=120.0)
    active.activate()
    try:
        # Warm the solve path first (bucket compile) — the drill
        # measures failover on a fleet that has served traffic, which
        # is the only fleet a failover can happen on.
        c.create("pods", _churn_pod_wire("failover-warmup"))
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if c.get("pods", "failover-warmup", namespace="default"
                     ).spec.node_name:
                break
            time.sleep(0.005)
        else:
            raise RuntimeError("failover warmup pod never bound")
        for r in range(rounds):
            # Prewarm the successor BEFORE the crash — the HA deploy
            # shape (HAScheduler keeps exactly one warm non-leader).
            standby = WarmStandbyScheduler(client(), sync_timeout=120.0)
            standby.prewarm()
            active.kill()
            t0 = time.monotonic()
            name = f"failover-r{r}"
            c.create("pods", _churn_pod_wire(name))
            standby.activate()
            deadline = t0 + 60.0
            while time.monotonic() < deadline:
                pod = c.get("pods", name, namespace="default")
                if pod.spec.node_name:
                    break
                time.sleep(0.002)
            else:
                raise RuntimeError(f"failover round {r}: pod never bound")
            samples.append(time.monotonic() - t0)
            active = standby
    finally:
        active.stop()
    samples.sort()
    p50 = samples[len(samples) // 2]
    p99 = samples[min(len(samples) - 1, int(len(samples) * 0.99))]
    obj = _slo.BENCH_OBJECTIVES["failover_to_first_bind_s"]
    print(
        f"# failover: {rounds} scheduler-leader kills at {n_nodes} nodes "
        f"— kill-to-first-bind p50 {p50 * 1000:.0f}ms, "
        f"p99 {p99 * 1000:.0f}ms (gate {obj.target:.1f}s)",
        file=sys.stderr,
    )
    return {
        "failover_rounds": rounds,
        "failover_nodes": n_nodes,
        "failover_to_first_bind_p50_s": round(p50, 4),
        "failover_to_first_bind_p99_s": round(p99, 4),
        "failover_slo_target_s": obj.target,
        "failover_slo": _slo.verdict_for_value(obj, p99),
    }


def _microtick_profile_figure(n_pods: int = 24) -> dict:
    """ISSUE 13: duty-cycle / overlap-efficiency figures from a LIVE
    micro-tick daemon (utils/profiler.py, fed by the pipelined
    incremental scheduler) — an in-process trickle so every pod gets
    its own tick, read back as the p50/p99 of the two ratio series the
    acceptance gate pins in this artifact."""
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.scheduler.daemon import (
        IncrementalBatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.utils import profiler

    def node_wire(j):
        return {
            "kind": "Node", "metadata": {"name": f"prof-n{j}"},
            "status": {
                "capacity": {"cpu": "16", "memory": "32Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def pod_wire(name):
        return {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "pause",
                "resources": {"limits": {"cpu": "50m", "memory": "32Mi"}},
            }]},
        }

    # Fresh measurement window: earlier bench segments (churn / bulk /
    # apichurn) drove incremental daemons in this process and fed the
    # same process-global series — without a reset the "trickle"
    # quantiles would read back the saturated churn distribution.
    profiler.DUTY_CYCLE.reset()
    profiler.OVERLAP.reset()
    busy_base = profiler.DEVICE_BUSY.value()
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(j))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    cfg.wait_for_sync(60)
    sched = IncrementalBatchScheduler(cfg, prewarm_buckets=64)
    bound = 0
    try:
        sched.prewarm()
        sched.start()
        for i in range(n_pods):
            client.create("pods", pod_wire(f"prof-p{i}"))
            time.sleep(0.05)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", namespace="default")
            bound = sum(1 for p in pods if p.spec.node_name)
            if bound >= n_pods:
                break
            time.sleep(0.1)
    finally:
        sched.stop()
        cfg.stop()
    fig = {
        "microtick_profile_pods_bound": bound,
        "scheduler_device_busy_seconds_total": round(
            profiler.DEVICE_BUSY.value() - busy_base, 4
        ),
    }
    # NaN-guarded like phase_p50_s: an empty series must not poison
    # the JSON record.
    for key, hist in (
        ("scheduler_device_duty_cycle", profiler.DUTY_CYCLE),
        ("scheduler_overlap_efficiency", profiler.OVERLAP),
    ):
        p50, p99 = hist.quantile(0.5), hist.quantile(0.99)
        if p50 == p50:
            fig[f"{key}_p50"] = round(p50, 4)
        if p99 == p99:
            fig[f"{key}_p99"] = round(p99, 4)
    return fig


def _capacity_figure(n_pods: int = 32) -> dict:
    """ISSUE 16: capacity & fragmentation figures from a LIVE
    micro-tick daemon — an in-process cluster loaded to a meaningful
    fill so the fragmentation score and probe-shape headroom read
    back non-trivially (the acceptance gate pins both keys in this
    artifact; tools/update_readme_bench.py renders them)."""
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.scheduler.daemon import (
        IncrementalBatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.utils import capacity as capmod

    def node_wire(j):
        return {
            "kind": "Node", "metadata": {"name": f"cap-n{j}"},
            "status": {
                "capacity": {"cpu": "2", "memory": "4Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def pod_wire(name):
        return {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{
                "name": "c", "image": "pause",
                "resources": {"limits": {"cpu": "200m", "memory": "128Mi"}},
            }]},
        }

    # Fresh measurement window: earlier segments drove daemons in this
    # process and fed the same process-global monitor.
    capmod.DEFAULT.reset()
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(4):
        client.create("nodes", node_wire(j))
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    cfg.wait_for_sync(60)
    sched = IncrementalBatchScheduler(cfg)
    bound = 0
    try:
        sched.start()
        # 32 x 200m on 4 x 2000m: an ~80% cpu-tight fill, so the big
        # slice probes lose headroom while small ones keep it.
        for i in range(n_pods):
            client.create("pods", pod_wire(f"cap-p{i}"))
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", namespace="default")
            bound = sum(1 for p in pods if p.spec.node_name)
            if bound >= n_pods and capmod.DEFAULT.snapshot()["sampled"]:
                break
            time.sleep(0.1)
    finally:
        sched.stop()
        cfg.stop()
    snap = capmod.DEFAULT.snapshot()
    fig = {"capacity_pods_bound": bound}
    if snap.get("sampled"):
        fig.update(
            {
                "fragmentation_score": snap["fragmentation_score"],
                "slice_alloc_success_rate": snap[
                    "slice_alloc_success_rate"
                ],
                "capacity_samples": snap["samples"],
                "capacity_stranded_nodes": snap["stranded_node_count"],
                "cluster_headroom_pods": {
                    p["shape"]: p["headroom_pods"] for p in snap["probes"]
                },
            }
        )
    return fig


def _rebalance_figure(n_nodes: int = 4) -> dict:
    """ISSUE 17: one live defrag cycle on a deliberately fragmented
    cluster — every node carries three 1000m fillers (born bound, the
    static-pod create shape), leaving a 1000m shard per node, so the
    slice-8x2000m probe shape has ZERO headroom until the descheduler
    consolidates two shards onto one node. The acceptance gate pins
    fragmentation_score_before > fragmentation_score_after in this
    artifact; the post-defrag 2000m probe binding is the payoff."""
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.controllers.descheduler import Descheduler
    from kubernetes_tpu.scheduler.daemon import (
        IncrementalBatchScheduler,
        SchedulerConfig,
    )
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.utils import capacity as capmod
    from kubernetes_tpu.models.objects import REBALANCE_DEST_ANNOTATION
    from kubernetes_tpu.utils import rebalance as rebmod

    def node_wire(j):
        return {
            "kind": "Node", "metadata": {"name": f"reb-n{j}"},
            "status": {
                "capacity": {"cpu": "4", "memory": "8Gi", "pods": "110"},
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def pod_wire(name, cpu, node=""):
        spec = {"containers": [{
            "name": "c", "image": "pause",
            "resources": {"limits": {"cpu": cpu, "memory": "256Mi"}},
        }]}
        if node:
            spec["nodeName"] = node
        return {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": spec,
        }

    capmod.DEFAULT.reset()
    rebmod.DEFAULT.reset()
    api = APIServer()
    client = Client(LocalTransport(api))
    for j in range(n_nodes):
        client.create("nodes", node_wire(j))
    # Born-bound fillers: the only race-free way to stage an exact
    # fragmented placement (a live scheduler would pack them).
    for j in range(n_nodes):
        for k in range(3):
            client.create(
                "pods", pod_wire(f"reb-f{j}-{k}", "1", node=f"reb-n{j}")
            )
    cfg = SchedulerConfig(Client(LocalTransport(api))).start()
    cfg.wait_for_sync(60)
    sched = IncrementalBatchScheduler(cfg)
    fig: dict = {}
    try:
        sched.start()
        desched = Descheduler(
            client,
            frag_threshold=0.01,
            move_budget=8,
            disruption_cap=8,
            wait_timeout_s=10.0,
        )
        summary = desched.sync_once(force=True)
        # Let every evicted mover re-bind on its nominated node before
        # reading the payoff (the dest annotation marks movers).
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            pods, _ = client.list("pods", namespace="default")
            movers = [
                p for p in pods
                if (p.metadata.annotations or {}).get(
                    REBALANCE_DEST_ANNOTATION
                )
            ]
            if all(p.spec.node_name for p in movers):
                break
            time.sleep(0.1)
        # The payoff: a 2000m slice-shaped pod that had zero headroom
        # pre-defrag binds on the consolidated node.
        client.create("pods", pod_wire("reb-probe", "2"))
        probe_bound = False
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            probe = client.get("pods", "reb-probe", namespace="default")
            if probe.spec.node_name:
                probe_bound = True
                break
            time.sleep(0.1)
        fig = {
            "fragmentation_score_before": summary["score_before"],
            "fragmentation_score_after": summary["score_after"],
            "rebalance_improvement": summary["improvement"],
            "rebalance_moves_executed": summary["moves_executed"],
            "rebalance_probe_bound": probe_bound,
        }
        if summary["improvement"] > 0:
            fig["rebalance_moves_per_improvement"] = round(
                summary["moves_executed"] / summary["improvement"], 2
            )
    finally:
        sched.stop()
        cfg.stop()
    return fig


def churn_main() -> None:
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    rate = int(os.environ.get("BENCH_CHURN_RATE", "1000"))  # pods/s each way
    ticks = int(os.environ.get("BENCH_CHURN_TICKS", "10"))
    mode = os.environ.get("BENCH_CHURN_MODE", "scan")
    fig = _churn_figure(n_nodes, rate, ticks, mode)
    pods_per_sec = fig["churn_scheduled_per_sec"]
    print(
        json.dumps(
            {
                "metric": f"churn_scheduled_per_sec_{n_nodes}nodes",
                "value": pods_per_sec,
                "unit": "pods/s",
                "vs_baseline": round(pods_per_sec / BASELINE_PODS_PER_SEC, 1),
                "tick_mode": mode,
            }
        )
    )


def _hotspot_figure() -> dict:
    """Sinkhorn's winning regime (VERDICT r4 #9): a capacity-tight
    heterogeneous fleet — 50 big nodes every pod prefers + 950 small,
    sized so the fleet is ~85% CPU-tight. Plain waves stampede the big
    nodes and drain in dribbles (the packer admits only per-node
    capacity per wave); congestion prices meter demand so whole waves
    survive: measured ~1.9x fewer device steps, ~1.6x faster solve,
    and slightly better mean regret at equal balance."""
    import numpy as np

    from kubernetes_tpu.models import serde
    from kubernetes_tpu.models.columnar import build_snapshot
    from kubernetes_tpu.models.objects import Node, Pod
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.oracle import assignment_quality
    from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments
    from kubernetes_tpu.ops.wave import wave_assignments

    def node_wire(j):
        return {
            "kind": "Node",
            "metadata": {"name": f"h{j}"},
            "status": {
                "capacity": {
                    "cpu": "32" if j < 50 else "4",  # 50 hot + 950 small
                    "memory": "32Gi",
                    "pods": "110",
                },
                "conditions": [{"type": "Ready", "status": "True"}],
            },
        }

    def pod_wire(name):  # identical demand: maximal contention
        return {
            "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {
                "containers": [
                    {
                        "name": "c",
                        "image": "app",
                        "resources": {
                            "limits": {"cpu": "250m", "memory": "128Mi"}
                        },
                    }
                ]
            },
        }

    nodes = [serde.from_wire(Node, node_wire(j)) for j in range(1000)]
    total_milli = 50 * 32000 + 950 * 4000
    n_pods = int(total_milli * 0.85 / 250)
    pods = [
        serde.from_wire(Pod, pod_wire(f"h{i}")) for i in range(n_pods)
    ]
    snap = build_snapshot(pods, nodes)
    d = device_snapshot(snap)
    out = {"hotspot_pods": n_pods}
    for label, fn in (("wave", wave_assignments), ("sinkhorn", sinkhorn_assignments)):
        fn(d)  # warm
        t0 = time.perf_counter()
        a, w = fn(d)
        a = np.asarray(a)[: d.n_pods]
        elapsed = time.perf_counter() - t0
        q = assignment_quality(snap, a)
        out[f"hotspot_{label}_waves"] = int(w)
        out[f"hotspot_{label}_solve_s"] = round(elapsed, 3)
        out[f"hotspot_{label}_placed"] = int((a >= 0).sum())
        out[f"hotspot_{label}_mean_regret"] = round(q["mean_regret"], 2)
    print(
        f"# hotspot ({n_pods} pods, 85% tight): sinkhorn "
        f"{out['hotspot_sinkhorn_waves']} waves/"
        f"{out['hotspot_sinkhorn_solve_s']}s vs wave "
        f"{out['hotspot_wave_waves']} waves/"
        f"{out['hotspot_wave_solve_s']}s",
        file=sys.stderr,
    )
    return out


def _parity_figures() -> dict:
    """Parity evidence published with every bench run (VERDICT r1 #3).

    - BASELINE config 2 (1k x 100): device vs the scalar object-graph
      oracle — the reference semantics themselves.
    - BASELINE config 3 (10k x 1k): device vs the sequential NumPy
      oracle (exact host arithmetic replay; its equivalence to the
      scalar oracle is tested in tests/test_solver_parity.py).
    - The NORTH-STAR shape (BENCH_PODS x BENCH_NODES, 50k x 5k by
      default): device vs the NumPy oracle at full scale — the >=0.99
      number BASELINE.md demands, measured rather than extrapolated
      (VERDICT r2 item 3). BENCH_FULL_PARITY=0 skips it.
    """
    import numpy as np

    from __graft_entry__ import _synthetic_objects
    from kubernetes_tpu.models.columnar import build_snapshot
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.oracle import solve_sequential_numpy
    from kubernetes_tpu.ops.solver import solve_assignments
    from kubernetes_tpu.scheduler.batch import (
        parity_report,
        schedule_backlog_scalar,
    )

    out = {}
    pods, nodes, services = _synthetic_objects(1000, 100, seed=11)
    snap = build_snapshot(pods, nodes, services=services)
    scalar = schedule_backlog_scalar(pods, nodes, services=services)
    dev = solve_assignments(device_snapshot(snap))
    names = snap.nodes.names
    dev_names = [names[i] if i >= 0 else None for i in dev]
    out["parity_scalar_1kx100"], _ = parity_report(scalar, dev_names)

    pods, nodes, services = _synthetic_objects(10000, 1000, seed=12)
    snap = build_snapshot(pods, nodes, services=services)
    seq = solve_sequential_numpy(snap)
    d = device_snapshot(snap)
    dev = np.asarray(solve_assignments(d))
    out["parity_seq_oracle_10kx1k"] = float((seq == dev).mean())

    if os.environ.get("BENCH_FULL_PARITY", "1") != "0":
        n_pods = int(os.environ.get("BENCH_PODS", "50000"))
        n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
        pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=13)
        snap = build_snapshot(pods, nodes, services=services)
        seq = solve_sequential_numpy(snap)
        dev = np.asarray(solve_assignments(device_snapshot(snap)))
        def _k(n: int) -> str:
            return f"{n // 1000}k" if n >= 1000 else str(n)

        key = f"parity_seq_oracle_{_k(n_pods)}x{_k(n_nodes)}"
        out[key] = float((seq == dev).mean())
    # NOTE: decision-identity parity is only meaningful for the scan
    # (which replicates the oracle's lowest-index tie-break). The
    # approximate modes (wave/sinkhorn) hash their ties, so on fleets
    # full of equal-score nodes their decisions rarely coincide with
    # the oracle's pick even at equal quality — their published
    # quality figures are placed counts and load stddev instead.
    return {k: round(v, 4) for k, v in out.items()}


#: Warn-only SLO thresholds for the API-plane drills (ISSUE 6): the
#: achieved figures and these targets are BOTH recorded in the bench
#: JSON; missing a target flags "warn", never fails the run. Since
#: PR 9 the definitions live in the production SLO engine
#: (utils/slo.BENCH_OBJECTIVES) so bench and `ktctl slo` can never
#: disagree; these module constants just surface the targets.
from kubernetes_tpu.utils import slo as _slo  # noqa: E402

CHURN_API_SLO_PODS_PER_SEC = _slo.BENCH_OBJECTIVES["churn_api_slo"].target
POD_CRUD_SLO_OPS_PER_SEC = _slo.BENCH_OBJECTIVES["pod_crud_slo"].target


def _crud_pod_wire(name: str) -> dict:
    return {
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"containers": [{"name": "c", "image": "x"}]},
    }


def _crud_worker(address, wid, tasks, batch, errors) -> None:
    """One CRUD driver connection: bulk create -> LIST -> bulk update
    -> bulk delete per cycle. Module-level (not a closure) so the
    forkserver/spawn driver process can pickle its way here."""
    c = _LeanHTTP(address)
    path = "/api/v1/namespaces/default/pods"
    try:
        for i in range(tasks):
            names = [f"crud-{wid}-{i}-{j}" for j in range(batch)]
            items = [_crud_pod_wire(n) for n in names]
            st = c.request(
                "POST", path + ":bulk",
                json.dumps({"items": items}).encode(),
            )
            if st != 200:
                raise RuntimeError(f"bulk create: HTTP {st}")
            # Read: one LIST over this worker's label-less namespace
            # view (served from the watch cache's per-object
            # encodings).
            st = c.request("GET", path)
            if st != 200:
                raise RuntimeError(f"list: HTTP {st}")
            for it in items:
                it["metadata"]["labels"] = {"touched": "true"}
                it["metadata"].pop("resourceVersion", None)
            st = c.request(
                "POST", path + ":bulkupdate",
                json.dumps({"items": items}).encode(),
            )
            if st != 200:
                raise RuntimeError(f"bulk update: HTTP {st}")
            st = c.request(
                "POST", path + ":bulkdelete",
                json.dumps({"names": names}).encode(),
            )
            if st != 200:
                raise RuntimeError(f"bulk delete: HTTP {st}")
    except Exception as e:  # pragma: no cover
        errors.append(e)
    finally:
        c.close()


def _crud_drive(address, n_workers, n_tasks, batch, conn) -> None:
    """Driver process body for _crud_figure: the timed worker threads
    in their own interpreter, result over the pipe."""
    import threading

    errors: list = []
    t0 = time.perf_counter()
    threads = [
        threading.Thread(
            target=_crud_worker, args=(address, w, n_tasks, batch, errors)
        )
        for w in range(n_workers)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0
    conn.send({"elapsed": elapsed, "errors": [repr(e) for e in errors]})


def _crud_figure(n_workers: int, n_tasks: int, batch: int = 256) -> dict:
    """Master pod-CRUD throughput over real HTTP (reference:
    test/integration/master_benchmark_test.go:38-93 — -bench-pods /
    -bench-workers against a local master), driven through the BULK
    verbs: each cycle bulk-creates `batch` pods, reads them back in one
    watch-cache LIST, bulk-updates them (label touch), and bulk-deletes
    them — 4 object operations per pod, one WAL group commit per batch
    verb. `n_tasks` counts cycles per worker. Returns
    {"pod_crud_ops_per_sec": ..., ...} (ops = objects touched)."""
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.server.httpserver import APIHTTPServer

    api = APIServer()
    api.list("pods", "default")  # build the pods watch cache up front
    srv = APIHTTPServer(api).start()
    try:
        ops = 4  # create + read + update(label) + delete, per pod

        # Short warmup (primes connections/threads); a failure here
        # means the server is broken — don't run the timed section.
        errors: list = []
        _crud_worker(srv.address, "warm", 2, batch, errors)
        if errors:
            raise errors[0]

        # The timed workers run in their OWN process: the load
        # generator's JSON encode/decode must not share the control
        # plane's GIL, or the driver becomes the thing measured.
        ctx = _mp_context()
        parent_conn, child_conn = ctx.Pipe(duplex=False)
        child = ctx.Process(
            target=_crud_drive,
            args=(srv.address, n_workers, n_tasks, batch, child_conn),
            daemon=True,
        )
        child.start()
        child_conn.close()
        if not parent_conn.poll(600):
            raise RuntimeError("crud drivers produced no result")
        result = parent_conn.recv()
        child.join(timeout=10)
        if result["errors"]:
            raise RuntimeError(result["errors"][0])
        elapsed = result["elapsed"]
        total_ops = n_workers * n_tasks * batch * ops
        rate = total_ops / elapsed
        print(
            f"# crud: {n_workers} workers x {n_tasks} cycles x {batch} pods "
            f"x {ops} bulk ops in {elapsed:.2f}s over HTTP "
            f"({rate:.0f} ops/s)",
            file=sys.stderr,
        )
        return {
            "pod_crud_ops_per_sec": round(rate, 1),
            "crud_workers": n_workers,
            "crud_batch": batch,
            "pod_crud_slo_target": POD_CRUD_SLO_OPS_PER_SEC,
            # Engine verdict (utils/slo.py): the warn-severity floor —
            # identical definition production serves at /debug/slo.
            "pod_crud_slo": _slo.verdict_for_value(
                _slo.BENCH_OBJECTIVES["pod_crud_slo"], rate
            ),
        }
    finally:
        srv.stop()


def crud_main() -> None:
    n_workers = int(os.environ.get("BENCH_CRUD_WORKERS", "4"))
    n_tasks = int(os.environ.get("BENCH_CRUD_TASKS", "200"))  # per worker
    fig = _crud_figure(n_workers, n_tasks)
    print(
        json.dumps(
            {
                "metric": f"pod_crud_ops_per_sec_{n_workers}w",
                "value": fig["pod_crud_ops_per_sec"],
                "unit": "ops/s",
                "vs_baseline": 0,  # reference publishes no number
            }
        )
    )


def main() -> None:
    n_pods = int(os.environ.get("BENCH_PODS", "50000"))
    n_nodes = int(os.environ.get("BENCH_NODES", "5000"))
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))

    import numpy as np

    from __graft_entry__ import _synthetic_objects
    from kubernetes_tpu.models.columnar import build_snapshot
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.solver import solve

    import gc

    from kubernetes_tpu.ops.pipeline import solve_backlog_pipelined

    # Fast-path configuration (VERDICT r3 next #1): the wave-mode
    # chunked pipeline. chunk=25088 (2 chunks at 50k) swept best on
    # hardware — fewer chunk-boundary waves than small chunks, while
    # still overlapping chunk 2's host lowering with chunk 1's device
    # waves (single-chunk control: ~1.2s; 8192 chunks: ~1.5s;
    # 25088: ~0.89s).
    fast_mode = os.environ.get("BENCH_FAST_MODE", "wave")
    fast_chunk = int(os.environ.get("BENCH_FAST_CHUNK", "25088"))

    # Warmup: one FULL pass of each path (compile + first-execution
    # program-load costs excluded from every timed repeat).
    pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=1)
    solve_backlog_pipelined(pods, nodes, services=services)
    solve_backlog_pipelined(
        pods, nodes, services=services, mode=fast_mode, chunk=fast_chunk
    )
    snap = build_snapshot(pods, nodes, services=services)
    d = device_snapshot(snap)
    np.asarray(solve(d.pods, d.nodes))
    del snap, d

    # Fresh in-situ phase window: the warmup's observations include the
    # XLA compiles, which would swamp the p99 of the steady-state phase
    # histogram the headline repeats populate below.
    from kubernetes_tpu.utils import tracing as _tracing

    _tracing.PHASE_SECONDS.reset()

    # Each fixture is built OUTSIDE its timed region: creating the
    # synthetic workload objects is test scaffolding, not framework
    # work. The timed region is the framework's full pipeline from API
    # objects to bound assignments: columnar lowering -> upload ->
    # jitted solve -> readback. GC is paused inside the timed region
    # (single-core machine: a collection pass over 50k live API objects
    # lands directly on the critical path).
    #
    # Headline path: solve_backlog_pipelined (chunked; host lowering
    # and upload overlap the device scan; decisions bit-identical).
    times = []
    placed = 0
    for r in range(repeats):
        pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=2 + r)
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        out = solve_backlog_pipelined(pods, nodes, services=services)
        t1 = time.perf_counter()
        gc.enable()
        times.append(t1 - t0)
        placed = sum(1 for x in out if x is not None)

    # Fast path: same end-to-end contract (API objects in, bound node
    # names out), wave-family solver, quality-gated below — regret
    # bounds decide whether it may carry the headline.
    fast_times = []
    fast_placed = 0
    for r in range(repeats):
        pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=2 + r)
        gc.collect()
        gc.disable()
        t0 = time.perf_counter()
        out = solve_backlog_pipelined(
            pods, nodes, services=services, mode=fast_mode, chunk=fast_chunk
        )
        t1 = time.perf_counter()
        gc.enable()
        fast_times.append(t1 - t0)
        fast_placed = sum(1 for x in out if x is not None)

    # In-situ phase histograms (utils/tracing.PHASE_SECONDS): the
    # always-on per-phase instrumentation inside the pipeline itself,
    # captured over the headline repeats above — device timings as the
    # running system sees them, not an external stopwatch. Under async
    # dispatch "solve" is dispatch-side; device time drains into the
    # blocking "readback".
    phase_p50 = {}
    phase_p99 = {}
    _phase_keys = [k for (k,) in _tracing.PHASE_SECONDS.label_values()]
    for ph in sorted(_phase_keys):
        p50 = _tracing.PHASE_SECONDS.quantile(0.5, phase=ph)
        p99 = _tracing.PHASE_SECONDS.quantile(0.99, phase=ph)
        if p50 == p50:  # NaN-safe: keep the BENCH json strictly valid
            phase_p50[ph] = round(p50, 4)
        if p99 == p99:
            phase_p99[ph] = round(p99, 4)

    # One monolithic (unpipelined) pass for the per-phase breakdown —
    # the pipeline overlaps these phases, so they are only separable
    # when run serially.
    pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=2)
    gc.collect()
    gc.disable()
    t0 = time.perf_counter()
    snap = build_snapshot(pods, nodes, services=services)
    t1 = time.perf_counter()
    d = device_snapshot(snap)
    import jax

    jax.block_until_ready((d.pods, d.nodes))
    t2 = time.perf_counter()
    out = solve(d.pods, d.nodes)
    out.block_until_ready()
    t3 = time.perf_counter()
    np.asarray(out)
    t4 = time.perf_counter()
    gc.enable()
    phases = {
        "lower": round(t1 - t0, 3),
        "upload": round(t2 - t1, 3),
        "solve": round(t3 - t2, 3),
        "readback": round(t4 - t3, 3),
        "serial_total": round(t4 - t0, 3),
    }

    # Wave-vs-scan comparison (VERDICT r1 #6): the batched wave solver
    # against the sequential-parity scan on the same device problem.
    from kubernetes_tpu.ops.wave import wave_assignments

    pods, nodes, services = _synthetic_objects(n_pods, n_nodes, seed=2)
    snap = build_snapshot(pods, nodes, services=services)
    d = device_snapshot(snap)
    wave_assignments(d)  # warm
    gc.collect()
    t0 = time.perf_counter()
    wave_assign, waves = wave_assignments(d)
    t_wave = time.perf_counter() - t0
    t0 = time.perf_counter()
    np.asarray(solve(d.pods, d.nodes))
    t_scan = time.perf_counter() - t0
    wave_placed = int((wave_assign >= 0).sum())
    wave_stats = {
        "wave_solve_s": round(t_wave, 3),
        "scan_solve_s": round(t_scan, 3),
        "wave_speedup": round(t_scan / max(t_wave, 1e-9), 2),
        "wave_count": int(waves),
        "pods_per_wave": round(wave_placed / max(int(waves), 1), 1),
        "wave_placed": wave_placed,
    }

    # Sinkhorn-matched mode (the north star's "Hungarian/Sinkhorn"
    # framing): congestion-priced waves; published next to the plain
    # wave so the step-count and balance win is measurable.
    from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments

    sinkhorn_assignments(d)  # warm
    gc.collect()
    t0 = time.perf_counter()
    sk_assign, sk_waves = sinkhorn_assignments(d)
    t_sk = time.perf_counter() - t0
    sk_placed = int((sk_assign >= 0).sum())
    per_node = np.bincount(
        sk_assign[sk_assign >= 0], minlength=d.n_nodes
    )[: d.n_nodes]
    wave_per_node = np.bincount(
        wave_assign[wave_assign >= 0].astype(int), minlength=d.n_nodes
    )[: d.n_nodes]
    wave_stats.update(
        {
            "sinkhorn_solve_s": round(t_sk, 3),
            "sinkhorn_waves": int(sk_waves),
            "sinkhorn_placed": sk_placed,
            "sinkhorn_load_stddev": round(float(per_node.std()), 2),
            "wave_load_stddev": round(float(wave_per_node.std()), 2),
        }
    )

    # Decision quality of the approximate modes (VERDICT r2 item 4):
    # pod-order replay against the greedy oracle — mean/p99 score
    # regret and exact-greedy match rate at 10k x 1k (scores are a
    # 0-30 scale: three 0-10 priorities). Match-rate vs the scan is
    # near zero by construction (tie hashing), so regret is the
    # published quality number; tests/test_quality_regression.py
    # bounds it in CI.
    from kubernetes_tpu.ops.oracle import assignment_quality

    pods_q, nodes_q, svcs_q = _synthetic_objects(10000, 1000, seed=12)
    snap_q = build_snapshot(pods_q, nodes_q, services=svcs_q)
    d_q = device_snapshot(snap_q)
    for label, fn in (
        ("wave", wave_assignments),
        ("sinkhorn", sinkhorn_assignments),
    ):
        a, _w = fn(d_q)
        a = np.asarray(a)[: d_q.n_pods]
        q = assignment_quality(snap_q, a)
        wave_stats[f"{label}_mean_regret_10kx1k"] = round(q["mean_regret"], 3)
        wave_stats[f"{label}_p99_regret_10kx1k"] = round(q["p99_regret"], 1)
        wave_stats[f"{label}_greedy_match_10kx1k"] = round(q["greedy_match"], 3)

    # BASELINE configs 1-3 (100x10, 1k x 100, 10k x 1k): the small and
    # mid configurations through the same full pipeline — published so
    # every BASELINE row has a measured number, not just the headline.
    small_walls = {}
    for cp, cn in ((100, 10), (1000, 100), (10000, 1000)):
        pods_s, nodes_s, svcs_s = _synthetic_objects(cp, cn, seed=7)
        solve_backlog_pipelined(pods_s, nodes_s, services=svcs_s)  # warm
        pods_s, nodes_s, svcs_s = _synthetic_objects(cp, cn, seed=8)
        gc.collect()
        t0 = time.perf_counter()
        solve_backlog_pipelined(pods_s, nodes_s, services=svcs_s)
        small_walls[f"{cp}x{cn}"] = round(time.perf_counter() - t0, 4)

    # Quality gate for the fast path: regret of the CHUNKED pipeline's
    # own decisions at 10k x 1k (the bounds tests/test_quality_regression.py
    # enforces in CI: mean <= 1.5, p99 <= 5). Passing lets the fast wall
    # carry the headline; failing falls back to the parity scan's wall —
    # speed never silently buys worse placements.
    name_idx = {n.metadata.name: i for i, n in enumerate(nodes_q)}
    fast_out = solve_backlog_pipelined(
        pods_q, nodes_q, services=svcs_q, mode=fast_mode, chunk=fast_chunk
    )
    fast_a = np.array(
        [name_idx.get(x, -1) if x is not None else -1 for x in fast_out],
        dtype=np.int32,
    )
    fast_q = assignment_quality(snap_q, fast_a)
    gate_ok = fast_q["mean_regret"] <= 1.5 and fast_q["p99_regret"] <= 5.0

    parity = _parity_figures()
    best = min(times)
    best_fast = min(fast_times)
    # The parity scan is ALWAYS quality-eligible (it IS the oracle
    # semantics); the approximate fast path must both pass its regret
    # gate and actually be faster to carry the headline. With the
    # pallas scan kernel the exact path usually wins outright.
    headline = n_pods / (best_fast if (gate_ok and best_fast < best) else best)
    record = {
        "metric": f"pods_scheduled_per_sec_{n_pods//1000}kx{n_nodes}",
        "value": round(headline, 1),
        "unit": "pods/s",
        "vs_baseline": round(headline / BASELINE_PODS_PER_SEC, 1),
        "wall_fast_s": [round(t, 3) for t in fast_times],
        "fast_mode": fast_mode,
        "fast_chunk": fast_chunk,
        "fast_placed": fast_placed,
        "fast_mean_regret_10kx1k": round(fast_q["mean_regret"], 3),
        "fast_p99_regret_10kx1k": round(fast_q["p99_regret"], 1),
        "fast_quality_gate": "pass" if gate_ok else "FAIL (headline=scan)",
        "headline_path": "fast" if (gate_ok and best_fast < best) else "scan",
        "wall_s": [round(t, 3) for t in times],
        "phases_serial_s": phases,
        "phase_p50_s": phase_p50,
        "phase_p99_s": phase_p99,
        "placed": placed,
    }
    record["config_walls_s"] = small_walls
    record.update(wave_stats)
    # Sinkhorn convergence telemetry next to the phase percentiles:
    # iteration-count p50/p99 + final residual, read from the same
    # always-on flight-recorder series the running daemons observe
    # (scheduler_solve_iterations / scheduler_sinkhorn_residual were
    # fed by the sinkhorn runs above). NaN-guarded like phase_p50_s so
    # the BENCH json stays strictly valid.
    from kubernetes_tpu.utils import flightrecorder as _fr

    sk_it_p50 = _fr.SOLVE_ITERATIONS.quantile(0.5, mode="sinkhorn")
    sk_it_p99 = _fr.SOLVE_ITERATIONS.quantile(0.99, mode="sinkhorn")
    if sk_it_p50 == sk_it_p50:
        record["sinkhorn_iters_p50"] = round(sk_it_p50, 1)
    if sk_it_p99 == sk_it_p99:
        record["sinkhorn_iters_p99"] = round(sk_it_p99, 1)
    record["sinkhorn_final_residual"] = round(
        float(_fr.SINKHORN_RESIDUAL.value()), 4
    )
    record.update(parity)
    # Short witnessed churn + CRUD segments (VERDICT r3 next #3: these
    # lived only behind BENCH_MODE env vars nothing set). Kept brief;
    # the dedicated BENCH_MODE=churn|crud runs remain for full-length
    # figures.
    if os.environ.get("BENCH_SEGMENTS", "1") != "0":
        record.update(
            _churn_figure(n_nodes=n_nodes, rate=1000, ticks=3, mode="scan")
        )
        record.update(_crud_figure(n_workers=2, n_tasks=20))
        # API-plane ingestion through the bulk fast path (ISSUE 6
        # headline: one WAL group commit per batch, watch-cache reads,
        # byte-counted watch visibility) — run with the health plane
        # live so record["alerts"] carries the sampler+engine overhead
        # fraction against its <5% gate (ISSUE 20).
        record.update(_alerts_overhead_figure())
        # The headline metric's second half (VERDICT r4 #1): churn +
        # p99 pod-to-bind latency through the REAL HTTP control plane.
        record.update(
            _api_churn_figure(n_nodes=n_nodes, rate=1000, duration_s=8.0)
        )
        # Sinkhorn's winning regime (VERDICT r4 #9).
        record.update(_hotspot_figure())
        # Device duty-cycle / overlap from a live micro-tick daemon
        # (ISSUE 13 acceptance: both series appear in the artifact).
        record.update(_microtick_profile_figure())
        # Capacity & fragmentation plane (ISSUE 16 acceptance:
        # fragmentation_score / slice_alloc_success_rate appear in the
        # artifact).
        try:
            record.update(_capacity_figure())
        except Exception as e:
            record["capacity_error"] = str(e)  # never sink a bench run
        # Rebalance plane (ISSUE 17 acceptance: one live defrag cycle
        # with fragmentation_score_before > _after in the artifact).
        try:
            record.update(_rebalance_figure())
        except Exception as e:
            record["rebalance_error"] = str(e)  # never sink a bench run
        # Chaos soak (ISSUE 15): faults injected / violations=0 /
        # post-fault bind p99 must appear in the artifact.
        try:
            record.update(_soak_figure())
        except Exception as e:
            record["soak_error"] = str(e)  # must never sink a bench run
        # HA failover drill (ISSUE 19 acceptance: scheduler-leader
        # kill -> warm standby's first bind under the 1 s p99 gate).
        try:
            record.update(_failover_figure())
        except Exception as e:
            record["failover_error"] = str(e)  # never sink a bench run
    # Preemption counters ride the record alongside the per-phase
    # latency fields (phase_p50_s/phase_p99_s already carry the
    # "preempt" phase when it ran): solve outcomes by kind + victims
    # evicted, read from the scheduler's own process-global series.
    from kubernetes_tpu.scheduler import daemon as _sched_daemon

    record["preemption"] = {
        "victims_total": _sched_daemon._PREEMPT_VICTIMS.value(),
        "solve_outcomes": {
            outcome: _sched_daemon._PREEMPT_OUTCOMES.value(outcome=outcome)
            for (outcome,) in _sched_daemon._PREEMPT_OUTCOMES.label_values()
        },
    }
    # Static-analysis counters: per-rule ktlint findings ride the bench
    # record so dashboards can chart lint debt over time alongside the
    # perf series (same JSON pipeline).
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from tools import ktlint as _ktlint

        _rep = _ktlint.lint()
        record["ktlint_findings_per_rule"] = _rep.counts()
        record["ktlint_suppressed"] = len(_rep.suppressed)
        record["ktlint_baselined"] = len(_rep.baselined)
    except Exception as e:
        record["ktlint_error"] = str(e)  # lint must never sink a bench run
    # ktsan: the interprocedural lock analysis rides next to the
    # per-rule counts — cycles/contract violations must chart at ZERO;
    # the lock/edge totals show the sanitizer's coverage growing.
    try:
        from tools.ktlint import lockgraph as _lockgraph

        _lg = _lockgraph.analyze()
        record["ktsan_findings"] = {
            "cycles": len(_lg.cycles),
            "locked_contract": len(_lg.violations),
            "suppressed": _lg.suppressed,
            "locks": len(_lg.locks),
            "edges": len(_lg.edges),
        }
    except Exception as e:
        record["ktsan_error"] = str(e)
    # ktshape: the kernel contract checker's verdict rides beside the
    # ktlint/ktsan counts — findings must chart at ZERO; the shardable
    # list is the live go/no-go set for the pod-axis Mesh work
    # (ROADMAP #2), so a kernel silently falling OFF it is visible.
    try:
        from tools.ktlint import ktshape as _ktshape

        _ks = _ktshape.analyze()
        record["ktshape_contracts"] = {
            "kernels_checked": len(_ks.kernels),
            "shardable": _ks.shardable,
            "findings": len(_ks.findings),
            "errors": len(_ks.errors),
        }
    except Exception as e:
        record["ktshape_error"] = str(e)
    # ktmesh: the static SPMD budget verdict — budget findings must
    # chart at ZERO, and the collective totals show the communication
    # the declared shardings cost (drift in either is a sharding
    # regression or a stale CommBudget pin).
    try:
        from tools.ktlint import ktmesh as _ktmesh

        _km = _ktmesh.analyze()
        record["ktmesh_budgets"] = {
            "kernels_checked": len(_km.kernels),
            "collectives_total": _km.collectives_total,
            "collective_bytes_total": _km.collective_bytes_total,
            "skipped": sum(
                1 for k in _km.kernels if k["status"] == "skipped"
            ),
            "budget_findings": len(_km.findings),
            "errors": len(_km.errors),
        }
    except Exception as e:
        record["ktmesh_error"] = str(e)
    # Compile/cost ledger summary (ISSUE 13): total compile wall +
    # top-3 kernels by FLOPs/bytes from the always-on traced-jit
    # ledger the run's solves populated, next to the ktlint/ktsan
    # counts. wait_pending lets the background Compiled.cost_analysis
    # harvest land before the read.
    try:
        from kubernetes_tpu.ops import ledger as _ledger

        _ledger.DEFAULT.wait_pending(60)
        record["profiler"] = _ledger.DEFAULT.summary()
    except Exception as e:
        record["profiler_error"] = str(e)  # must never sink a bench run
    print(json.dumps(record))
    print(
        f"# fast wall best {best_fast:.3f}s ({fast_mode}, gate "
        f"{'pass' if gate_ok else 'FAIL'}), scan wall best {best:.3f}s for "
        f"{n_pods} pods x {n_nodes} nodes ({placed} placed); "
        f"fast={['%.3f' % t for t in fast_times]}; "
        f"scan={['%.3f' % t for t in times]}; "
        f"serial phases={phases}; parity={parity}",
        file=sys.stderr,
    )


if __name__ == "__main__":
    try:
        from kubernetes_tpu import native as _native

        _native.ensure_built()  # best-effort; NumPy fallback otherwise
    except Exception:
        pass
    mode = os.environ.get("BENCH_MODE", "backlog")
    if mode == "churn":
        churn_main()
    elif mode == "crud":
        crud_main()
    elif mode == "apichurn":
        apichurn_main()
    else:
        main()
