"""Object validation.

Behavioral parity with pkg/api/validation/validation.go (subset): DNS
naming rules, required fields, uniqueness constraints, port ranges.
Errors are collected (not fail-fast) like the reference's field-error
lists (pkg/util/fielderrors/).
"""

from __future__ import annotations

import re
from typing import List

from kubernetes_tpu.models.objects import (
    Node,
    Pod,
    ReplicationController,
    Service,
)

# RFC 1123 subdomain/label (reference: util.IsDNS1123Subdomain/Label).
_DNS1123_LABEL = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_DNS1123_SUBDOMAIN = re.compile(
    r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*$"
)
_LABEL_VALUE = re.compile(r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$")

RESTART_POLICIES = {"Always", "OnFailure", "Never"}
PULL_POLICIES = {"Always", "Never", "IfNotPresent"}
PROTOCOLS = {"TCP", "UDP"}


#: Quantity strings already proven parseable (bounded memo): pods in a
#: fleet reuse a handful of resource sizes, so the wire validator's
#: quantity re-parse is almost always a set hit.
_KNOWN_GOOD_QUANTITIES: set = set()


class ValidationError(ValueError):
    def __init__(self, errors: List[str]):
        self.errors = errors
        super().__init__("; ".join(errors))


def is_dns1123_label(s: str) -> bool:
    return bool(s) and len(s) <= 63 and bool(_DNS1123_LABEL.match(s))


def is_dns1123_subdomain(s: str) -> bool:
    return bool(s) and len(s) <= 253 and bool(_DNS1123_SUBDOMAIN.match(s))


def _validate_meta(meta, errs: List[str], *, namespace_required: bool = True) -> None:
    if not meta.name and not meta.generate_name:
        errs.append("metadata.name: required")
    elif meta.name and not is_dns1123_subdomain(meta.name):
        errs.append(f"metadata.name: invalid name {meta.name!r}")
    if namespace_required and not meta.namespace:
        errs.append("metadata.namespace: required")
    for k, v in (meta.labels or {}).items():
        if not _LABEL_VALUE.match(v):
            errs.append(f"metadata.labels[{k}]: invalid value {v!r}")


def _validate_containers(containers, errs: List[str]) -> None:
    if not containers:
        errs.append("spec.containers: required")
    names = set()
    for i, c in enumerate(containers):
        where = f"spec.containers[{i}]"
        if not is_dns1123_label(c.name):
            errs.append(f"{where}.name: invalid {c.name!r}")
        if c.name in names:
            errs.append(f"{where}.name: duplicate {c.name!r}")
        names.add(c.name)
        if not c.image:
            errs.append(f"{where}.image: required")
        if c.image_pull_policy and c.image_pull_policy not in PULL_POLICIES:
            errs.append(f"{where}.imagePullPolicy: invalid {c.image_pull_policy!r}")
        for p in c.ports:
            if not (0 < p.container_port < 65536):
                errs.append(f"{where}.ports: containerPort {p.container_port} invalid")
            if p.host_port and not (0 < p.host_port < 65536):
                errs.append(f"{where}.ports: hostPort {p.host_port} invalid")
            if p.protocol not in PROTOCOLS:
                errs.append(f"{where}.ports: protocol {p.protocol!r} invalid")


def validate_pod_wire(obj: dict) -> None:
    """validate_pod's wire-form twin: the SAME checks evaluated
    directly on the camelCase wire dict, skipping the typed decode.

    Exists for the bulk-create fast path: serde.from_wire + the typed
    validator cost ~60us/pod — at bulk-ingest rates the decode (whose
    result is thrown away) was the apiserver's single largest per-pod
    cost. tests/test_watchcache.py pins accept/reject parity between
    the twins on shared fixtures so they cannot drift silently.

    One deliberate strengthening: resource quantity strings are parsed
    here (the typed path parses them inside from_wire, surfacing a bad
    quantity as a 500 from the codec; the wire path reports it as a
    field error like the reference's validation does)."""
    from kubernetes_tpu.models.objects import (
        MAX_PRIORITY,
        PREEMPT_LOWER_PRIORITY,
        PREEMPT_NEVER,
    )
    from kubernetes_tpu.models.quantity import parse_quantity

    errs: List[str] = []
    meta = obj.get("metadata") or {}
    if not meta.get("name") and not meta.get("generateName"):
        errs.append("metadata.name: required")
    elif meta.get("name") and not is_dns1123_subdomain(meta["name"]):
        errs.append(f"metadata.name: invalid name {meta['name']!r}")
    if not meta.get("namespace"):
        errs.append("metadata.namespace: required")
    for k, v in (meta.get("labels") or {}).items():
        if not isinstance(v, str) or not _LABEL_VALUE.match(v):
            errs.append(f"metadata.labels[{k}]: invalid value {v!r}")
    spec = obj.get("spec") or {}
    containers = spec.get("containers") or []
    if not containers:
        errs.append("spec.containers: required")
    names = set()
    for i, c in enumerate(containers):
        where = f"spec.containers[{i}]"
        cname = c.get("name", "")
        if not is_dns1123_label(cname):
            errs.append(f"{where}.name: invalid {cname!r}")
        if cname in names:
            errs.append(f"{where}.name: duplicate {cname!r}")
        names.add(cname)
        if not c.get("image"):
            errs.append(f"{where}.image: required")
        pull = c.get("imagePullPolicy", "")
        if pull and pull not in PULL_POLICIES:
            errs.append(f"{where}.imagePullPolicy: invalid {pull!r}")
        for p in c.get("ports") or []:
            cp = p.get("containerPort", 0)
            hp = p.get("hostPort", 0)
            if not (0 < cp < 65536):
                errs.append(f"{where}.ports: containerPort {cp} invalid")
            if hp and not (0 < hp < 65536):
                errs.append(f"{where}.ports: hostPort {hp} invalid")
            if p.get("protocol", "TCP") not in PROTOCOLS:
                errs.append(
                    f"{where}.ports: protocol {p.get('protocol')!r} invalid"
                )
        for kind in ("limits", "requests"):
            for rname, q in ((c.get("resources") or {}).get(kind) or {}).items():
                q_s = str(q)
                if q_s in _KNOWN_GOOD_QUANTITIES:
                    continue  # fleets reuse a handful of sizes
                try:
                    parse_quantity(q_s)
                except (ValueError, TypeError):
                    errs.append(
                        f"{where}.resources.{kind}[{rname}]: "
                        f"invalid quantity {q!r}"
                    )
                else:
                    if len(_KNOWN_GOOD_QUANTITIES) < 4096:
                        _KNOWN_GOOD_QUANTITIES.add(q_s)
    if spec.get("restartPolicy", "Always") not in RESTART_POLICIES:
        errs.append(
            f"spec.restartPolicy: invalid {spec.get('restartPolicy')!r}"
        )
    if spec.get("preemptionPolicy", "") not in (
        "", PREEMPT_LOWER_PRIORITY, PREEMPT_NEVER
    ):
        errs.append(
            f"spec.preemptionPolicy: invalid {spec.get('preemptionPolicy')!r} "
            f"(want {PREEMPT_LOWER_PRIORITY} or {PREEMPT_NEVER})"
        )
    prio = spec.get("priority")
    if prio is not None:
        try:
            if abs(int(prio)) > MAX_PRIORITY:
                errs.append(
                    f"spec.priority: must be within ±{MAX_PRIORITY}"
                )
        except (TypeError, ValueError):
            errs.append(f"spec.priority: invalid {prio!r}")
    vol_names = set()
    for i, v in enumerate(spec.get("volumes") or []):
        vname = v.get("name", "")
        if not is_dns1123_label(vname):
            errs.append(f"spec.volumes[{i}].name: invalid {vname!r}")
        if vname in vol_names:
            errs.append(f"spec.volumes[{i}].name: duplicate {vname!r}")
        vol_names.add(vname)
    for c in containers:
        for m in c.get("volumeMounts") or []:
            if m.get("name") not in vol_names:
                errs.append(
                    f"volumeMounts: unknown volume {m.get('name')!r}"
                )
    if errs:
        raise ValidationError(errs)


def validate_pod(pod: Pod) -> None:
    from kubernetes_tpu.models.objects import (
        MAX_PRIORITY,
        PREEMPT_LOWER_PRIORITY,
        PREEMPT_NEVER,
    )

    errs: List[str] = []
    _validate_meta(pod.metadata, errs)
    _validate_containers(pod.spec.containers, errs)
    if pod.spec.restart_policy not in RESTART_POLICIES:
        errs.append(f"spec.restartPolicy: invalid {pod.spec.restart_policy!r}")
    if pod.spec.preemption_policy not in (
        "", PREEMPT_LOWER_PRIORITY, PREEMPT_NEVER
    ):
        # A typoed opt-out ("Nevr") must fail loudly, not silently
        # leave the pod preempt-capable (pod_can_preempt treats any
        # non-"Never" string as PreemptLowerPriority).
        errs.append(
            f"spec.preemptionPolicy: invalid {pod.spec.preemption_policy!r} "
            f"(want {PREEMPT_LOWER_PRIORITY} or {PREEMPT_NEVER})"
        )
    if pod.spec.priority is not None and abs(pod.spec.priority) > MAX_PRIORITY:
        errs.append(f"spec.priority: must be within ±{MAX_PRIORITY}")
    vol_names = set()
    for i, v in enumerate(pod.spec.volumes):
        if not is_dns1123_label(v.name):
            errs.append(f"spec.volumes[{i}].name: invalid {v.name!r}")
        if v.name in vol_names:
            errs.append(f"spec.volumes[{i}].name: duplicate {v.name!r}")
        vol_names.add(v.name)
    for c in pod.spec.containers:
        for m in c.volume_mounts:
            if m.name not in vol_names:
                errs.append(f"volumeMounts: unknown volume {m.name!r}")
    if errs:
        raise ValidationError(errs)


def validate_node(node: Node) -> None:
    errs: List[str] = []
    _validate_meta(node.metadata, errs, namespace_required=False)
    for k, q in (node.status.capacity or {}).items():
        if q.milli_value() < 0:
            errs.append(f"status.capacity[{k}]: must be nonnegative")
    if errs:
        raise ValidationError(errs)


def validate_service(svc: Service) -> None:
    errs: List[str] = []
    _validate_meta(svc.metadata, errs)
    if not svc.spec.ports:
        errs.append("spec.ports: required")
    for i, p in enumerate(svc.spec.ports):
        if not (0 < p.port < 65536):
            errs.append(f"spec.ports[{i}].port: invalid {p.port}")
        if p.protocol not in PROTOCOLS:
            errs.append(f"spec.ports[{i}].protocol: invalid {p.protocol!r}")
    if errs:
        raise ValidationError(errs)


def validate_replication_controller(rc: ReplicationController) -> None:
    errs: List[str] = []
    _validate_meta(rc.metadata, errs)
    if rc.spec.replicas < 0:
        errs.append("spec.replicas: must be nonnegative")
    if not rc.spec.selector:
        errs.append("spec.selector: required")
    tmpl = rc.spec.template
    if tmpl is None:
        errs.append("spec.template: required")
    else:
        labels = tmpl.metadata.labels or {}
        for k, v in rc.spec.selector.items():
            if labels.get(k) != v:
                errs.append(f"spec.template.metadata.labels: selector {k}={v} not matched")
        _validate_containers(tmpl.spec.containers, errs)
        if tmpl.spec.restart_policy != "Always":
            # Reference: RC templates must have RestartPolicy Always
            # (validation.go ValidateReplicationControllerSpec).
            errs.append("spec.template.spec.restartPolicy: must be Always")
    if errs:
        raise ValidationError(errs)


ACCESS_MODES = {"ReadWriteOnce", "ReadOnlyMany", "ReadWriteMany"}
RECLAIM_POLICIES = {"Retain", "Recycle", "Delete"}
LIMIT_TYPES = {"Pod", "Container"}


def validate_service_account(sa) -> None:
    errs: List[str] = []
    _validate_meta(sa.metadata, errs)
    if errs:
        raise ValidationError(errs)


def validate_limit_range(lr) -> None:
    """Reference: validation.go ValidateLimitRange — types unique, min<=max."""
    errs: List[str] = []
    _validate_meta(lr.metadata, errs)
    seen = set()
    for i, item in enumerate(lr.spec.limits):
        if item.type not in LIMIT_TYPES:
            errs.append(f"spec.limits[{i}].type: invalid {item.type!r}")
        if item.type in seen:
            errs.append(f"spec.limits[{i}].type: duplicate {item.type!r}")
        seen.add(item.type)
        for k, mn in (item.min or {}).items():
            mx = (item.max or {}).get(k)
            if mx is not None and mn.milli_value() > mx.milli_value():
                errs.append(f"spec.limits[{i}].min[{k}]: exceeds max")
    if errs:
        raise ValidationError(errs)


def validate_resource_quota(rq) -> None:
    errs: List[str] = []
    _validate_meta(rq.metadata, errs)
    for k, q in (rq.spec.hard or {}).items():
        if q.milli_value() < 0:
            errs.append(f"spec.hard[{k}]: must be nonnegative")
    if errs:
        raise ValidationError(errs)


def validate_persistent_volume(pv) -> None:
    """Reference: validation.go ValidatePersistentVolume."""
    errs: List[str] = []
    _validate_meta(pv.metadata, errs, namespace_required=False)
    if not pv.spec.capacity:
        errs.append("spec.capacity: required")
    if not pv.spec.access_modes:
        errs.append("spec.accessModes: required")
    for m in pv.spec.access_modes:
        if m not in ACCESS_MODES:
            errs.append(f"spec.accessModes: invalid {m!r}")
    if pv.spec.persistent_volume_reclaim_policy not in RECLAIM_POLICIES:
        errs.append(
            "spec.persistentVolumeReclaimPolicy: invalid "
            f"{pv.spec.persistent_volume_reclaim_policy!r}"
        )
    src = pv.spec.persistent_volume_source
    set_sources = [
        s
        for s in (
            src.host_path,
            src.gce_persistent_disk,
            src.aws_elastic_block_store,
            src.nfs,
            src.glusterfs,
            src.rbd,
            src.iscsi,
        )
        if s is not None
    ]
    if len(set_sources) != 1:
        errs.append("spec.persistentVolumeSource: exactly one source required")
    if errs:
        raise ValidationError(errs)


def validate_pod_group(pg) -> None:
    """Gang-scheduling group: minMember >= 1, maxMember (when set)
    covers minMember, timeout nonnegative."""
    errs: List[str] = []
    _validate_meta(pg.metadata, errs)
    if pg.spec.min_member < 1:
        errs.append("spec.minMember: must be >= 1")
    if pg.spec.max_member < 0:
        errs.append("spec.maxMember: must be nonnegative")
    elif pg.spec.max_member and pg.spec.max_member < pg.spec.min_member:
        errs.append("spec.maxMember: must cover spec.minMember")
    if pg.spec.schedule_timeout_seconds < 0:
        errs.append("spec.scheduleTimeoutSeconds: must be nonnegative")
    if errs:
        raise ValidationError(errs)


def validate_priority_class(pc) -> None:
    """PriorityClass: value within the user-definable band, policy one
    of the two enum values (empty = PreemptLowerPriority)."""
    from kubernetes_tpu.models.objects import (
        MAX_PRIORITY,
        PREEMPT_LOWER_PRIORITY,
        PREEMPT_NEVER,
    )

    errs: List[str] = []
    _validate_meta(pc.metadata, errs, namespace_required=False)
    if not isinstance(pc.value, int) or isinstance(pc.value, bool):
        errs.append("value: must be an integer")
    elif abs(pc.value) > MAX_PRIORITY:
        errs.append(f"value: must be within ±{MAX_PRIORITY}")
    if pc.preemption_policy not in ("", PREEMPT_LOWER_PRIORITY, PREEMPT_NEVER):
        errs.append(
            f"preemptionPolicy: invalid {pc.preemption_policy!r} "
            f"(want {PREEMPT_LOWER_PRIORITY} or {PREEMPT_NEVER})"
        )
    if errs:
        raise ValidationError(errs)


def validate_persistent_volume_claim(pvc) -> None:
    errs: List[str] = []
    _validate_meta(pvc.metadata, errs)
    if not pvc.spec.access_modes:
        errs.append("spec.accessModes: required")
    for m in pvc.spec.access_modes:
        if m not in ACCESS_MODES:
            errs.append(f"spec.accessModes: invalid {m!r}")
    req = pvc.spec.resources.requests or pvc.spec.resources.limits
    if "storage" not in req:
        errs.append("spec.resources: storage request required")
    if errs:
        raise ValidationError(errs)
