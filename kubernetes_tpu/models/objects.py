"""Typed API objects.

Behavioral parity with the reference's internal object model
(pkg/api/types.go): Pod, Node, Service, Endpoints, ReplicationController,
Binding, Event, Namespace, Secret, plus list/status envelope types.
Wire form is camelCase JSON via kubernetes_tpu.models.serde.

Only fields the framework actually consumes are modeled; the codec
ignores unknown wire fields so richer manifests still load.
"""

from __future__ import annotations

import random as _random
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from kubernetes_tpu.models.quantity import Quantity

# Resource names (reference: pkg/api/types.go ResourceName consts).
RESOURCE_CPU = "cpu"
RESOURCE_MEMORY = "memory"
RESOURCE_PODS = "pods"

ResourceList = Dict[str, Quantity]

#: Second-granular ISO timestamp memo: creationTimestamp stamping sits
#: on the bulk-create hot path, and strftime+gmtime per object was
#: ~6us of pure re-formatting of the same second.
_NOW_ISO = (0, "")


def now_iso() -> str:
    global _NOW_ISO
    t = int(time.time())
    if t != _NOW_ISO[0]:
        _NOW_ISO = (t, time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t)))
    return _NOW_ISO[1]


#: uid entropy: one urandom-seeded PRNG per process instead of a
#: urandom() syscall per object (uuid.uuid4 reads the kernel CSPRNG
#: every call — ~57us/pod, the single largest cost of a bulk create).
#: uids need uniqueness, not cryptographic unpredictability; the seed
#: itself still comes from the kernel.
_UID_RAND = _random.Random()


def new_uid() -> str:
    h = "%032x" % _UID_RAND.getrandbits(128)
    # uuid4-shaped (version/variant nibbles fixed) so anything parsing
    # uids as UUIDs keeps working.
    return (
        f"{h[0:8]}-{h[8:12]}-4{h[13:16]}-"
        f"{'89ab'[int(h[16], 16) & 3]}{h[17:20]}-{h[20:32]}"
    )


@dataclass
class ObjectMeta:
    """Reference: pkg/api/types.go ObjectMeta."""

    name: str = ""
    namespace: str = ""
    uid: str = ""
    resource_version: str = ""
    creation_timestamp: str = ""
    deletion_timestamp: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    generate_name: str = ""
    # Graceful-delete bookkeeping (reference: api.ObjectMeta — later
    # releases): set together with deletion_timestamp when a pod is
    # marked Terminating; the kubelet force-deletes once the stamped
    # deadline passes.
    deletion_grace_period_seconds: Optional[int] = None


@dataclass
class ListMeta:
    resource_version: str = ""


@dataclass
class TypeMeta:
    kind: str = ""
    api_version: str = ""


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    name: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass
class ResourceRequirements:
    limits: Dict[str, Quantity] = field(default_factory=dict)
    requests: Dict[str, Quantity] = field(default_factory=dict)


@dataclass
class ExecAction:
    command: List[str] = field(default_factory=list)


@dataclass
class HTTPGetAction:
    path: str = ""
    port: int = 0
    host: str = ""


@dataclass
class TCPSocketAction:
    port: int = 0


@dataclass
class Probe:
    exec: Optional[ExecAction] = None
    http_get: Optional[HTTPGetAction] = None
    tcp_socket: Optional[TCPSocketAction] = None
    initial_delay_seconds: int = 0
    timeout_seconds: int = 1


@dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclass
class VolumeMount:
    name: str = ""
    mount_path: str = ""
    read_only: bool = False


@dataclass
class Capabilities:
    add: List[str] = field(default_factory=list)
    drop: List[str] = field(default_factory=list)


@dataclass
class SecurityContext:
    """Reference: pkg/api/types.go SecurityContext (pkg/securitycontext/)."""

    privileged: bool = False
    capabilities: Optional[Capabilities] = None
    run_as_user: Optional[int] = None
    se_linux_options: Optional[Dict[str, str]] = None


@dataclass
class Container:
    """Reference: pkg/api/types.go Container."""

    name: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    working_dir: str = ""
    ports: List[ContainerPort] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    image_pull_policy: str = "IfNotPresent"
    security_context: Optional[SecurityContext] = None


@dataclass
class EmptyDirVolumeSource:
    medium: str = ""


@dataclass
class HostPathVolumeSource:
    path: str = ""


@dataclass
class GCEPersistentDiskVolumeSource:
    pd_name: str = ""
    fs_type: str = ""
    read_only: bool = False


@dataclass
class AWSElasticBlockStoreVolumeSource:
    volume_id: str = field(default="", metadata={"wire": "volumeID"})
    fs_type: str = ""
    read_only: bool = False


@dataclass
class SecretVolumeSource:
    secret_name: str = ""


@dataclass
class NFSVolumeSource:
    server: str = ""
    path: str = ""
    read_only: bool = False


@dataclass
class GitRepoVolumeSource:
    repository: str = ""
    revision: str = ""


@dataclass
class GlusterfsVolumeSource:
    endpoints_name: str = field(default="", metadata={"wire": "endpoints"})
    path: str = ""
    read_only: bool = False


@dataclass
class RBDVolumeSource:
    monitors: List[str] = field(default_factory=list)
    image: str = ""
    pool: str = "rbd"
    fs_type: str = ""
    read_only: bool = False


@dataclass
class ISCSIVolumeSource:
    target_portal: str = ""
    iqn: str = ""
    lun: int = 0
    fs_type: str = ""
    read_only: bool = False


@dataclass
class PersistentVolumeClaimVolumeSource:
    claim_name: str = ""
    read_only: bool = False


@dataclass
class Volume:
    """Reference: pkg/api/types.go Volume / VolumeSource."""

    name: str = ""
    empty_dir: Optional[EmptyDirVolumeSource] = None
    host_path: Optional[HostPathVolumeSource] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    secret: Optional[SecretVolumeSource] = None
    nfs: Optional[NFSVolumeSource] = None
    git_repo: Optional[GitRepoVolumeSource] = None
    glusterfs: Optional[GlusterfsVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = None
    iscsi: Optional[ISCSIVolumeSource] = None
    persistent_volume_claim: Optional[PersistentVolumeClaimVolumeSource] = None


@dataclass
class PodSpec:
    """Reference: pkg/api/types.go PodSpec."""

    volumes: List[Volume] = field(default_factory=list)
    containers: List[Container] = field(default_factory=list)
    restart_policy: str = "Always"
    node_selector: Dict[str, str] = field(default_factory=dict)
    node_name: str = ""
    host_network: bool = False
    service_account: str = ""
    # Priority & preemption (shape follows the later reference's
    # scheduling.k8s.io wiring): priorityClassName names a cluster
    # PriorityClass; the Priority admission plugin resolves it into
    # `priority` (and `preemption_policy`) and freezes all three.
    # None = unresolved; schedulers read it through pod_priority().
    priority_class_name: str = ""
    priority: Optional[int] = None
    preemption_policy: str = ""  # "" -> PreemptLowerPriority


@dataclass
class ContainerStatus:
    name: str = ""
    state: Dict[str, Any] = field(default_factory=dict)
    ready: bool = False
    restart_count: int = 0
    image: str = ""
    container_id: str = field(default="", metadata={"wire": "containerID"})


@dataclass
class PodCondition:
    type: str = ""
    status: str = ""
    # Wall-clock of the last status flip (reference: v1.PodCondition
    # .lastTransitionTime). The kubelet stamps it when the condition
    # changes and CARRIES it over when it doesn't, so the Running/Ready
    # transition instant survives status rewrites — the telemetry
    # plane's wire-visible startup timestamp (utils/sli.py).
    last_transition_time: str = ""


@dataclass
class PodStatus:
    """Reference: pkg/api/types.go PodStatus. phase in
    Pending|Running|Succeeded|Failed|Unknown."""

    phase: str = "Pending"
    conditions: List[PodCondition] = field(default_factory=list)
    message: str = ""
    reason: str = ""
    host_ip: str = ""
    pod_ip: str = ""
    start_time: str = ""
    container_statuses: List[ContainerStatus] = field(default_factory=list)
    # Node the scheduler nominated this (still pending) pod onto after
    # preempting victims there; cleared implicitly by binding. Lower-
    # priority pods must not race the freed capacity while this is set.
    nominated_node_name: str = ""


@dataclass
class Pod:
    kind: str = "Pod"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class NodeCondition:
    type: str = ""  # Ready
    status: str = ""  # True | False | Unknown
    last_heartbeat_time: str = ""
    last_transition_time: str = ""
    reason: str = ""
    message: str = ""


@dataclass
class NodeAddress:
    type: str = ""  # InternalIP | ExternalIP | Hostname
    address: str = ""


@dataclass
class DaemonEndpoint:
    port: int = 0


@dataclass
class NodeDaemonEndpoints:
    """Where this node's kubelet API listens. The reference hard-codes
    port 10250 and dials node addresses (pkg/master/master.go:497-520);
    publishing the endpoint in NodeStatus is the discovery seam our
    apiserver uses to proxy pod log/exec subresources."""

    kubelet_endpoint: DaemonEndpoint = field(default_factory=DaemonEndpoint)


@dataclass
class NodeStatus:
    """Reference: pkg/api/types.go NodeStatus (capacity drives scheduling)."""

    capacity: Dict[str, Quantity] = field(default_factory=dict)
    phase: str = ""
    conditions: List[NodeCondition] = field(default_factory=list)
    addresses: List[NodeAddress] = field(default_factory=list)
    daemon_endpoints: NodeDaemonEndpoints = field(
        default_factory=NodeDaemonEndpoints
    )
    node_info: Dict[str, str] = field(default_factory=dict)


@dataclass
class NodeSpec:
    pod_cidr: str = field(default="", metadata={"wire": "podCIDR"})
    external_id: str = field(default="", metadata={"wire": "externalID"})
    unschedulable: bool = False


@dataclass
class Node:
    kind: str = "Node"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)


# ---------------------------------------------------------------------------
# Service / Endpoints
# ---------------------------------------------------------------------------


@dataclass
class ServicePort:
    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    target_port: Any = 0  # int or named port string
    node_port: int = 0


@dataclass
class ServiceSpec:
    """Reference: pkg/api/types.go ServiceSpec."""

    ports: List[ServicePort] = field(default_factory=list)
    selector: Dict[str, str] = field(default_factory=dict)
    cluster_ip: str = ""
    type: str = "ClusterIP"
    external_ips: List[str] = field(default_factory=list)
    session_affinity: str = "None"


@dataclass
class Service:
    kind: str = "Service"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ServiceSpec = field(default_factory=ServiceSpec)
    status: Dict[str, Any] = field(default_factory=dict)


@dataclass
class EndpointAddress:
    ip: str = field(default="", metadata={"wire": "ip"})
    target_ref: Optional[Dict[str, str]] = None


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"


@dataclass
class EndpointSubset:
    addresses: List[EndpointAddress] = field(default_factory=list)
    ports: List[EndpointPort] = field(default_factory=list)


@dataclass
class Endpoints:
    kind: str = "Endpoints"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: List[EndpointSubset] = field(default_factory=list)


# ---------------------------------------------------------------------------
# ReplicationController
# ---------------------------------------------------------------------------


@dataclass
class PodTemplateSpec:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)


@dataclass
class ReplicationControllerSpec:
    replicas: int = 0
    selector: Dict[str, str] = field(default_factory=dict)
    template: Optional[PodTemplateSpec] = None


@dataclass
class ReplicationControllerStatus:
    replicas: int = 0
    observed_generation: int = 0


@dataclass
class ReplicationController:
    kind: str = "ReplicationController"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ReplicationControllerSpec = field(default_factory=ReplicationControllerSpec)
    status: ReplicationControllerStatus = field(
        default_factory=ReplicationControllerStatus
    )


# ---------------------------------------------------------------------------
# Binding / Event / Namespace / Secret / envelopes
# ---------------------------------------------------------------------------


@dataclass
class ObjectReference:
    kind: str = ""
    namespace: str = ""
    name: str = ""
    uid: str = ""
    api_version: str = ""
    resource_version: str = ""
    field_path: str = ""


@dataclass
class Binding:
    """Reference: pkg/api/types.go Binding — metadata names the pod,
    target names the node (pkg/registry/pod/etcd/etcd.go:123-181)."""

    kind: str = "Binding"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    target: ObjectReference = field(default_factory=ObjectReference)


@dataclass
class Event:
    kind: str = "Event"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    involved_object: ObjectReference = field(default_factory=ObjectReference)
    reason: str = ""
    message: str = ""
    source: Dict[str, str] = field(default_factory=dict)
    first_timestamp: str = ""
    last_timestamp: str = ""
    count: int = 0


@dataclass
class NamespaceSpec:
    finalizers: List[str] = field(default_factory=list)


@dataclass
class NamespaceStatus:
    phase: str = "Active"


@dataclass
class Namespace:
    kind: str = "Namespace"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NamespaceSpec = field(default_factory=NamespaceSpec)
    status: NamespaceStatus = field(default_factory=NamespaceStatus)


@dataclass
class Secret:
    kind: str = "Secret"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: Dict[str, str] = field(default_factory=dict)
    type: str = "Opaque"


@dataclass
class ServiceAccount:
    """Reference: pkg/api/types.go ServiceAccount."""

    kind: str = "ServiceAccount"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: List[ObjectReference] = field(default_factory=list)
    image_pull_secrets: List[Dict[str, str]] = field(default_factory=list)


@dataclass
class LimitRangeItem:
    """Reference: pkg/api/types.go LimitRangeItem — per-type min/max/default."""

    type: str = "Container"  # Pod | Container
    max: ResourceList = field(default_factory=dict)
    min: ResourceList = field(default_factory=dict)
    default: ResourceList = field(default_factory=dict)


@dataclass
class LimitRangeSpec:
    limits: List[LimitRangeItem] = field(default_factory=list)


@dataclass
class LimitRange:
    kind: str = "LimitRange"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: LimitRangeSpec = field(default_factory=LimitRangeSpec)


@dataclass
class ResourceQuotaSpec:
    hard: ResourceList = field(default_factory=dict)


@dataclass
class ResourceQuotaStatus:
    hard: ResourceList = field(default_factory=dict)
    used: ResourceList = field(default_factory=dict)


@dataclass
class ResourceQuota:
    """Reference: pkg/api/types.go ResourceQuota. Hard limits include
    cpu/memory plus object counts (pods, services, ...)."""

    kind: str = "ResourceQuota"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: ResourceQuotaSpec = field(default_factory=ResourceQuotaSpec)
    status: ResourceQuotaStatus = field(default_factory=ResourceQuotaStatus)


@dataclass
class PersistentVolumeSource:
    """Exactly one of the fields should be set (reference:
    pkg/api/types.go PersistentVolumeSource)."""

    host_path: Optional[HostPathVolumeSource] = None
    gce_persistent_disk: Optional[GCEPersistentDiskVolumeSource] = None
    aws_elastic_block_store: Optional[AWSElasticBlockStoreVolumeSource] = None
    nfs: Optional[NFSVolumeSource] = None
    glusterfs: Optional[GlusterfsVolumeSource] = None
    rbd: Optional[RBDVolumeSource] = None
    iscsi: Optional[ISCSIVolumeSource] = None


@dataclass
class PersistentVolumeSpec:
    capacity: ResourceList = field(default_factory=dict)
    access_modes: List[str] = field(default_factory=list)  # RWO/ROX/RWX
    persistent_volume_source: PersistentVolumeSource = field(
        default_factory=PersistentVolumeSource
    )
    claim_ref: Optional[ObjectReference] = None
    persistent_volume_reclaim_policy: str = "Retain"


@dataclass
class PersistentVolumeStatus:
    phase: str = "Pending"  # Pending|Available|Bound|Released|Failed
    message: str = ""
    reason: str = ""


@dataclass
class PersistentVolume:
    kind: str = "PersistentVolume"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeSpec = field(default_factory=PersistentVolumeSpec)
    status: PersistentVolumeStatus = field(default_factory=PersistentVolumeStatus)


@dataclass
class PersistentVolumeClaimSpec:
    access_modes: List[str] = field(default_factory=list)
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    volume_name: str = ""


@dataclass
class PersistentVolumeClaimStatus:
    phase: str = "Pending"  # Pending|Bound|Lost
    access_modes: List[str] = field(default_factory=list)
    capacity: ResourceList = field(default_factory=dict)


@dataclass
class PersistentVolumeClaim:
    kind: str = "PersistentVolumeClaim"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PersistentVolumeClaimSpec = field(default_factory=PersistentVolumeClaimSpec)
    status: PersistentVolumeClaimStatus = field(
        default_factory=PersistentVolumeClaimStatus
    )


@dataclass
class PodTemplate:
    """Reference: pkg/api/types.go PodTemplate (pkg/registry/podtemplate)."""

    kind: str = "PodTemplate"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)


# The pod label naming the PodGroup (same namespace) a pod belongs to —
# the association seam shared by admission, the gang solver, and the
# gang lifecycle controller.
POD_GROUP_LABEL = "pod-group.kubernetes-tpu.io/name"

# Rebalance-move destination annotation: the descheduler stamps this on
# the replacement pod it recreates after a graceful eviction, and the
# solver's columnar staging honors it as a HostName pin (alongside the
# status.nominatedNodeName reservation) so the micro-tick daemon
# rebinds the pod at its planned destination. The descheduler clears
# stale stamps from pods that stay Pending past the nomination window,
# returning them to ordinary (unpinned) solving.
REBALANCE_DEST_ANNOTATION = "rebalance.kubernetes-tpu.io/destination"

# Label marking a PodTemplate as a journaled rebalance move intent
# (value: the move's destination node). Written BEFORE the eviction,
# deleted after the replacement pod is recreated — crash recovery
# replays orphaned intents so a move interrupted between eviction and
# recreation strands nothing.
REBALANCE_JOURNAL_LABEL = "rebalance.kubernetes-tpu.io/move"


@dataclass
class PodGroupSpec:
    """Gang-scheduling intent (no reference analog in this tree; shape
    follows the sig-scheduling coscheduling PodGroup CRD). A group's
    member pods carry the pod-group label (scheduler/gang.py
    POD_GROUP_LABEL); the batch solver places them all-or-nothing."""

    # Minimum members that must be schedulable together; fewer than
    # this many feasible placements rejects the whole group atomically.
    min_member: int = 1
    # Optional ceiling on group membership; 0 = unlimited. Admission
    # rejects pods that would push the group past this (an "oversized"
    # group is a manifest bug, not a scheduling problem).
    max_member: int = 0
    # Groups still Pending this many seconds after creation are marked
    # Unschedulable by the gang controller (events + status); 0 = no
    # timeout.
    schedule_timeout_seconds: int = 0


@dataclass
class PodGroupStatus:
    phase: str = "Pending"  # Pending | Scheduled | Unschedulable
    members: int = 0  # pods carrying the group label
    bound: int = 0  # members with spec.nodeName set
    message: str = ""
    # When the current Pending stint began (ISO8601); the gang
    # controller ages scheduleTimeoutSeconds against THIS, not
    # creationTimestamp, so a gang that re-pends after running gets a
    # fresh timeout window. Empty = pending since creation.
    pending_since: str = ""


@dataclass
class PodGroup:
    kind: str = "PodGroup"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodGroupSpec = field(default_factory=PodGroupSpec)
    status: PodGroupStatus = field(default_factory=PodGroupStatus)


# Preemption policies (reference: core.PreemptionPolicy). The empty
# string on a pod/class means PREEMPT_LOWER_PRIORITY.
PREEMPT_LOWER_PRIORITY = "PreemptLowerPriority"
PREEMPT_NEVER = "Never"

#: |value| ceiling for user PriorityClasses (reference:
#: scheduling.k8s.io HighestUserDefinablePriority).
MAX_PRIORITY = 1_000_000_000


@dataclass
class PriorityClass:
    """Cluster-scoped pod importance (no analog in this reference tree;
    shape follows scheduling.k8s.io/v1 PriorityClass). `value` is
    copied onto pods by the Priority admission plugin; `globalDefault`
    marks the class applied to pods naming no class at all;
    `preemptionPolicy: Never` opts a class's pods out of preempting
    (they still queue by priority and can themselves be preempted)."""

    kind: str = "PriorityClass"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    preemption_policy: str = PREEMPT_LOWER_PRIORITY
    description: str = ""


def pod_priority(pod: "Pod") -> int:
    """Resolved scheduling priority (0 = unset/best-effort)."""
    return pod.spec.priority or 0


def pod_full_key(pod: "Pod") -> str:
    """Canonical 'namespace/name' pod key with the empty namespace
    defaulted — THE format preemption decisions, nominations, and the
    gang preemption guard compare (one definition, not three)."""
    return f"{pod.metadata.namespace or 'default'}/{pod.metadata.name}"


def pod_can_preempt(pod: "Pod") -> bool:
    """Whether this pod may evict others (its own policy, not its
    victims'). Unset policy = PreemptLowerPriority, matching the
    reference's default."""
    return (pod.spec.preemption_policy or PREEMPT_LOWER_PRIORITY) != PREEMPT_NEVER


def pod_is_terminating(pod: "Pod") -> bool:
    """Graceful delete in flight: marked with deletionTimestamp but not
    yet removed from the store. Still occupies node capacity; no longer
    a preemption victim candidate (its capacity is already promised)."""
    return bool(pod.metadata.deletion_timestamp)


@dataclass
class ComponentCondition:
    type: str = "Healthy"
    status: str = "Unknown"  # True|False|Unknown
    message: str = ""
    error: str = ""


@dataclass
class ComponentStatus:
    """Reference: pkg/registry/componentstatus — health of master components."""

    kind: str = "ComponentStatus"
    api_version: str = "v1"
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    conditions: List[ComponentCondition] = field(default_factory=list)


@dataclass
class DeleteOptions:
    kind: str = "DeleteOptions"
    api_version: str = "v1"
    grace_period_seconds: Optional[int] = None


@dataclass
class StatusDetails:
    name: str = ""
    kind: str = ""
    causes: List[Dict[str, str]] = field(default_factory=list)


@dataclass
class Status:
    """Reference: pkg/api/types.go Status — API error/success envelope."""

    kind: str = "Status"
    api_version: str = "v1"
    metadata: ListMeta = field(default_factory=ListMeta)
    status: str = ""  # Success | Failure
    message: str = ""
    reason: str = ""
    details: Optional[StatusDetails] = None
    code: int = 0


# Registry of kinds for decode dispatch (reference: runtime.Scheme type map).
KINDS = {
    "Pod": Pod,
    "Node": Node,
    "Minion": Node,
    "Service": Service,
    "Endpoints": Endpoints,
    "ReplicationController": ReplicationController,
    "Binding": Binding,
    "Event": Event,
    "Namespace": Namespace,
    "Secret": Secret,
    "ServiceAccount": ServiceAccount,
    "LimitRange": LimitRange,
    "ResourceQuota": ResourceQuota,
    "PersistentVolume": PersistentVolume,
    "PersistentVolumeClaim": PersistentVolumeClaim,
    "PodTemplate": PodTemplate,
    "PodGroup": PodGroup,
    "PriorityClass": PriorityClass,
    "ComponentStatus": ComponentStatus,
    "DeleteOptions": DeleteOptions,
    "Status": Status,
}
