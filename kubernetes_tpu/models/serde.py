"""Dataclass <-> wire (camelCase JSON) codec.

Plays the role of the reference's runtime.Codec / generated conversions
(pkg/runtime/scheme.go, pkg/api/v1/conversion_generated.go): every API
object serializes to the camelCase JSON wire form and decodes back into
typed Python dataclasses, recursively, driven by type hints. Unknown
wire fields are ignored (forward compatibility); zero-valued fields are
omitted on encode like Go's `omitempty`.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict, Type, get_args, get_origin, get_type_hints

from kubernetes_tpu.models.quantity import Quantity, parse_quantity

_SPECIAL_CAMEL = {
    # Wire names that simple snake->camel conversion would get wrong.
    "api_version": "apiVersion",
    "cluster_ip": "clusterIP",
    "pod_ip": "podIP",
    "host_ip": "hostIP",
    "external_ips": "externalIPs",
    "node_port": "nodePort",
    "target_port": "targetPort",
    "host_port": "hostPort",
    "container_port": "containerPort",
    "image_pull_policy": "imagePullPolicy",
    "tcp_socket": "tcpSocket",
    "http_get": "httpGet",
    "uid": "uid",
}


def snake_to_camel(name: str) -> str:
    if name in _SPECIAL_CAMEL:
        return _SPECIAL_CAMEL[name]
    parts = name.split("_")
    return parts[0] + "".join(p.capitalize() for p in parts[1:])


_hints_cache: Dict[type, Dict[str, Any]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    h = _hints_cache.get(cls)
    if h is None:
        h = get_type_hints(cls)
        _hints_cache[cls] = h
    return h


def _is_zero(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, (list, dict, str)) and not v:
        return True
    if isinstance(v, bool):
        return v is False
    if isinstance(v, int) and not isinstance(v, bool):
        return v == 0
    if isinstance(v, Quantity):
        return v.is_zero()
    return False


# -- compiled encode plans ------------------------------------------------
#
# Per-class field tables, built once: the reference generates its
# conversions ahead of time (pkg/api/v1/conversion_generated.go via
# cmd/genconversion) for exactly this reason — reflective per-object
# field walks are too slow on the watch/decode hot path. Here the
# "generated code" is a cached plan: (attr, wire key, always?) tuples
# for encode, wire-key -> (attr, decoder-closure) for decode.

_encode_plan_cache: Dict[type, tuple] = {}


def _encode_plan(cls: type) -> tuple:
    plan = _encode_plan_cache.get(cls)
    if plan is None:
        plan = tuple(
            (
                f.name,
                f.metadata.get("wire", snake_to_camel(f.name)),
                bool(f.metadata.get("always")),
            )
            for f in dataclasses.fields(cls)
        )
        _encode_plan_cache[cls] = plan
    return plan


def to_wire(obj: Any, *, omit_empty: bool = True) -> Any:
    """Recursively encode a dataclass (or container) to wire-form JSON."""
    if obj is None:
        return None
    if isinstance(obj, Quantity):
        return str(obj)
    if dataclasses.is_dataclass(obj):
        out: Dict[str, Any] = {}
        for name, wire_key, always in _encode_plan(type(obj)):
            v = getattr(obj, name)
            if omit_empty and not always and _is_zero(v):
                continue
            out[wire_key] = to_wire(v, omit_empty=omit_empty)
        return out
    if isinstance(obj, dict):
        return {k: to_wire(v, omit_empty=omit_empty) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_wire(v, omit_empty=omit_empty) for v in obj]
    return obj


# -- compiled decode plans ------------------------------------------------

_decode_plan_cache: Dict[type, Dict[str, tuple]] = {}


_SCALAR_HINTS = (str, int, float, bool)


def _copy_raw(v: Any) -> Any:
    """Deep-copy raw (untyped) wire leaves. Any-typed fields (e.g.
    ContainerStatus.state) would otherwise alias the source dict —
    and store watch events share ONE object across all watchers
    (kvstore._dispatch_event), so an aliased leaf mutated by one
    informer consumer would silently corrupt every other's view."""
    if isinstance(v, dict):
        return {k: _copy_raw(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_copy_raw(x) for x in v]
    return v


def _decoder_for(hint: Any):
    """Build a decoder closure for one type hint (None = identity,
    safe only for scalar hints). Callers handle v=None before
    invoking."""
    origin = get_origin(hint)
    if origin is typing.Union:  # Optional[T] and friends
        args = [a for a in get_args(hint) if a is not type(None)]
        if len(args) == 1:
            return _decoder_for(args[0])
        return _copy_raw  # ambiguous union: defensive copy
    if hint is Quantity:
        return parse_quantity
    if dataclasses.is_dataclass(hint):
        return lambda v, _c=hint: from_wire(_c, v)
    if origin in (list, typing.List):
        (elem,) = get_args(hint) or (Any,)
        ed = _decoder_for(elem)
        if ed is None:
            return list  # fresh container, scalar elements
        return lambda v, _d=ed: [None if x is None else _d(x) for x in v]
    if origin in (dict, typing.Dict):
        args = get_args(hint)
        elem = args[1] if len(args) == 2 else Any
        vd = _decoder_for(elem)
        if vd is None:
            return dict  # fresh container, scalar values
        return lambda v, _d=vd: {
            k: None if x is None else _d(x) for k, x in v.items()
        }
    if hint in _SCALAR_HINTS:
        return None  # immutable: raw passthrough
    return _copy_raw  # Any / unknown: never alias the source


def _decode_plan(cls: type) -> Dict[str, tuple]:
    plan = _decode_plan_cache.get(cls)
    if plan is None:
        hints = _hints(cls)
        plan = {
            f.metadata.get("wire", snake_to_camel(f.name)): (
                f.name,
                _decoder_for(hints[f.name]),
            )
            for f in dataclasses.fields(cls)
        }
        _decode_plan_cache[cls] = plan
    return plan


def from_wire(cls: Type, data: Dict[str, Any] | None):
    """Decode wire-form JSON into dataclass `cls`, ignoring unknown keys."""
    if data is None:
        return None
    if not isinstance(data, dict):
        raise ValueError(f"cannot decode {cls.__name__} from {type(data).__name__}")
    plan = _decode_plan(cls)
    kwargs: Dict[str, Any] = {}
    for wire_key, v in data.items():
        ent = plan.get(wire_key)
        if ent is None:
            continue
        name, dec = ent
        kwargs[name] = v if v is None or dec is None else dec(v)
    return cls(**kwargs)
