"""Columnar (struct-of-arrays) encodings of pods and nodes.

This is the matrix schema consumed by the TPU scheduler path: the
reference's per-pod Go loops over object graphs
(plugin/pkg/scheduler/generic_scheduler.go:106-171,
plugin/pkg/scheduler/algorithm/predicates/predicates.go) become dense
ops over these arrays.

Design notes (TPU-first):
- Resources are lowered once, host-side, to integer-valued float32
  columns: CPU in millicores, memory in MiB (ceil). float32 holds
  integers exactly up to 2^24, i.e. 16 TiB of MiB-granular memory and
  16M millicores — beyond any single node. Integer score truncation
  (priorities.go:39) is then exact on device for Mi-granular quantities.
- Set-valued predicates (nodeSelector subset-match, hostPort conflicts,
  exclusive-disk conflicts) use snapshot-scoped vocabularies: every
  distinct key=value / port / volume-id observed is assigned an id, and
  membership becomes uint32 bitsets. Subset/intersection tests are then
  bitwise AND + reductions — MXU/VPU friendly, no string work on device.
- Pods with identical selector sets share a row in a deduped selector
  table (usually tiny), so the expensive [S, N] match matrix is computed
  once per distinct selector, then gathered per pod.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.objects import (
    Node,
    Pod,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    Service,
)

MIB = 1024 * 1024


# ---------------------------------------------------------------------------
# Vocabularies
# ---------------------------------------------------------------------------


class Vocab:
    """Snapshot-scoped string->id mapping used for bitset encodings."""

    def __init__(self):
        self.index: Dict[str, int] = {}

    def id(self, token: str) -> int:
        i = self.index.get(token)
        if i is None:
            i = len(self.index)
            self.index[token] = i
        return i

    def __len__(self) -> int:
        return len(self.index)

    @property
    def words(self) -> int:
        """Number of uint32 words needed for a bitset (at least 1)."""
        return max(1, (len(self.index) + 31) // 32)


def bitset(ids: Sequence[int], words: int) -> np.ndarray:
    out = np.zeros(words, dtype=np.uint32)
    for i in ids:
        out[i >> 5] |= np.uint32(1 << (i & 31))
    return out


# ---------------------------------------------------------------------------
# Resource lowering
# ---------------------------------------------------------------------------


def pod_resource_request(pod: Pod) -> Tuple[int, int]:
    """Sum of container requests: (milli-CPU, memory bytes).

    Reference: predicates.go:106-114 getResourceRequest — sums
    requests.cpu.MilliValue() and requests.memory.Value() per container.
    """
    cpu = 0
    mem = 0
    for c in pod.spec.containers:
        req = c.resources.requests
        if RESOURCE_CPU in req:
            cpu += req[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in req:
            mem += req[RESOURCE_MEMORY].value()
    return cpu, mem


def mem_to_mib(mem_bytes: int) -> int:
    """Lower bytes to MiB, rounding up so requests never under-count."""
    return -((-mem_bytes) // MIB)


def pod_host_ports(pod: Pod) -> List[int]:
    """All nonzero hostPorts of a pod (reference: predicates.go:351-360)."""
    ports = []
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                ports.append(p.host_port)
    return ports


def pod_exclusive_volumes(pod: Pod) -> List[str]:
    """Volume ids subject to single-attach exclusivity.

    Reference: predicates.go:59-95 NoDiskConflict — GCE PD and AWS EBS
    volumes may not be attached read-write by two pods on one node (the
    v0.19 check ignores read-only flags and simply forbids same-id
    co-location).
    """
    vols = []
    for v in pod.spec.volumes:
        if v.gce_persistent_disk is not None and v.gce_persistent_disk.pd_name:
            vols.append("gce-pd:" + v.gce_persistent_disk.pd_name)
        if (
            v.aws_elastic_block_store is not None
            and v.aws_elastic_block_store.volume_id
        ):
            vols.append("aws-ebs:" + v.aws_elastic_block_store.volume_id)
    return vols


# ---------------------------------------------------------------------------
# Columnar batches
# ---------------------------------------------------------------------------


@dataclass
class PodColumns:
    """Struct-of-arrays for P pending pods."""

    names: List[str]  # namespace/name keys, host-side only
    cpu_milli: np.ndarray  # f32[P]
    mem_mib: np.ndarray  # f32[P]
    selector_id: np.ndarray  # i32[P] — row into sel_table (-0 == no selector row 0)
    port_bits: np.ndarray  # u32[P, PW]
    vol_bits: np.ndarray  # u32[P, VW]
    pinned_node: np.ndarray  # i32[P] — node index or -1
    service_id: np.ndarray  # i32[P] — first matching service, -1 if none
    # Deduped selector table: row u of sel_bits is a bitset of required
    # key=value ids; row 0 is always the empty selector.
    sel_bits: np.ndarray  # u32[U, LW]

    @property
    def count(self) -> int:
        return len(self.names)


@dataclass
class NodeColumns:
    """Struct-of-arrays for N nodes (capacity + current occupancy)."""

    names: List[str]
    cpu_cap: np.ndarray  # f32[N] millicores
    mem_cap: np.ndarray  # f32[N] MiB
    cpu_used: np.ndarray  # f32[N] millicores, from already-assigned pods
    mem_used: np.ndarray  # f32[N] MiB
    label_bits: np.ndarray  # u32[N, LW] — key=value ids present on node
    used_port_bits: np.ndarray  # u32[N, PW] — hostPorts taken by existing pods
    used_vol_bits: np.ndarray  # u32[N, VW] — exclusive volumes attached
    service_counts: np.ndarray  # f32[N, S] — matching-pod count per service
    schedulable: np.ndarray  # bool[N] — Ready and not unschedulable

    @property
    def count(self) -> int:
        return len(self.names)


@dataclass
class Snapshot:
    """One scheduling problem: P pending pods x N nodes.

    Produced host-side from API objects; everything the device solver
    needs and nothing it does not (names stay on host).
    """

    pods: PodColumns
    nodes: NodeColumns
    label_vocab: Vocab
    port_vocab: Vocab
    vol_vocab: Vocab
    service_names: List[str]


def pod_key(pod: Pod) -> str:
    return f"{pod.metadata.namespace}/{pod.metadata.name}"


def node_is_ready(node: Node) -> bool:
    """Reference: StoreToNodeLister filters to Ready nodes
    (pkg/client/cache/listers.go) and spec.unschedulable gates fit."""
    if node.spec.unschedulable:
        return False
    for c in node.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    # Nodes with no conditions reported are treated as ready (matches the
    # reference's permissive default for freshly registered nodes).
    return True


def _first_matching_service(pod: Pod, services: List[Service]) -> int:
    """Index of the first service whose selector matches the pod.

    Reference: pkg/registry/service/registry GetPodServices as used by
    CalculateSpreadPriority (spreading.go:44-56); v0.19 uses the first
    matching service's selector.
    """
    labels = pod.metadata.labels or {}
    for i, svc in enumerate(services):
        sel = svc.spec.selector
        if not sel:
            continue
        if svc.metadata.namespace != pod.metadata.namespace:
            continue
        if all(labels.get(k) == v for k, v in sel.items()):
            return i
    return -1


def build_snapshot(
    pending_pods: Sequence[Pod],
    nodes: Sequence[Node],
    assigned_pods: Sequence[Pod] = (),
    services: Sequence[Service] = (),
) -> Snapshot:
    """Lower API objects into a dense scheduling snapshot.

    `assigned_pods` are pods already bound to nodes (they contribute to
    occupancy the way MapPodsToMachines does, predicates.go:379-392).
    """
    nodes = list(nodes)
    pending_pods = list(pending_pods)
    services = list(services)
    node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
    N, P, S = len(nodes), len(pending_pods), len(services)

    label_vocab, port_vocab, vol_vocab = Vocab(), Vocab(), Vocab()

    # -- vocabulary passes (host-side, one sweep each) --
    for n in nodes:
        for k, v in (n.metadata.labels or {}).items():
            label_vocab.id(f"{k}={v}")
    sel_keys: Dict[Tuple[Tuple[str, str], ...], int] = {(): 0}
    pod_sel_rows = np.zeros(P, dtype=np.int32)
    for i, p in enumerate(pending_pods):
        sel = tuple(sorted((p.spec.node_selector or {}).items()))
        for k, v in sel:
            label_vocab.id(f"{k}={v}")
        row = sel_keys.setdefault(sel, len(sel_keys))
        pod_sel_rows[i] = row
        for port in pod_host_ports(p):
            port_vocab.id(str(port))
        for vol in pod_exclusive_volumes(p):
            vol_vocab.id(vol)
    for p in assigned_pods:
        for port in pod_host_ports(p):
            port_vocab.id(str(port))
        for vol in pod_exclusive_volumes(p):
            vol_vocab.id(vol)

    LW, PW, VW = label_vocab.words, port_vocab.words, vol_vocab.words

    # -- pod columns --
    cpu_req = np.zeros(P, dtype=np.float32)
    mem_req = np.zeros(P, dtype=np.float32)
    port_bits = np.zeros((P, PW), dtype=np.uint32)
    vol_bits = np.zeros((P, VW), dtype=np.uint32)
    pinned = np.full(P, -1, dtype=np.int32)
    service_id = np.full(P, -1, dtype=np.int32)
    for i, p in enumerate(pending_pods):
        cpu, mem = pod_resource_request(p)
        cpu_req[i] = cpu
        mem_req[i] = mem_to_mib(mem)
        port_bits[i] = bitset([port_vocab.id(str(x)) for x in pod_host_ports(p)], PW)
        vol_bits[i] = bitset(
            [vol_vocab.id(v) for v in pod_exclusive_volumes(p)], VW
        )
        if p.spec.node_name:
            pinned[i] = node_index.get(p.spec.node_name, -2)  # -2: unknown node
        service_id[i] = _first_matching_service(p, services)

    sel_bits = np.zeros((len(sel_keys), LW), dtype=np.uint32)
    for sel, row in sel_keys.items():
        sel_bits[row] = bitset([label_vocab.id(f"{k}={v}") for k, v in sel], LW)

    # -- node columns --
    cpu_cap = np.zeros(N, dtype=np.float32)
    mem_cap = np.zeros(N, dtype=np.float32)
    cpu_used = np.zeros(N, dtype=np.float32)
    mem_used = np.zeros(N, dtype=np.float32)
    label_bits = np.zeros((N, LW), dtype=np.uint32)
    used_port_bits = np.zeros((N, PW), dtype=np.uint32)
    used_vol_bits = np.zeros((N, VW), dtype=np.uint32)
    service_counts = np.zeros((N, max(S, 1)), dtype=np.float32)
    schedulable = np.zeros(N, dtype=bool)
    for j, n in enumerate(nodes):
        cap = n.status.capacity or {}
        if RESOURCE_CPU in cap:
            cpu_cap[j] = cap[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in cap:
            # Capacity rounds DOWN (requests round up) so lowering can
            # only under-promise, never overcommit a node.
            mem_cap[j] = cap[RESOURCE_MEMORY].value() // MIB
        label_bits[j] = bitset(
            [label_vocab.id(f"{k}={v}") for k, v in (n.metadata.labels or {}).items()],
            LW,
        )
        schedulable[j] = node_is_ready(n)

    for p in assigned_pods:
        j = node_index.get(p.spec.node_name)
        if j is None:
            continue
        cpu, mem = pod_resource_request(p)
        cpu_used[j] += cpu
        mem_used[j] += mem_to_mib(mem)
        used_port_bits[j] |= bitset(
            [port_vocab.id(str(x)) for x in pod_host_ports(p)], PW
        )
        used_vol_bits[j] |= bitset(
            [vol_vocab.id(v) for v in pod_exclusive_volumes(p)], VW
        )
        svc = _first_matching_service(p, services)
        if svc >= 0:
            service_counts[j, svc] += 1

    return Snapshot(
        pods=PodColumns(
            names=[pod_key(p) for p in pending_pods],
            cpu_milli=cpu_req,
            mem_mib=mem_req,
            selector_id=pod_sel_rows,
            port_bits=port_bits,
            vol_bits=vol_bits,
            pinned_node=pinned,
            service_id=service_id,
            sel_bits=sel_bits,
        ),
        nodes=NodeColumns(
            names=[n.metadata.name for n in nodes],
            cpu_cap=cpu_cap,
            mem_cap=mem_cap,
            cpu_used=cpu_used,
            mem_used=mem_used,
            label_bits=label_bits,
            used_port_bits=used_port_bits,
            used_vol_bits=used_vol_bits,
            service_counts=service_counts,
            schedulable=schedulable,
        ),
        label_vocab=label_vocab,
        port_vocab=port_vocab,
        vol_vocab=vol_vocab,
        service_names=[f"{s.metadata.namespace}/{s.metadata.name}" for s in services],
    )
