"""Columnar (struct-of-arrays) encodings of pods and nodes.

This is the matrix schema consumed by the TPU scheduler path: the
reference's per-pod Go loops over object graphs
(plugin/pkg/scheduler/generic_scheduler.go:106-171,
plugin/pkg/scheduler/algorithm/predicates/predicates.go) become dense
ops over these arrays. Semantics mirror the scalar oracle
(kubernetes_tpu.scheduler.predicates/priorities) bit for bit wherever
integers allow.

Design notes (TPU-first):
- Resources are lowered once, host-side, to integer-valued float32
  columns: CPU in millicores, memory in MiB. float32 holds integers
  exactly up to 2^24, i.e. 16 TiB of MiB-granular memory and 16M
  millicores — beyond any single node. Requests round UP to MiB and
  capacity rounds DOWN, so lowering can under-promise but never
  overcommit. Integer score truncation (priorities.go:39) is then exact
  on device for Mi-granular quantities.
- Resource accounting uses container LIMITS, matching the v0.19
  reference (getResourceRequest, predicates.go:106-114).
- PodFitsResources parity needs three per-node facts (predicates.go:
  116-156): the greedy-fitted usage sums, whether ANY existing pod
  overflowed the greedy simulation (such nodes reject every new pod),
  and the existing-pod count vs pods capacity. Priorities instead use
  the FULL usage sums including overflowing pods (calculateOccupancy,
  priorities.go:44-58). Both are encoded.
- Set-valued predicates (nodeSelector subset-match, hostPort conflicts,
  exclusive-disk conflicts) use snapshot-scoped vocabularies: every
  distinct key=value / port / volume-id observed is assigned an id, and
  membership becomes uint32 bitsets. Volumes carry two bitsets (all
  mounts vs read-write mounts) so the GCE-PD both-read-only exemption
  (predicates.go:59-66) survives lowering; AWS EBS volumes set both
  bits because they conflict regardless of read-only.
- Pods with identical selector sets share a row in a deduped selector
  table, so selector bitsets are stored once per distinct selector.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.objects import (
    Node,
    Pod,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Service,
)

MIB = 1024 * 1024


# ---------------------------------------------------------------------------
# Vocabularies
# ---------------------------------------------------------------------------


class Vocab:
    """Snapshot-scoped string->id mapping used for bitset encodings."""

    def __init__(self):
        self.index: Dict[str, int] = {}

    def id(self, token: str) -> int:
        i = self.index.get(token)
        if i is None:
            i = len(self.index)
            self.index[token] = i
        return i

    def __len__(self) -> int:
        return len(self.index)

    @property
    def words(self) -> int:
        """Number of uint32 words needed for a bitset (at least 1)."""
        return max(1, (len(self.index) + 31) // 32)


def bitset(ids: Sequence[int], words: int) -> np.ndarray:
    out = np.zeros(words, dtype=np.uint32)
    for i in ids:
        out[i >> 5] |= np.uint32(1 << (i & 31))
    return out


# ---------------------------------------------------------------------------
# Resource lowering
# ---------------------------------------------------------------------------


def pod_resource_limits(pod: Pod) -> Tuple[int, int]:
    """Sum of container LIMITS: (milli-CPU, memory bytes).

    Reference: predicates.go:106-114 getResourceRequest — v0.19 sums
    limits.Cpu().MilliValue() and limits.Memory().Value().
    """
    cpu = 0
    mem = 0
    for c in pod.spec.containers:
        lim = c.resources.limits
        if RESOURCE_CPU in lim:
            cpu += lim[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in lim:
            mem += lim[RESOURCE_MEMORY].value()
    return cpu, mem


def mem_to_mib_ceil(mem_bytes: int) -> int:
    return -((-mem_bytes) // MIB)


def pod_host_ports(pod: Pod) -> List[int]:
    """Nonzero hostPorts (getUsedPorts skips 0 at the check site,
    predicates.go:337-349)."""
    ports = []
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                ports.append(p.host_port)
    return ports


def pod_volumes(pod: Pod) -> List[Tuple[str, bool]]:
    """Exclusive volumes as (id, read_write) pairs.

    GCE PD mounts conflict unless BOTH are read-only; AWS EBS mounts
    always conflict (isVolumeConflict, predicates.go:53-78) — EBS is
    returned as read_write=True regardless.
    """
    vols = []
    for v in pod.spec.volumes:
        if v.gce_persistent_disk is not None and v.gce_persistent_disk.pd_name:
            vols.append(
                ("gce-pd:" + v.gce_persistent_disk.pd_name,
                 not v.gce_persistent_disk.read_only)
            )
        if (
            v.aws_elastic_block_store is not None
            and v.aws_elastic_block_store.volume_id
        ):
            vols.append(("aws-ebs:" + v.aws_elastic_block_store.volume_id, True))
    return vols


# ---------------------------------------------------------------------------
# Columnar batches
# ---------------------------------------------------------------------------


@dataclass
class PodColumns:
    """Struct-of-arrays for P pending pods."""

    names: List[str]  # namespace/name keys, host-side only
    cpu_milli: np.ndarray  # f32[P]
    mem_mib: np.ndarray  # f32[P]
    zero_req: np.ndarray  # bool[P] — cpu==0 and mem==0 (different fit rule)
    selector_id: np.ndarray  # i32[P] — row into sel_bits (0 = empty selector)
    port_bits: np.ndarray  # u32[P, PW]
    vol_any_bits: np.ndarray  # u32[P, VW] — all exclusive mounts
    vol_rw_bits: np.ndarray  # u32[P, VW] — read-write mounts only
    pinned_node: np.ndarray  # i32[P] — node index, -1 unpinned, -2 unknown
    service_id: np.ndarray  # i32[P] — first matching service, -1 if none
    svc_member: np.ndarray  # f32[P, S] — 1.0 per service whose selector matches
    sel_bits: np.ndarray  # u32[U, LW] — deduped selector table

    @property
    def count(self) -> int:
        return len(self.names)


@dataclass
class NodeColumns:
    """Struct-of-arrays for N nodes (capacity + current occupancy)."""

    names: List[str]
    cpu_cap: np.ndarray  # f32[N] millicores
    mem_cap: np.ndarray  # f32[N] MiB
    pods_cap: np.ndarray  # f32[N] max pods
    # Feasibility-side occupancy: greedy-fitted sums + overflow flag
    # (CheckPodsExceedingCapacity semantics).
    cpu_fit_used: np.ndarray  # f32[N]
    mem_fit_used: np.ndarray  # f32[N]
    overcommitted: np.ndarray  # bool[N] — some existing pod overflowed
    # Scoring-side occupancy: FULL sums (calculateOccupancy semantics).
    cpu_used: np.ndarray  # f32[N]
    mem_used: np.ndarray  # f32[N]
    pods_used: np.ndarray  # f32[N] — count of existing (non-terminal) pods
    label_bits: np.ndarray  # u32[N, LW]
    used_port_bits: np.ndarray  # u32[N, PW]
    used_vol_any_bits: np.ndarray  # u32[N, VW]
    used_vol_rw_bits: np.ndarray  # u32[N, VW]
    service_counts: np.ndarray  # f32[N, S] — matching-pod count per service
    schedulable: np.ndarray  # bool[N] — Ready and not unschedulable

    @property
    def count(self) -> int:
        return len(self.names)


@dataclass
class Snapshot:
    """One scheduling problem: P pending pods x N nodes."""

    pods: PodColumns
    nodes: NodeColumns
    label_vocab: Vocab
    port_vocab: Vocab
    vol_vocab: Vocab
    service_names: List[str]


def pod_key(pod: Pod) -> str:
    return f"{pod.metadata.namespace}/{pod.metadata.name}"


def node_is_ready(node: Node) -> bool:
    """Reference: StoreToNodeLister filters to Ready nodes
    (pkg/client/cache/listers.go) and spec.unschedulable gates fit."""
    if node.spec.unschedulable:
        return False
    for c in node.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    # Nodes with no conditions reported are treated as ready (matches the
    # reference's permissive default for freshly registered nodes).
    return True


def _service_membership(pod: Pod, services: List[Service]) -> np.ndarray:
    """Multi-hot f32[S]: which same-namespace service selectors match
    the pod's labels. The pending pod spreads against its FIRST match
    (GetPodServices / spreading.go:44-56), but as an *existing* pod it
    is counted by every service whose selector matches it
    (pod_lister.list(selector) in CalculateSpreadPriority)."""
    out = np.zeros(max(len(services), 1), dtype=np.float32)
    labels = pod.metadata.labels or {}
    for i, svc in enumerate(services):
        sel = svc.spec.selector
        if not sel:
            continue
        if svc.metadata.namespace != pod.metadata.namespace:
            continue
        if all(labels.get(k) == v for k, v in sel.items()):
            out[i] = 1.0
    return out


def _first_matching_service(pod: Pod, services: List[Service]) -> int:
    member = _service_membership(pod, services)
    nz = np.nonzero(member[: len(services)])[0]
    return int(nz[0]) if len(nz) else -1


def build_snapshot(
    pending_pods: Sequence[Pod],
    nodes: Sequence[Node],
    assigned_pods: Sequence[Pod] = (),
    services: Sequence[Service] = (),
) -> Snapshot:
    """Lower API objects into a dense scheduling snapshot.

    `assigned_pods` are pods already bound to nodes; they contribute to
    occupancy the way MapPodsToMachines does (predicates.go:379-392),
    with terminal-phase pods filtered out.
    """
    nodes = list(nodes)
    pending_pods = list(pending_pods)
    services = list(services)
    # Terminal-phase filtering applies to OCCUPANCY (MapPodsToMachines /
    # filterNonRunningPods, predicates.go:361-377) but NOT to service
    # spreading counts — CalculateSpreadPriority lists pods by selector
    # with no phase filter (spreading.go:44-57).
    all_assigned = list(assigned_pods)
    assigned_pods = [
        p for p in all_assigned if p.status.phase not in ("Succeeded", "Failed")
    ]
    node_index = {n.metadata.name: i for i, n in enumerate(nodes)}
    N, P, S = len(nodes), len(pending_pods), len(services)

    label_vocab, port_vocab, vol_vocab = Vocab(), Vocab(), Vocab()

    # -- vocabulary passes (host-side, one sweep each) --
    for n in nodes:
        for k, v in (n.metadata.labels or {}).items():
            label_vocab.id(f"{k}={v}")
    sel_keys: Dict[Tuple[Tuple[str, str], ...], int] = {(): 0}
    pod_sel_rows = np.zeros(P, dtype=np.int32)
    for i, p in enumerate(pending_pods):
        sel = tuple(sorted((p.spec.node_selector or {}).items()))
        for k, v in sel:
            label_vocab.id(f"{k}={v}")
        row = sel_keys.setdefault(sel, len(sel_keys))
        pod_sel_rows[i] = row
        for port in pod_host_ports(p):
            port_vocab.id(str(port))
        for vol, _rw in pod_volumes(p):
            vol_vocab.id(vol)
    for p in assigned_pods:
        for port in pod_host_ports(p):
            port_vocab.id(str(port))
        for vol, _rw in pod_volumes(p):
            vol_vocab.id(vol)

    LW, PW, VW = label_vocab.words, port_vocab.words, vol_vocab.words

    # -- pod columns --
    cpu_req = np.zeros(P, dtype=np.float32)
    mem_req = np.zeros(P, dtype=np.float32)
    zero_req = np.zeros(P, dtype=bool)
    port_bits = np.zeros((P, PW), dtype=np.uint32)
    vol_any = np.zeros((P, VW), dtype=np.uint32)
    vol_rw = np.zeros((P, VW), dtype=np.uint32)
    pinned = np.full(P, -1, dtype=np.int32)
    service_id = np.full(P, -1, dtype=np.int32)
    svc_member = np.zeros((P, max(S, 1)), dtype=np.float32)
    for i, p in enumerate(pending_pods):
        cpu, mem = pod_resource_limits(p)
        cpu_req[i] = cpu
        mem_req[i] = mem_to_mib_ceil(mem)
        zero_req[i] = cpu == 0 and mem == 0
        port_bits[i] = bitset([port_vocab.id(str(x)) for x in pod_host_ports(p)], PW)
        vols = pod_volumes(p)
        vol_any[i] = bitset([vol_vocab.id(v) for v, _ in vols], VW)
        vol_rw[i] = bitset([vol_vocab.id(v) for v, rw in vols if rw], VW)
        if p.spec.node_name:
            pinned[i] = node_index.get(p.spec.node_name, -2)
        svc_member[i] = _service_membership(p, services)
        nz = np.nonzero(svc_member[i][:S])[0]
        service_id[i] = int(nz[0]) if len(nz) else -1

    sel_bits = np.zeros((len(sel_keys), LW), dtype=np.uint32)
    for sel, row in sel_keys.items():
        sel_bits[row] = bitset([label_vocab.id(f"{k}={v}") for k, v in sel], LW)

    # -- node columns --
    cpu_cap = np.zeros(N, dtype=np.float32)
    mem_cap = np.zeros(N, dtype=np.float32)
    pods_cap = np.zeros(N, dtype=np.float32)
    cpu_fit_used = np.zeros(N, dtype=np.float32)
    mem_fit_used = np.zeros(N, dtype=np.float32)
    overcommitted = np.zeros(N, dtype=bool)
    cpu_used = np.zeros(N, dtype=np.float32)
    mem_used = np.zeros(N, dtype=np.float32)
    pods_used = np.zeros(N, dtype=np.float32)
    label_bits = np.zeros((N, LW), dtype=np.uint32)
    used_port_bits = np.zeros((N, PW), dtype=np.uint32)
    used_vol_any = np.zeros((N, VW), dtype=np.uint32)
    used_vol_rw = np.zeros((N, VW), dtype=np.uint32)
    service_counts = np.zeros((N, max(S, 1)), dtype=np.float32)
    schedulable = np.zeros(N, dtype=bool)
    for j, n in enumerate(nodes):
        cap = n.status.capacity or {}
        if RESOURCE_CPU in cap:
            cpu_cap[j] = cap[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in cap:
            # Capacity rounds DOWN (requests round up) so lowering can
            # only under-promise, never overcommit a node.
            mem_cap[j] = cap[RESOURCE_MEMORY].value() // MIB
        if RESOURCE_PODS in cap:
            pods_cap[j] = cap[RESOURCE_PODS].value()
        label_bits[j] = bitset(
            [label_vocab.id(f"{k}={v}") for k, v in (n.metadata.labels or {}).items()],
            LW,
        )
        schedulable[j] = node_is_ready(n)

    for p in assigned_pods:
        j = node_index.get(p.spec.node_name)
        if j is None:
            continue
        cpu, mem = pod_resource_limits(p)
        mem_mib = mem_to_mib_ceil(mem)
        # Scoring-side: full sums + pod count.
        cpu_used[j] += cpu
        mem_used[j] += mem_mib
        pods_used[j] += 1
        # Feasibility-side: greedy simulation in list order.
        fits_cpu = cpu_cap[j] == 0 or cpu_fit_used[j] + cpu <= cpu_cap[j]
        fits_mem = mem_cap[j] == 0 or mem_fit_used[j] + mem_mib <= mem_cap[j]
        if fits_cpu and fits_mem:
            cpu_fit_used[j] += cpu
            mem_fit_used[j] += mem_mib
        else:
            overcommitted[j] = True
        used_port_bits[j] |= bitset(
            [port_vocab.id(str(x)) for x in pod_host_ports(p)], PW
        )
        vols = pod_volumes(p)
        used_vol_any[j] |= bitset([vol_vocab.id(v) for v, _ in vols], VW)
        used_vol_rw[j] |= bitset([vol_vocab.id(v) for v, rw in vols if rw], VW)

    # Spreading counts: every pod (phase-unfiltered) contributes to
    # every service whose selector matches its labels.
    for p in all_assigned:
        j = node_index.get(p.spec.node_name)
        if j is None:
            continue
        service_counts[j] += _service_membership(p, services)

    return Snapshot(
        pods=PodColumns(
            names=[pod_key(p) for p in pending_pods],
            cpu_milli=cpu_req,
            mem_mib=mem_req,
            zero_req=zero_req,
            selector_id=pod_sel_rows,
            port_bits=port_bits,
            vol_any_bits=vol_any,
            vol_rw_bits=vol_rw,
            pinned_node=pinned,
            service_id=service_id,
            svc_member=svc_member,
            sel_bits=sel_bits,
        ),
        nodes=NodeColumns(
            names=[n.metadata.name for n in nodes],
            cpu_cap=cpu_cap,
            mem_cap=mem_cap,
            pods_cap=pods_cap,
            cpu_fit_used=cpu_fit_used,
            mem_fit_used=mem_fit_used,
            overcommitted=overcommitted,
            cpu_used=cpu_used,
            mem_used=mem_used,
            pods_used=pods_used,
            label_bits=label_bits,
            used_port_bits=used_port_bits,
            used_vol_any_bits=used_vol_any,
            used_vol_rw_bits=used_vol_rw,
            service_counts=service_counts,
            schedulable=schedulable,
        ),
        label_vocab=label_vocab,
        port_vocab=port_vocab,
        vol_vocab=vol_vocab,
        service_names=[f"{s.metadata.namespace}/{s.metadata.name}" for s in services],
    )
