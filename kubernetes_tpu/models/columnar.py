"""Columnar (struct-of-arrays) encodings of pods and nodes.

This is the matrix schema consumed by the TPU scheduler path: the
reference's per-pod Go loops over object graphs
(plugin/pkg/scheduler/generic_scheduler.go:106-171,
plugin/pkg/scheduler/algorithm/predicates/predicates.go) become dense
ops over these arrays. Semantics mirror the scalar oracle
(kubernetes_tpu.scheduler.predicates/priorities) bit for bit wherever
integers allow.

Design notes (TPU-first):
- Resources are lowered once, host-side, to integer-valued float32
  columns: CPU in millicores, memory in MiB. float32 holds integers
  exactly up to 2^24, i.e. 16 TiB of MiB-granular memory and 16M
  millicores — beyond any single node. Requests round UP to MiB and
  capacity rounds DOWN, so lowering can under-promise but never
  overcommit. Integer score truncation (priorities.go:39) is then exact
  on device for Mi-granular quantities.
- Resource accounting uses container LIMITS, matching the v0.19
  reference (getResourceRequest, predicates.go:106-114).
- PodFitsResources parity needs three per-node facts (predicates.go:
  116-156): the greedy-fitted usage sums, whether ANY existing pod
  overflowed the greedy simulation (such nodes reject every new pod),
  and the existing-pod count vs pods capacity. Priorities instead use
  the FULL usage sums including overflowing pods (calculateOccupancy,
  priorities.go:44-58). Both are encoded.
- Set-valued predicates (nodeSelector subset-match, hostPort conflicts,
  exclusive-disk conflicts) use snapshot-scoped vocabularies: every
  distinct key=value / port / volume-id observed is assigned an id, and
  membership becomes uint32 bitsets. Volumes carry two bitsets (all
  mounts vs read-write mounts) so the GCE-PD both-read-only exemption
  (predicates.go:59-66) survives lowering; AWS EBS volumes set both
  bits because they conflict regardless of read-only.
- Pods with identical selector sets share a row in a deduped selector
  table, so selector bitsets are stored once per distinct selector.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.algspec import (
    AlgorithmSpec,
    LoweredSpec,
    lower_spec,
)
from kubernetes_tpu.models.objects import (
    Node,
    Pod,
    REBALANCE_DEST_ANNOTATION,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Service,
)

MIB = 1024 * 1024

# Services a single pod can belong to on device (top-K id list; pods
# matching more than SVC_K services contribute only their first SVC_K —
# far beyond any realistic overlap). Shared by the device path
# (ops.matrices), the sequential oracle, and the incremental session so
# truncation is identical everywhere.
SVC_K = 8


# ---------------------------------------------------------------------------
# Vocabularies
# ---------------------------------------------------------------------------


class Vocab:
    """Snapshot-scoped string->id mapping used for bitset encodings."""

    def __init__(self):
        self.index: Dict[str, int] = {}

    def id(self, token: str) -> int:
        i = self.index.get(token)
        if i is None:
            i = len(self.index)
            self.index[token] = i
        return i

    def __len__(self) -> int:
        return len(self.index)

    @property
    def words(self) -> int:
        """Number of uint32 words needed for a bitset (at least 1)."""
        return max(1, (len(self.index) + 31) // 32)


def bitset(ids: Sequence[int], words: int) -> np.ndarray:
    out = np.zeros(words, dtype=np.uint32)
    for i in ids:
        out[i >> 5] |= np.uint32(1 << (i & 31))
    return out


# ---------------------------------------------------------------------------
# Resource lowering
# ---------------------------------------------------------------------------


def pod_resource_limits(pod: Pod) -> Tuple[int, int]:
    """Sum of container LIMITS: (milli-CPU, memory bytes).

    Reference: predicates.go:106-114 getResourceRequest — v0.19 sums
    limits.Cpu().MilliValue() and limits.Memory().Value().
    """
    cpu = 0
    mem = 0
    for c in pod.spec.containers:
        lim = c.resources.limits
        if RESOURCE_CPU in lim:
            cpu += lim[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in lim:
            mem += lim[RESOURCE_MEMORY].value()
    return cpu, mem


def mem_to_mib_ceil(mem_bytes: int) -> int:
    return -((-mem_bytes) // MIB)


def pod_host_ports(pod: Pod) -> List[int]:
    """Nonzero hostPorts (getUsedPorts skips 0 at the check site,
    predicates.go:337-349)."""
    ports = []
    for c in pod.spec.containers:
        for p in c.ports:
            if p.host_port > 0:
                ports.append(p.host_port)
    return ports


def pod_volumes(pod: Pod) -> List[Tuple[str, bool]]:
    """Exclusive volumes as (id, read_write) pairs.

    GCE PD mounts conflict unless BOTH are read-only; AWS EBS mounts
    always conflict (isVolumeConflict, predicates.go:53-78) — EBS is
    returned as read_write=True regardless.
    """
    vols = []
    for v in pod.spec.volumes:
        if v.gce_persistent_disk is not None and v.gce_persistent_disk.pd_name:
            vols.append(
                ("gce-pd:" + v.gce_persistent_disk.pd_name,
                 not v.gce_persistent_disk.read_only)
            )
        if (
            v.aws_elastic_block_store is not None
            and v.aws_elastic_block_store.volume_id
        ):
            vols.append(("aws-ebs:" + v.aws_elastic_block_store.volume_id, True))
    return vols


# ---------------------------------------------------------------------------
# Columnar batches
# ---------------------------------------------------------------------------


@dataclass
class PodColumns:
    """Struct-of-arrays for P pending pods."""

    names: List[str]  # namespace/name keys, host-side only
    cpu_milli: np.ndarray  # f32[P]
    mem_mib: np.ndarray  # f32[P]
    zero_req: np.ndarray  # bool[P] — cpu==0 and mem==0 (different fit rule)
    selector_id: np.ndarray  # i32[P] — row into sel_bits (0 = empty selector)
    port_bits: np.ndarray  # u32[P, PW]
    vol_any_bits: np.ndarray  # u32[P, VW] — all exclusive mounts
    vol_rw_bits: np.ndarray  # u32[P, VW] — read-write mounts only
    pinned_node: np.ndarray  # i32[P] — node index, -1 unpinned, -2 unknown
    service_id: np.ndarray  # i32[P] — first matching service, -1 if none
    svc_topk: np.ndarray  # i32[P, SVC_K] — matching service ids, -1 pad
    sel_bits: np.ndarray  # u32[U, LW] — deduped selector table
    # Policy-spec columns (None unless a non-default spec is lowered):
    # per ServiceAffinity label: the pod's pinned nodeSelector pair id
    # (label vocab id of "l=v"), -1 when the pod doesn't pin it.
    aff_pin: Optional[np.ndarray] = None  # i32[P, K]

    @property
    def count(self) -> int:
        return len(self.names)


@dataclass
class NodeColumns:
    """Struct-of-arrays for N nodes (capacity + current occupancy)."""

    names: List[str]
    cpu_cap: np.ndarray  # f32[N] millicores
    mem_cap: np.ndarray  # f32[N] MiB
    pods_cap: np.ndarray  # f32[N] max pods
    # Feasibility-side occupancy: greedy-fitted sums + overflow flag
    # (CheckPodsExceedingCapacity semantics).
    cpu_fit_used: np.ndarray  # f32[N]
    mem_fit_used: np.ndarray  # f32[N]
    overcommitted: np.ndarray  # bool[N] — some existing pod overflowed
    # Scoring-side occupancy: FULL sums (calculateOccupancy semantics).
    cpu_used: np.ndarray  # f32[N]
    mem_used: np.ndarray  # f32[N]
    pods_used: np.ndarray  # f32[N] — count of existing (non-terminal) pods
    label_bits: np.ndarray  # u32[N, LW]
    used_port_bits: np.ndarray  # u32[N, PW]
    used_vol_any_bits: np.ndarray  # u32[N, VW]
    used_vol_rw_bits: np.ndarray  # u32[N, VW]
    service_counts: np.ndarray  # f32[N, S] — matching-pod count per service
    schedulable: np.ndarray  # bool[N] — Ready and not unschedulable
    # Policy-spec columns (None unless a non-default spec is lowered):
    policy_ok: Optional[np.ndarray] = None  # bool[N] — NodeLabelPresence AND
    static_prio: Optional[np.ndarray] = None  # i32[N] — LabelPreference sum
    aff_vid: Optional[np.ndarray] = None  # i32[N, K] — "l=value" pair ids
    aa_zone: Optional[np.ndarray] = None  # i32[N, I] — anti-affinity zones

    @property
    def count(self) -> int:
        return len(self.names)


@dataclass
class Snapshot:
    """One scheduling problem: P pending pods x N nodes."""

    pods: PodColumns
    nodes: NodeColumns
    label_vocab: Vocab
    port_vocab: Vocab
    vol_vocab: Vocab
    service_names: List[str]
    # Non-default policy lowering (None for the default pipeline):
    lowered: Optional[LoweredSpec] = None
    weights: Optional[Tuple[int, int, int]] = None
    # ServiceAffinity / ServiceAntiAffinity carry seeds, one slot per
    # service: index of the node hosting each service's FIRST listed
    # peer (-1 none, -2 unknown node — the scalar's error case), and
    # the phase-unfiltered peer count (numServicePods).
    anchor_init: Optional[np.ndarray] = None  # i32[max(S,1)]
    svc_total_init: Optional[np.ndarray] = None  # f32[max(S,1)]


def pod_key(pod: Pod) -> str:
    """Canonical 'namespace/name' key with the empty namespace
    normalized to 'default' — the SAME scheme the daemons' pending-path
    maps, gang keys, and preemption records use (models.objects.
    pod_full_key is the typed twin). One scheme everywhere: a pod
    created with namespace='' must solve, match, and bind under ONE
    key, never slip between '/p' and 'default/p' (ADVICE r5)."""
    return f"{pod.metadata.namespace or 'default'}/{pod.metadata.name}"


def node_is_ready(node: Node) -> bool:
    """Reference: StoreToNodeLister filters to Ready nodes
    (pkg/client/cache/listers.go) and spec.unschedulable gates fit."""
    if node.spec.unschedulable:
        return False
    for c in node.status.conditions:
        if c.type == "Ready":
            return c.status == "True"
    # Nodes with no conditions reported are treated as ready (matches the
    # reference's permissive default for freshly registered nodes).
    return True


_EMPTY_IDS = np.zeros(0, dtype=np.int64)


class ServiceMatcher:
    """Inverted index over service selectors: pod -> multi-hot
    membership in O(pod labels), not O(services).

    Semantics identical to the naive scan: a pod matches a service iff
    they share a namespace, the selector is non-empty, and every
    selector pair appears in the pod's labels. The pending pod spreads
    against its FIRST match (GetPodServices / spreading.go:44-56), but
    as an *existing* pod it is counted by every matching service
    (pod_lister.list(selector) in CalculateSpreadPriority). At 50k
    pods x 500 services the naive scan is 25M dict compares — the
    dominant host cost of snapshot lowering.
    """

    def __init__(self, services: List[Service]):
        self.S = len(services)
        self.out_width = max(self.S, 1)
        # namespace -> ((k,v) -> np.array of service indices)
        self._pair_index: Dict[str, Dict[Tuple[str, str], np.ndarray]] = {}
        self._sel_size = np.zeros(max(self.S, 1), dtype=np.int32)
        # Pods from one RC share an identical label set, so membership
        # is memoized by (namespace, labels) signature: a 50k-pod
        # backlog with a few hundred distinct templates costs a few
        # hundred matches, not 50k. Bounded: long-lived sessions
        # (incremental.SolverSession holds one matcher for its life)
        # feeding per-pod-unique labels must not grow host memory
        # without limit — on overflow the cache resets wholesale
        # (recomputing a membership is cheap; unbounded growth is not).
        self._id_cache: Dict[Tuple, Tuple[np.ndarray, int]] = {}
        self._cache_limit = 65536
        by_ns: Dict[str, Dict[Tuple[str, str], List[int]]] = {}
        for i, svc in enumerate(services):
            sel = svc.spec.selector
            if not sel:
                continue  # selector-less services never match
            self._sel_size[i] = len(sel)
            ns_idx = by_ns.setdefault(svc.metadata.namespace, {})
            for pair in sel.items():
                ns_idx.setdefault(pair, []).append(i)
        for ns, idx in by_ns.items():
            self._pair_index[ns] = {
                pair: np.asarray(ids, dtype=np.int64) for pair, ids in idx.items()
            }

    def membership(self, pod: Pod) -> np.ndarray:
        """Multi-hot f32[max(S,1)]."""
        out = np.zeros(self.out_width, dtype=np.float32)
        idx = self._pair_index.get(pod.metadata.namespace)
        labels = pod.metadata.labels
        if not idx or not labels:
            return out
        counts = np.zeros(self.out_width, dtype=np.int32)
        for pair in labels.items():
            ids = idx.get(pair)
            if ids is not None:
                counts[ids] += 1
        matched = (counts == self._sel_size) & (self._sel_size > 0)
        out[: len(matched)] = matched
        return out

    def first_match(self, member: np.ndarray) -> int:
        nz = np.nonzero(member[: self.S])[0]
        return int(nz[0]) if len(nz) else -1

    def membership_ids(self, pod: Pod) -> Tuple[np.ndarray, int]:
        """(sorted matching service indices i64[k], first index or -1),
        memoized by (namespace, labels) signature."""
        labels = pod.metadata.labels
        ns = pod.metadata.namespace
        if not labels or ns not in self._pair_index:
            return _EMPTY_IDS, -1
        # Tuple of items, not frozenset: ~2x cheaper to build+hash, and
        # this key construction runs once per pod on the lowering
        # critical path. Same labels in a different insertion order
        # produce a second (identical-valued) entry — harmless.
        key = (ns, tuple(labels.items()))
        hit = self._id_cache.get(key)
        if hit is not None:
            return hit
        idx = self._pair_index[ns]
        counts = np.zeros(self.out_width, dtype=np.int32)
        for pair in labels.items():
            ids = idx.get(pair)
            if ids is not None:
                counts[ids] += 1
        matched = np.nonzero((counts == self._sel_size) & (self._sel_size > 0))[0]
        hit = (matched, int(matched[0]) if len(matched) else -1)
        if len(self._id_cache) >= self._cache_limit:
            self._id_cache.clear()
        self._id_cache[key] = hit
        return hit


def _service_membership(pod: Pod, services: List[Service]) -> np.ndarray:
    """One-shot convenience wrapper (tests); bulk callers build one
    ServiceMatcher and reuse it."""
    return ServiceMatcher(services).membership(pod)


class SnapshotBuilder:
    """Two-phase lowering: a cheap vocabulary pass over ALL objects,
    then column fills that may be CHUNKED over the pending backlog.

    Chunking exists so the host->device pipeline can overlap: lower
    chunk k+1 on the host while the device solves chunk k (the solver
    carry chains placements across chunks, so decisions are identical
    to one monolithic solve). build_snapshot() is the one-shot wrapper.
    """

    def __init__(
        self,
        pending_pods: Sequence[Pod],
        nodes: Sequence[Node],
        assigned_pods: Sequence[Pod] = (),
        services: Sequence[Service] = (),
        spec: Optional[AlgorithmSpec] = None,
    ):
        # A non-default AlgorithmSpec adds policy columns (and may
        # raise UnloweredPolicyError right here, before any lowering
        # work — the batch daemon catches it and runs the scalar path).
        self.spec = None if spec is None or spec.is_default() else spec
        if self.spec is not None:
            self._lowered_partial, self._weights = lower_spec(self.spec)
        self.nodes = list(nodes)
        self.pending = list(pending_pods)
        self.services = list(services)
        # Terminal-phase filtering applies to OCCUPANCY
        # (MapPodsToMachines / filterNonRunningPods,
        # predicates.go:361-377) but NOT to service spreading counts —
        # CalculateSpreadPriority lists pods by selector with no phase
        # filter (spreading.go:44-57).
        self.all_assigned = list(assigned_pods)
        self.assigned = [
            p
            for p in self.all_assigned
            if p.status.phase not in ("Succeeded", "Failed")
        ]
        self.node_index = {n.metadata.name: i for i, n in enumerate(self.nodes)}
        self.S = len(self.services)
        self.matcher = ServiceMatcher(self.services)
        self.label_vocab, self.port_vocab, self.vol_vocab = (
            Vocab(),
            Vocab(),
            Vocab(),
        )

        # -- vocabulary passes (one sweep each; selector table dedup) --
        for n in self.nodes:
            for k, v in (n.metadata.labels or {}).items():
                self.label_vocab.id(f"{k}={v}")
        # Vocab pass over every pod: fully serial before the first
        # chunk can lower, so it sits on the pipelined solve's critical
        # path — locals bound outside the loop, helper calls inlined,
        # and the overwhelmingly common empty selector/port/volume
        # cases short-circuited (was ~0.18s of the 50k wall).
        self.sel_keys: Dict[Tuple[Tuple[str, str], ...], int] = {(): 0}
        self._pod_sel_rows = np.zeros(len(self.pending), dtype=np.int32)
        label_id = self.label_vocab.id
        port_id = self.port_vocab.id
        vol_id = self.vol_vocab.id
        sel_keys = self.sel_keys
        sel_rows = self._pod_sel_rows
        for i, p in enumerate(self.pending):
            spec = p.spec
            nsel = spec.node_selector
            if nsel:
                sel = tuple(sorted(nsel.items()))
                for k, v in sel:
                    label_id(f"{k}={v}")
                sel_rows[i] = sel_keys.setdefault(sel, len(sel_keys))
            for c in spec.containers:
                for cp in c.ports:
                    if cp.host_port > 0:
                        port_id(str(cp.host_port))
            if spec.volumes:
                for vol, _rw in pod_volumes(p):
                    vol_id(vol)
        for p in self.assigned:
            for c in p.spec.containers:
                for cp in c.ports:
                    if cp.host_port > 0:
                        port_id(str(cp.host_port))
            if p.spec.volumes:
                for vol, _rw in pod_volumes(p):
                    vol_id(vol)
        self.LW = self.label_vocab.words
        self.PW = self.port_vocab.words
        self.VW = self.vol_vocab.words
        self._sel_bits: Optional[np.ndarray] = None

    @property
    def sel_bits(self) -> np.ndarray:
        if self._sel_bits is None:
            out = np.zeros((len(self.sel_keys), self.LW), dtype=np.uint32)
            for sel, row in self.sel_keys.items():
                out[row] = bitset(
                    [self.label_vocab.id(f"{k}={v}") for k, v in sel], self.LW
                )
            self._sel_bits = out
        return self._sel_bits

    def pod_columns(self, start: int = 0, stop: Optional[int] = None) -> PodColumns:
        """Lower pending pods [start:stop) (the whole backlog by
        default). Chunks share the global vocabularies/selector table."""
        from kubernetes_tpu import native

        stop = len(self.pending) if stop is None else stop
        chunk = self.pending[start:stop]
        P = len(chunk)
        # This loop IS the serial "lower" phase of the pipelined solve
        # (the only host work on the 50k-backlog critical path), so the
        # extraction helpers (pod_resource_limits / pod_host_ports /
        # pod_volumes — the single-pod API, kept for tests and scalar
        # callers) are inlined here with locals bound outside the loop:
        # per-pod function-call + per-element ndarray-store overhead was
        # ~40% of the phase at 50k pods.
        cpu_list: List[float] = []
        mem_list: List[int] = []
        zero_list: List[bool] = []
        pinned = np.full(P, -1, dtype=np.int32)
        service_id = np.full(P, -1, dtype=np.int32)
        svc_topk = np.full((P, SVC_K), -1, dtype=np.int32)
        port_id_lists: List[List[int]] = []
        vol_any_lists: List[List[int]] = []
        vol_rw_lists: List[List[int]] = []
        port_vocab_id = self.port_vocab.id
        vol_vocab_id = self.vol_vocab.id
        node_index_get = self.node_index.get
        membership_ids = self.matcher.membership_ids
        cpu_key, mem_key = RESOURCE_CPU, RESOURCE_MEMORY
        for i, p in enumerate(chunk):
            spec = p.spec
            cpu = 0
            mem = 0
            port_ids: List[int] = []
            for c in spec.containers:
                lim = c.resources.limits
                q = lim.get(cpu_key)
                if q is not None:
                    cpu += q.milli_value()
                q = lim.get(mem_key)
                if q is not None:
                    mem += q.value()
                for cp in c.ports:
                    hp = cp.host_port
                    if hp > 0:
                        port_ids.append(port_vocab_id(str(hp)))
            cpu_list.append(cpu)
            mem_list.append(-((-mem) // MIB))  # mem_to_mib_ceil
            zero_list.append(cpu == 0 and mem == 0)
            port_id_lists.append(port_ids)
            vol_any: List[int] = []
            vol_rw: List[int] = []
            for v in spec.volumes:
                pd = v.gce_persistent_disk
                if pd is not None and pd.pd_name:
                    vid = vol_vocab_id("gce-pd:" + pd.pd_name)
                    vol_any.append(vid)
                    if not pd.read_only:
                        vol_rw.append(vid)
                ebs = v.aws_elastic_block_store
                if ebs is not None and ebs.volume_id:
                    vid = vol_vocab_id("aws-ebs:" + ebs.volume_id)
                    vol_any.append(vid)
                    vol_rw.append(vid)
            vol_any_lists.append(vol_any)
            vol_rw_lists.append(vol_rw)
            if spec.node_name:
                pinned[i] = node_index_get(spec.node_name, -2)
            else:
                # Rebalance nomination: a pod the descheduler recreated
                # after a defrag eviction carries its planned
                # destination as an annotation (mirrored in
                # status.nominatedNodeName); honor it as a HostName pin
                # so the micro-tick daemon rebinds it there. Unknown
                # node -> unpinned (-1): a destination that vanished
                # mid-move must not strand the pod, it just re-solves
                # anywhere.
                dest = (p.metadata.annotations or {}).get(
                    REBALANCE_DEST_ANNOTATION, ""
                )
                if dest:
                    pinned[i] = node_index_get(dest, -1)
            ids, first = membership_ids(p)
            if len(ids):
                k = min(len(ids), SVC_K)
                svc_topk[i, :k] = ids[:k]
                service_id[i] = first
        cpu_req = np.asarray(cpu_list, dtype=np.float32)
        mem_req = np.asarray(mem_list, dtype=np.float32)
        zero_req = np.asarray(zero_list, dtype=bool)
        aff_pin = None
        if self.spec is not None and self.spec.affinity_labels:
            # ServiceAffinity: per affinity label, the pod's pinned
            # "l=v" pair id from its nodeSelector (predicates.go:273-281
            # — pinned values are never overridden by the anchor peer).
            aff = self.spec.affinity_labels
            aff_pin = np.full((P, len(aff)), -1, dtype=np.int32)
            for i, p in enumerate(chunk):
                nsel = p.spec.node_selector or {}
                for k, label in enumerate(aff):
                    if label in nsel:
                        aff_pin[i, k] = self.label_vocab.id(
                            f"{label}={nsel[label]}"
                        )
        return PodColumns(
            names=[pod_key(p) for p in chunk],
            cpu_milli=cpu_req,
            mem_mib=mem_req,
            zero_req=zero_req,
            selector_id=self._pod_sel_rows[start:stop],
            port_bits=native.pack_bitsets(port_id_lists, self.PW),
            vol_any_bits=native.pack_bitsets(vol_any_lists, self.VW),
            vol_rw_bits=native.pack_bitsets(vol_rw_lists, self.VW),
            pinned_node=pinned,
            service_id=service_id,
            svc_topk=svc_topk,
            sel_bits=self.sel_bits,
            aff_pin=aff_pin,
        )

    def node_columns(self) -> NodeColumns:
        from kubernetes_tpu import native

        nodes, N = self.nodes, len(self.nodes)
        LW, PW, VW = self.LW, self.PW, self.VW
        cpu_cap = np.zeros(N, dtype=np.float32)
        mem_cap = np.zeros(N, dtype=np.float32)
        pods_cap = np.zeros(N, dtype=np.float32)
        cpu_fit_used = np.zeros(N, dtype=np.float32)
        mem_fit_used = np.zeros(N, dtype=np.float32)
        overcommitted = np.zeros(N, dtype=bool)
        cpu_used = np.zeros(N, dtype=np.float32)
        mem_used = np.zeros(N, dtype=np.float32)
        pods_used = np.zeros(N, dtype=np.float32)
        label_bits = np.zeros((N, LW), dtype=np.uint32)
        used_port_bits = np.zeros((N, PW), dtype=np.uint32)
        used_vol_any = np.zeros((N, VW), dtype=np.uint32)
        used_vol_rw = np.zeros((N, VW), dtype=np.uint32)
        service_counts = np.zeros((N, max(self.S, 1)), dtype=np.float32)
        schedulable = np.zeros(N, dtype=bool)
        for j, n in enumerate(nodes):
            cap = n.status.capacity or {}
            if RESOURCE_CPU in cap:
                cpu_cap[j] = cap[RESOURCE_CPU].milli_value()
            if RESOURCE_MEMORY in cap:
                # Capacity rounds DOWN (requests round up) so lowering
                # can only under-promise, never overcommit a node.
                mem_cap[j] = cap[RESOURCE_MEMORY].value() // MIB
            if RESOURCE_PODS in cap:
                pods_cap[j] = cap[RESOURCE_PODS].value()
            label_bits[j] = bitset(
                [
                    self.label_vocab.id(f"{k}={v}")
                    for k, v in (n.metadata.labels or {}).items()
                ],
                LW,
            )
            schedulable[j] = node_is_ready(n)

        # Assigned-pod occupancy sweep through the native kernels
        # (MapPodsToMachines greedy order = list order).
        A = len(self.assigned)
        a_idx = np.full(A, -1, dtype=np.int32)
        a_cpu = np.zeros(A, dtype=np.float32)
        a_mem = np.zeros(A, dtype=np.float32)
        a_port_lists: List[List[int]] = []
        a_vol_any_lists: List[List[int]] = []
        a_vol_rw_lists: List[List[int]] = []
        for i, p in enumerate(self.assigned):
            j = self.node_index.get(p.spec.node_name)
            a_idx[i] = -1 if j is None else j
            cpu, mem = pod_resource_limits(p)
            a_cpu[i] = cpu
            a_mem[i] = mem_to_mib_ceil(mem)
            a_port_lists.append(
                [self.port_vocab.id(str(x)) for x in pod_host_ports(p)]
            )
            vols = pod_volumes(p)
            a_vol_any_lists.append([self.vol_vocab.id(v) for v, _ in vols])
            a_vol_rw_lists.append(
                [self.vol_vocab.id(v) for v, rw in vols if rw]
            )
        native.greedy_fit(
            a_idx, a_cpu, a_mem, cpu_cap, mem_cap,
            cpu_fit_used, mem_fit_used, overcommitted, cpu_used, mem_used,
            pods_used,
        )
        native.or_rows_by_index(
            a_idx, native.pack_bitsets(a_port_lists, PW), used_port_bits
        )
        native.or_rows_by_index(
            a_idx, native.pack_bitsets(a_vol_any_lists, VW), used_vol_any
        )
        native.or_rows_by_index(
            a_idx, native.pack_bitsets(a_vol_rw_lists, VW), used_vol_rw
        )

        # Spreading counts: every pod (phase-unfiltered) contributes to
        # every service whose selector matches its labels.
        for p in self.all_assigned:
            j = self.node_index.get(p.spec.node_name)
            if j is None:
                continue
            ids, _ = self.matcher.membership_ids(p)
            if len(ids):
                service_counts[j, ids] += 1.0

        policy_ok = static_prio = aff_vid = aa_zone = None
        if self.spec is not None:
            policy_ok, static_prio, aff_vid, aa_zone = self._policy_node_columns()

        return NodeColumns(
            names=[n.metadata.name for n in nodes],
            cpu_cap=cpu_cap,
            mem_cap=mem_cap,
            pods_cap=pods_cap,
            cpu_fit_used=cpu_fit_used,
            mem_fit_used=mem_fit_used,
            overcommitted=overcommitted,
            cpu_used=cpu_used,
            mem_used=mem_used,
            pods_used=pods_used,
            label_bits=label_bits,
            used_port_bits=used_port_bits,
            used_vol_any_bits=used_vol_any,
            used_vol_rw_bits=used_vol_rw,
            service_counts=service_counts,
            schedulable=schedulable,
            policy_ok=policy_ok,
            static_prio=static_prio,
            aff_vid=aff_vid,
            aa_zone=aa_zone,
        )

    # -- policy-spec lowering -----------------------------------------

    def _policy_node_columns(self):
        """Node-side columns for the configurable vocabulary. All are
        pure node facts, so they lower host-side to static columns; the
        order-dependent ServiceAffinity anchor state lives in the
        solver carry instead (seeded by _service_seeds)."""
        spec, N = self.spec, len(self.nodes)
        node_labels = [n.metadata.labels or {} for n in self.nodes]
        # CheckNodeLabelPresence (predicates.go:226-240): pod-independent
        # — one AND-combined bool per node across all instances.
        policy_ok = None
        checkers = [p for p in spec.predicates if p.kind == "NodeLabelPresence"]
        if checkers:
            policy_ok = np.ones(N, dtype=bool)
            for j, labels in enumerate(node_labels):
                for c in checkers:
                    for label in c.labels:
                        exists = label in labels
                        if (exists and not c.presence) or (
                            not exists and c.presence
                        ):
                            policy_ok[j] = False
                            break
                    else:
                        continue
                    break
        # CalculateNodeLabelPriority (priorities.go:113-138): static
        # 10-or-0 per node, summed over instances with weights.
        static_prio = None
        prefs = [
            p
            for p in spec.priorities
            if p.kind == "LabelPreference" and p.weight != 0
        ]
        if prefs:
            static_prio = np.zeros(N, dtype=np.int32)
            for j, labels in enumerate(node_labels):
                for p in prefs:
                    exists = p.label in labels
                    if (exists and p.presence) or (not exists and not p.presence):
                        static_prio[j] += 10 * p.weight
        # ServiceAffinity: per node per affinity label, the "l=value"
        # pair id (shared vocab with pod nodeSelector pins, so equality
        # is one integer compare on device).
        aff_vid = None
        aff = spec.affinity_labels
        if aff:
            aff_vid = np.full((N, len(aff)), -1, dtype=np.int32)
            for j, labels in enumerate(node_labels):
                for k, label in enumerate(aff):
                    if label in labels:
                        aff_vid[j, k] = self.label_vocab.id(
                            f"{label}={labels[label]}"
                        )
        # ServiceAntiAffinity (spreading.go:105-169): nodes partition
        # into zones by the value of one label; -1 = unlabeled (scores
        # a flat 0). Zone vocabularies are per instance and compact,
        # bucketed to 16 so value churn reuses compiled executables.
        aa_zone = None
        self._aa_zones: Tuple[int, ...] = ()
        # Filter EXACTLY like lower_spec filters aa_weights: columns
        # here and weights there are zipped positionally in the solver.
        antis = [
            p
            for p in spec.priorities
            if p.kind == "ServiceAntiAffinity" and p.weight != 0
        ]
        if antis:
            aa_zone = np.full((N, len(antis)), -1, dtype=np.int32)
            zones = []
            for i, p in enumerate(antis):
                vocab: Dict[str, int] = {}
                for j, labels in enumerate(node_labels):
                    if p.label in labels:
                        aa_zone[j, i] = vocab.setdefault(
                            labels[p.label], len(vocab)
                        )
                zones.append(max(16, -(-len(vocab) // 16) * 16))
            self._aa_zones = tuple(zones)
        return policy_ok, static_prio, aff_vid, aa_zone

    def _service_seeds(self) -> Tuple[np.ndarray, np.ndarray]:
        """Seed the ServiceAffinity/AntiAffinity carry from the
        already-assigned pods: per service, the node index of the FIRST
        listed peer (nsServicePods[0], predicates.go:301-313; -1 no
        peers, -2 peer on an unknown node = the scalar's GetNodeInfo
        error, which fails the pod everywhere) and the peer count
        (numServicePods, spreading.go:150 — node-presence-unfiltered)."""
        S1 = max(self.S, 1)
        anchor = np.full(S1, -1, dtype=np.int32)
        total = np.zeros(S1, dtype=np.float32)
        for p in self.all_assigned:
            ids, _ = self.matcher.membership_ids(p)
            if not len(ids):
                continue
            total[ids] += 1.0
            j = self.node_index.get(p.spec.node_name)
            for sid in ids:
                if anchor[sid] == -1:
                    anchor[sid] = -2 if j is None else j
        return anchor, total

    def snapshot(self) -> Snapshot:
        pods = self.pod_columns()
        nodes = self.node_columns()
        lowered = weights = anchor = svc_total = None
        if self.spec is not None:
            lowered = self._lowered_partial._replace(aa_zones=self._aa_zones)
            weights = self._weights
            if lowered.service_affinity or lowered.aa_weights:
                anchor, svc_total = self._service_seeds()
        return Snapshot(
            pods=pods,
            nodes=nodes,
            label_vocab=self.label_vocab,
            port_vocab=self.port_vocab,
            vol_vocab=self.vol_vocab,
            service_names=[
                f"{s.metadata.namespace}/{s.metadata.name}"
                for s in self.services
            ],
            lowered=lowered,
            weights=weights,
            anchor_init=anchor,
            svc_total_init=svc_total,
        )


def build_snapshot(
    pending_pods: Sequence[Pod],
    nodes: Sequence[Node],
    assigned_pods: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    spec: Optional[AlgorithmSpec] = None,
) -> Snapshot:
    """Lower API objects into a dense scheduling snapshot.

    `assigned_pods` are pods already bound to nodes; they contribute to
    occupancy the way MapPodsToMachines does (predicates.go:379-392),
    with terminal-phase pods filtered out. A non-default `spec` adds
    the policy columns (raises UnloweredPolicyError for kinds with no
    columnar encoding).
    """
    return SnapshotBuilder(
        pending_pods, nodes, assigned_pods, services, spec=spec
    ).snapshot()
