"""Object model: typed API objects, resource quantities, label/field
selectors, validation, and columnar (struct-of-arrays) encodings for the
TPU scheduler path.

Reference parity: pkg/api/types.go, pkg/api/resource/, pkg/labels/,
pkg/fields/, pkg/api/validation/validation.go.
"""

from kubernetes_tpu.models.quantity import Quantity, parse_quantity
from kubernetes_tpu.models.objects import (
    ObjectMeta,
    Container,
    ContainerPort,
    ResourceRequirements,
    PodSpec,
    PodStatus,
    Pod,
    NodeStatus,
    NodeSpec,
    Node,
    ServiceSpec,
    ServicePort,
    Service,
    Endpoints,
    EndpointAddress,
    ReplicationControllerSpec,
    ReplicationController,
    Binding,
    Event,
    Namespace,
    Volume,
    Probe,
    DeleteOptions,
    ListMeta,
    Status,
)

__all__ = [
    "Quantity",
    "parse_quantity",
    "ObjectMeta",
    "Container",
    "ContainerPort",
    "ResourceRequirements",
    "PodSpec",
    "PodStatus",
    "Pod",
    "NodeStatus",
    "NodeSpec",
    "Node",
    "ServiceSpec",
    "ServicePort",
    "Service",
    "Endpoints",
    "EndpointAddress",
    "ReplicationControllerSpec",
    "ReplicationController",
    "Binding",
    "Event",
    "Namespace",
    "Volume",
    "Probe",
    "DeleteOptions",
    "ListMeta",
    "Status",
]
