"""Label and field selectors.

Behavioral parity with the reference's pkg/labels/ (Selector, Parse,
SelectorFromSet — used in the scheduler hot path at
plugin/pkg/scheduler/algorithm/predicates/predicates.go:176-177) and
pkg/fields/ (used e.g. for the unassigned-pod watch,
plugin/pkg/scheduler/factory/factory.go:226).

Grammar: comma-separated requirements, each one of
    key = value | key == value | key != value
    key in (v1, v2) | key notin (v1, v2)
    key            (exists)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Sequence

EQUALS = "="
DOUBLE_EQUALS = "=="
NOT_EQUALS = "!="
IN = "in"
NOT_IN = "notin"
EXISTS = "exists"


@dataclass(frozen=True)
class Requirement:
    key: str
    operator: str
    values: FrozenSet[str] = field(default_factory=frozenset)

    def matches(self, labels: Dict[str, str]) -> bool:
        if self.operator in (EQUALS, DOUBLE_EQUALS, IN):
            return self.key in labels and labels[self.key] in self.values
        if self.operator == NOT_EQUALS:
            return self.key not in labels or labels[self.key] not in self.values
        if self.operator == NOT_IN:
            # Reference semantics: notin requires the key to exist with a
            # value outside the set? pkg/labels Requirement.Matches for
            # NotIn returns true when the key is absent.
            return self.key not in labels or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return self.key in labels
        raise ValueError(f"unknown operator {self.operator!r}")

    def __str__(self) -> str:
        if self.operator == EXISTS:
            return self.key
        if self.operator in (EQUALS, DOUBLE_EQUALS, NOT_EQUALS):
            return f"{self.key}{self.operator}{next(iter(self.values))}"
        return f"{self.key} {self.operator} ({','.join(sorted(self.values))})"


class Selector:
    """A parsed label selector: conjunction of requirements."""

    def __init__(self, requirements: Sequence[Requirement] = ()):
        self.requirements: List[Requirement] = list(requirements)

    def matches(self, labels: Dict[str, str] | None) -> bool:
        labels = labels or {}
        return all(r.matches(labels) for r in self.requirements)

    def empty(self) -> bool:
        return not self.requirements

    def __str__(self) -> str:
        return ",".join(str(r) for r in self.requirements)

    def __eq__(self, other) -> bool:
        return isinstance(other, Selector) and set(map(str, self.requirements)) == set(
            map(str, other.requirements)
        )


def everything() -> Selector:
    return Selector()


def selector_from_set(labels: Dict[str, str] | None) -> Selector:
    """Exact-match selector from a map (reference: labels.SelectorFromSet)."""
    labels = labels or {}
    return Selector(
        [Requirement(k, EQUALS, frozenset([v])) for k, v in sorted(labels.items())]
    )


_SET_RE = re.compile(
    r"^\s*(?P<key>[A-Za-z0-9._/-]+)\s+(?P<op>in|notin)\s+\(\s*(?P<vals>[^)]*)\)\s*$"
)
_EQ_RE = re.compile(
    r"^\s*(?P<key>[A-Za-z0-9._/-]+)\s*(?P<op>==|=|!=)\s*(?P<val>[A-Za-z0-9._-]*)\s*$"
)
_EXISTS_RE = re.compile(r"^\s*(?P<key>[A-Za-z0-9._/-]+)\s*$")


def _split_top(s: str) -> List[str]:
    """Split on commas not inside parentheses."""
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


def parse(s: str | None) -> Selector:
    """Parse a selector string (reference: pkg/labels/selector.go Parse)."""
    if not s or not s.strip():
        return everything()
    reqs: List[Requirement] = []
    for part in _split_top(s):
        if not part.strip():
            continue
        m = _SET_RE.match(part)
        if m:
            vals = frozenset(v.strip() for v in m.group("vals").split(",") if v.strip())
            reqs.append(Requirement(m.group("key"), m.group("op"), vals))
            continue
        m = _EQ_RE.match(part)
        if m:
            op = m.group("op")
            op = NOT_EQUALS if op == "!=" else EQUALS
            reqs.append(Requirement(m.group("key"), op, frozenset([m.group("val")])))
            continue
        m = _EXISTS_RE.match(part)
        if m:
            reqs.append(Requirement(m.group("key"), EXISTS))
            continue
        raise ValueError(f"invalid selector segment: {part!r}")
    return Selector(reqs)


# ---------------------------------------------------------------------------
# Field selectors (reference: pkg/fields/) — only =, ==, != over flat fields.
# ---------------------------------------------------------------------------


class FieldSelector:
    def __init__(self, requirements: Sequence[tuple] = ()):
        # each requirement: (key, op, value) with op in {"=", "!="}
        self.requirements = list(requirements)

    def matches(self, fields: Dict[str, str]) -> bool:
        for key, op, value in self.requirements:
            have = fields.get(key, "")
            if op == EQUALS and have != value:
                return False
            if op == NOT_EQUALS and have == value:
                return False
        return True

    def empty(self) -> bool:
        return not self.requirements

    def __str__(self) -> str:
        return ",".join(
            f"{k}{'!=' if op == NOT_EQUALS else '='}{v}" for k, op, v in self.requirements
        )


def parse_fields(s: str | None) -> FieldSelector:
    if not s or not s.strip():
        return FieldSelector()
    reqs = []
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            reqs.append((k.strip(), NOT_EQUALS, v.strip()))
        elif "==" in part:
            k, v = part.split("==", 1)
            reqs.append((k.strip(), EQUALS, v.strip()))
        elif "=" in part:
            k, v = part.split("=", 1)
            reqs.append((k.strip(), EQUALS, v.strip()))
        else:
            raise ValueError(f"invalid field selector segment: {part!r}")
    return FieldSelector(reqs)
