"""Resource quantities.

Behavioral parity with the reference's pkg/api/resource/quantity.go:
quantities are decimal numbers with an optional SI or binary suffix
("100m" CPU = 0.1 cores, "64Mi" memory = 64*2^20 bytes). The scheduler
consumes them as integers: CPU via milli-value, memory via value
(reference: plugin/pkg/scheduler/algorithm/predicates/predicates.go:110-111).

Internally a Quantity is an exact integer count of milli-units, which
represents every suffix the reference supports without floating point.
"""

from __future__ import annotations

import re
from functools import total_ordering

_BINARY = {
    "Ki": 1024,
    "Mi": 1024**2,
    "Gi": 1024**3,
    "Ti": 1024**4,
    "Pi": 1024**5,
    "Ei": 1024**6,
}
_DECIMAL = {
    "n": -3,  # handled specially (sub-milli rounds up, like the reference's scale)
    "u": -2,
    "m": -1,
    "": 0,
    "k": 1,
    "M": 2,
    "G": 3,
    "T": 4,
    "P": 5,
    "E": 6,
}

_QTY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>\d+(?:\.\d+)?|\.\d+)(?P<suffix>[numkMGTPE]|[KMGTPE]i|)$"
)


@total_ordering
class Quantity:
    """An exact resource amount, stored as integer milli-units."""

    __slots__ = ("milli", "_suffix_hint")

    def __init__(self, milli: int = 0, suffix_hint: str = ""):
        self.milli = int(milli)
        self._suffix_hint = suffix_hint

    # -- constructors -------------------------------------------------
    @classmethod
    def from_string(cls, s: str) -> "Quantity":
        return parse_quantity(s)

    @classmethod
    def from_int(cls, v: int) -> "Quantity":
        return cls(int(v) * 1000)

    @classmethod
    def from_milli(cls, v: int) -> "Quantity":
        return cls(int(v), suffix_hint="m")

    # -- accessors (reference: Cpu().MilliValue(), Memory().Value()) --
    def milli_value(self) -> int:
        return self.milli

    def value(self) -> int:
        """Whole-unit value, rounding up like the reference's Value()."""
        return -((-self.milli) // 1000)

    def is_zero(self) -> bool:
        return self.milli == 0

    # -- arithmetic ---------------------------------------------------
    def __add__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli + other.milli, self._suffix_hint)

    def __sub__(self, other: "Quantity") -> "Quantity":
        return Quantity(self.milli - other.milli, self._suffix_hint)

    def __eq__(self, other) -> bool:
        return isinstance(other, Quantity) and self.milli == other.milli

    def __lt__(self, other: "Quantity") -> bool:
        return self.milli < other.milli

    def __hash__(self) -> int:
        return hash(self.milli)

    # -- formatting ---------------------------------------------------
    def __str__(self) -> str:
        m = self.milli
        if m == 0:
            return "0"
        # Preserve binary suffix hint when it divides evenly.
        hint = self._suffix_hint
        if hint in _BINARY and m % (1000 * _BINARY[hint]) == 0:
            return f"{m // (1000 * _BINARY[hint])}{hint}"
        if m % 1000 == 0:
            v = m // 1000
            # Compact large decimal values using the largest clean suffix.
            for suf in ("E", "P", "T", "G", "M", "k"):
                scale = 1000 ** _DECIMAL[suf]
                if v % scale == 0 and abs(v) >= scale and scale > 1:
                    return f"{v // scale}{suf}"
            return str(v)
        return f"{m}m"

    def __repr__(self) -> str:
        return f"Quantity({self!s})"

    def to_wire(self) -> str:
        return str(self)


def parse_quantity(s) -> Quantity:
    """Parse a quantity string ("250m", "2", "64Mi", "1.5Gi", "100M")."""
    if isinstance(s, Quantity):
        return s
    if isinstance(s, int):
        return Quantity.from_int(s)
    if isinstance(s, float):
        return Quantity(round(s * 1000))
    s = s.strip()
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    sign = -1 if m.group("sign") == "-" else 1
    num = m.group("num")
    suffix = m.group("suffix")

    if "." in num:
        int_part, frac_part = num.split(".")
        int_part = int_part or "0"
    else:
        int_part, frac_part = num, ""

    if suffix in _BINARY:
        base = _BINARY[suffix]
        milli = int(int_part) * base * 1000
        if frac_part:
            frac = int(frac_part) * base * 1000
            denom = 10 ** len(frac_part)
            # Round up fractional remainders (reference rounds up on scale).
            milli += -((-frac) // denom)
    else:
        power = _DECIMAL[suffix]
        # Express as milli-units: value * 10^(3*power) * 1000.
        exp = 3 * power + 3
        digits = int_part + frac_part
        point = len(int_part)  # digits before the decimal point
        # value = digits * 10^(point - len(digits)); milli = value * 10^exp
        shift = exp + point - len(digits)
        n = int(digits) if digits else 0
        if shift >= 0:
            milli = n * (10**shift)
        else:
            d = 10 ** (-shift)
            milli = -((-n) // d)  # round away from zero magnitude upward
    return Quantity(sign * milli, suffix_hint=suffix)
