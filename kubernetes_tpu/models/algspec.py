"""Algorithm specs: a structured description of the configured
predicate/priority set, shared by the scalar path and the TPU lowering.

The reference builds its scheduler from either an algorithm provider's
key sets (plugin/pkg/scheduler/algorithmprovider/defaults/defaults.go)
or a policy file naming predicates/priorities with arguments
(plugin/pkg/scheduler/api/types.go:25-104, factory/plugins.go:138-153).
Both converge here on an AlgorithmSpec: the single source of truth the
batch scheduler consults to decide whether the configured set can be
lowered to the device pipeline — and, when it can, exactly which
columns and score terms the solver needs. A policy-configured
scheduler therefore either runs the SAME decisions on device or falls
back to the scalar path with the configured plugins; it never silently
schedules with defaults (round-2 VERDICT Weak #1).

Lowerable vocabulary (all reference kinds):
  predicates: PodFitsPorts, PodFitsResources, NoDiskConflict,
    MatchNodeSelector, HostName (defaults.go:38-48);
    NodeLabelPresence (predicates.go:226-240),
    ServiceAffinity (predicates.go:268-335).
  priorities: LeastRequestedPriority, BalancedResourceAllocation,
    ServiceSpreadingPriority, EqualPriority (defaults.go:51-60);
    LabelPreference (priorities.go:113-138),
    ServiceAntiAffinity (spreading.go:105-169).
Anything else (user-registered custom plugins) raises
UnloweredPolicyError and the batch daemon uses the scalar path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, NamedTuple, Tuple

BASE_PREDICATES = (
    "PodFitsPorts",
    "PodFitsResources",
    "NoDiskConflict",
    "MatchNodeSelector",
    "HostName",
)
BASE_PRIORITIES = (
    "LeastRequestedPriority",
    "BalancedResourceAllocation",
    "ServiceSpreadingPriority",
    "EqualPriority",
)


class UnloweredPolicyError(Exception):
    """The configured plugin set has no columnar encoding."""


@dataclass(frozen=True)
class PredicateSpec:
    kind: str  # semantic kind, not the policy's display name
    labels: Tuple[str, ...] = ()
    presence: bool = True


@dataclass(frozen=True)
class PrioritySpec:
    kind: str
    weight: int = 1
    label: str = ""
    presence: bool = True


@dataclass(frozen=True)
class AlgorithmSpec:
    predicates: Tuple[PredicateSpec, ...]
    priorities: Tuple[PrioritySpec, ...]

    def is_default(self) -> bool:
        """Exactly the DefaultProvider set (order-insensitive:
        predicates AND together, priorities sum). Any argumented
        priority (ServiceAntiAffinity/LabelPreference) is non-default
        even alongside the stock three — _weight_map skips them, so
        check for them explicitly or they'd be silently dropped."""
        if any(
            p.kind in ("ServiceAntiAffinity", "LabelPreference") and p.weight
            for p in self.priorities
        ):
            return False
        return (
            {(p.kind, p.labels, p.presence) for p in self.predicates}
            == {(k, (), True) for k in BASE_PREDICATES}
            and _weight_map(self.priorities)
            == {
                "LeastRequestedPriority": 1,
                "BalancedResourceAllocation": 1,
                "ServiceSpreadingPriority": 1,
            }
        )

    @property
    def affinity_labels(self) -> Tuple[str, ...]:
        """Concatenated ServiceAffinity labels across all instances.
        Per-label decomposition is exact: each label's requirement
        (pinned nodeSelector value, else the anchor peer node's value)
        is independent, and predicates AND together."""
        out = []
        for p in self.predicates:
            if p.kind == "ServiceAffinity":
                out.extend(p.labels)
        return tuple(out)


def _weight_map(priorities: Tuple[PrioritySpec, ...]) -> Dict[str, int]:
    """kind -> summed weight, dropping zero-weight and EqualPriority
    (a constant shift never changes an argmax; the reference registers
    it but excludes it from the default provider, defaults.go:64-66)."""
    out: Dict[str, int] = {}
    for p in priorities:
        if p.kind == "EqualPriority" or p.weight == 0:
            continue
        if p.kind in ("LabelPreference", "ServiceAntiAffinity"):
            continue  # argumented kinds are not mergeable by kind
        out[p.kind] = out.get(p.kind, 0) + p.weight
    return out


DEFAULT_SPEC = AlgorithmSpec(
    predicates=tuple(PredicateSpec(k) for k in BASE_PREDICATES),
    priorities=(
        PrioritySpec("LeastRequestedPriority", 1),
        PrioritySpec("BalancedResourceAllocation", 1),
        PrioritySpec("ServiceSpreadingPriority", 1),
    ),
)


def spec_from_policy(policy: dict) -> AlgorithmSpec:
    """Policy document -> spec (plugin/pkg/scheduler/api/types.go).

    Argumented entries carry arbitrary display names; the argument
    decides the semantic kind. Plain entries must be base kinds or
    user-registered names (which lower_spec will reject, routing the
    daemon to the scalar path)."""
    predicates = []
    for p in policy.get("predicates", []):
        arg = p.get("argument") or {}
        if "serviceAffinity" in arg:
            predicates.append(
                PredicateSpec(
                    "ServiceAffinity",
                    labels=tuple(arg["serviceAffinity"].get("labels", [])),
                )
            )
        elif "labelsPresence" in arg:
            predicates.append(
                PredicateSpec(
                    "NodeLabelPresence",
                    labels=tuple(arg["labelsPresence"].get("labels", [])),
                    presence=arg["labelsPresence"].get("presence", True),
                )
            )
        else:
            predicates.append(PredicateSpec(p["name"]))
    priorities = []
    for p in policy.get("priorities", []):
        weight = p.get("weight", 1)
        arg = p.get("argument") or {}
        if "serviceAntiAffinity" in arg:
            priorities.append(
                PrioritySpec(
                    "ServiceAntiAffinity",
                    weight=weight,
                    label=arg["serviceAntiAffinity"].get("label", ""),
                )
            )
        elif "labelPreference" in arg:
            priorities.append(
                PrioritySpec(
                    "LabelPreference",
                    weight=weight,
                    label=arg["labelPreference"].get("label", ""),
                    presence=arg["labelPreference"].get("presence", True),
                )
            )
        else:
            priorities.append(PrioritySpec(p["name"], weight=weight))
    return AlgorithmSpec(tuple(predicates), tuple(priorities))


def spec_from_keys(
    predicate_keys, priority_keys: Dict[str, int]
) -> AlgorithmSpec:
    """Provider key sets -> spec (factory.CreateFromKeys shape)."""
    return AlgorithmSpec(
        tuple(PredicateSpec(k) for k in predicate_keys),
        tuple(PrioritySpec(k, weight=w) for k, w in priority_keys.items()),
    )


# ---------------------------------------------------------------------------
# Lowering
# ---------------------------------------------------------------------------


class LoweredSpec(NamedTuple):
    """Static (hashable) solver configuration — a jit static argument,
    so each distinct configured pipeline compiles once. Shapes of the
    per-spec columns ride on the arrays themselves except the
    anti-affinity zone-count lengths (aa_zones), which size a scatter
    target and must be static."""

    resources: bool = True
    ports: bool = True
    disk: bool = True
    selector: bool = True
    hostname: bool = True
    node_label: bool = False  # nodes["policy_ok"] static mask present
    service_affinity: bool = False  # aff columns + anchor/svc_total carry
    static_prio: bool = False  # nodes["static_prio"] column present
    aa_weights: Tuple[int, ...] = ()  # one ServiceAntiAffinity per entry
    aa_zones: Tuple[int, ...] = ()  # zone-vocab size per instance


DEFAULT_LOWERED = LoweredSpec()


def lower_spec(spec: AlgorithmSpec) -> Tuple[LoweredSpec, Tuple[int, int, int]]:
    """Validate + lower a spec to (LoweredSpec, priority weights).

    aa_zones is left empty here — zone vocabularies are snapshot-scoped
    (observed node label values), so SnapshotBuilder fills them in.
    Raises UnloweredPolicyError for kinds with no columnar encoding.
    """
    base = set(BASE_PREDICATES)
    ls = dict(
        resources=False, ports=False, disk=False, selector=False, hostname=False
    )
    flag_for = {
        "PodFitsPorts": "ports",
        "PodFitsResources": "resources",
        "NoDiskConflict": "disk",
        "MatchNodeSelector": "selector",
        "HostName": "hostname",
    }
    node_label = False
    service_affinity = False
    for p in spec.predicates:
        if p.kind in base:
            ls[flag_for[p.kind]] = True
        elif p.kind == "NodeLabelPresence":
            node_label = True
        elif p.kind == "ServiceAffinity":
            # Label-less ServiceAffinity is a no-op in the scalar path
            # (empty affinity selector matches everything); don't make
            # the solver expect columns that won't be built.
            if p.labels:
                service_affinity = True
        else:
            raise UnloweredPolicyError(f"predicate kind {p.kind!r}")
    weights = _weight_map(spec.priorities)
    static_prio = False
    aa_weights = []
    for p in spec.priorities:
        if p.kind in BASE_PRIORITIES or p.weight == 0:
            continue
        if p.kind == "LabelPreference":
            static_prio = True
        elif p.kind == "ServiceAntiAffinity":
            aa_weights.append(p.weight)
        else:
            raise UnloweredPolicyError(f"priority kind {p.kind!r}")
    lowered = LoweredSpec(
        resources=ls["resources"],
        ports=ls["ports"],
        disk=ls["disk"],
        selector=ls["selector"],
        hostname=ls["hostname"],
        node_label=node_label,
        service_affinity=service_affinity,
        static_prio=static_prio,
        aa_weights=tuple(aa_weights),
        aa_zones=(),
    )
    return lowered, (
        weights.get("LeastRequestedPriority", 0),
        weights.get("BalancedResourceAllocation", 0),
        weights.get("ServiceSpreadingPriority", 0),
    )
