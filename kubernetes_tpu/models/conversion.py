"""Multi-version API: wire-level conversion between v1 and v1beta3.

Reference: pkg/api/latest/latest.go:32-78 (version negotiation;
OldestVersion = v1beta3) and pkg/api/v1beta3/conversion.go — the
semantic (non-mechanical) differences between the two wire forms:

- PodSpec:      v1beta3 "host"      <-> v1 "nodeName"
                (conversion.go convert_v1beta3_PodSpec_To_api_PodSpec:
                 out.NodeName = in.Host)
- ServiceSpec:  v1beta3 "portalIP"  <-> v1 "clusterIP"
                v1beta3 "createExternalLoadBalancer" <-> v1 type ==
                "LoadBalancer" (conversion.go:358-447)
                v1beta3 "publicIPs" <-> v1 "externalIPs"
- Container:    v1beta3 carries legacy top-level "capabilities" /
                "privileged" compat fields that duplicate
                securityContext (conversion.go:226-256): decoding folds
                them into securityContext (securityContext wins on
                conflict); encoding to v1beta3 emits only
                securityContext, like the reference (conversion.go:
                267-350 writes no legacy fields).
- Status:       v1beta3 details "id" <-> v1 details "name"
                (conversion.go:669-707)

TPU-first design note: the reference generates 226 struct-to-struct
conversion functions per version (pkg/api/v1/conversion_generated.go).
Our internal model IS the v1 wire shape (models/serde.py), so
conversion happens once, at the HTTP boundary, as dict rewriting —
no generated code, no parallel type hierarchy. Everything the
converters don't name passes through untouched (mechanical fields are
identical between the two versions).
"""

from __future__ import annotations

import copy
from typing import Dict

VERSIONS = ("v1", "v1beta3")
PREFERRED = "v1"
OLDEST = "v1beta3"


def _convert_pod_spec_to_v1(spec: dict) -> None:
    if "host" in spec:
        spec.setdefault("nodeName", spec.pop("host"))
    for c in spec.get("containers") or []:
        if not isinstance(c, dict):
            continue
        caps = c.pop("capabilities", None)
        priv = c.pop("privileged", None)
        if caps is not None or priv:
            sc = c.setdefault("securityContext", {})
            if caps is not None:
                sc.setdefault("capabilities", caps)
            if priv:
                sc.setdefault("privileged", priv)


def _convert_pod_spec_to_v1beta3(spec: dict) -> None:
    if "nodeName" in spec:
        spec.setdefault("host", spec.pop("nodeName"))


def _convert_service_spec_to_v1(spec: dict) -> None:
    if "portalIP" in spec:
        spec.setdefault("clusterIP", spec.pop("portalIP"))
    if "publicIPs" in spec:
        spec.setdefault("externalIPs", spec.pop("publicIPs"))
    if "type" not in spec:
        # The bool selects LoadBalancer only when type is ABSENT —
        # when both are present, type wins and the bool is ignored,
        # exactly like the reference (conversion.go:381-388:
        # `typeIn := in.Type; if typeIn == "" { ...bool... }`). Yes,
        # that means a v1beta3 client flipping only the bool on an
        # object that carries type is ignored; reference parity over
        # intuition here.
        if spec.pop("createExternalLoadBalancer", False):
            spec["type"] = "LoadBalancer"
    else:
        spec.pop("createExternalLoadBalancer", None)


def _convert_service_spec_to_v1beta3(spec: dict) -> None:
    if "clusterIP" in spec:
        spec.setdefault("portalIP", spec.pop("clusterIP"))
    if "externalIPs" in spec:
        spec.setdefault("publicIPs", spec.pop("externalIPs"))
    if spec.get("type") == "LoadBalancer":
        spec["createExternalLoadBalancer"] = True


def _walk(wire: dict, to_v1: bool, version: str) -> None:
    """Apply kind-specific conversions in place (recursing into lists
    and pod templates)."""
    kind = wire.get("kind", "")
    if kind.endswith("List"):
        for item in wire.get("items", []):
            if isinstance(item, dict):
                _walk(item, to_v1, version)
                # Items self-describe their version; converted fields
                # must carry the matching apiVersion.
                if "apiVersion" in item:
                    item["apiVersion"] = "v1" if to_v1 else version
        return
    if kind == "Pod":
        spec = wire.get("spec")
        if isinstance(spec, dict):
            (_convert_pod_spec_to_v1 if to_v1 else _convert_pod_spec_to_v1beta3)(spec)
    elif kind == "Service":
        spec = wire.get("spec")
        if isinstance(spec, dict):
            (
                _convert_service_spec_to_v1
                if to_v1
                else _convert_service_spec_to_v1beta3
            )(spec)
    elif kind == "Status":
        details = wire.get("details")
        if isinstance(details, dict):
            if to_v1 and "id" in details:
                details.setdefault("name", details.pop("id"))
            elif not to_v1 and "name" in details:
                details.setdefault("id", details.pop("name"))
    elif kind in ("ReplicationController", "PodTemplate"):
        spec = wire.get("spec", {})
        template = (
            spec.get("template") if kind == "ReplicationController" else wire.get("template")
        )
        if isinstance(template, dict) and isinstance(template.get("spec"), dict):
            (
                _convert_pod_spec_to_v1
                if to_v1
                else _convert_pod_spec_to_v1beta3
            )(template["spec"])
    # Bindings arrive as {"target": {...}} in both versions — no-op.


def to_internal(wire: dict, version: str) -> dict:
    """Decode any supported wire version into the internal (v1) form."""
    if version == "v1" or not isinstance(wire, dict):
        return wire
    if version not in VERSIONS:
        raise ValueError(f"unknown API version {version!r}")
    out = copy.deepcopy(wire)
    _walk(out, to_v1=True, version=version)
    if out.get("apiVersion") == version:
        out["apiVersion"] = "v1"
    return out


def from_internal(wire: dict, version: str) -> dict:
    """Encode the internal (v1) form into the requested wire version."""
    if version == "v1" or not isinstance(wire, dict):
        return wire
    if version not in VERSIONS:
        raise ValueError(f"unknown API version {version!r}")
    out = copy.deepcopy(wire)
    _walk(out, to_v1=False, version=version)
    if out.get("apiVersion") == "v1":
        out["apiVersion"] = version
    return out
