"""WAL-shipping replication for the kvstore — the raft-lite HA plane.

The reference delegates layer-0 durability AND availability to etcd;
we own the WAL, so replication is an append stream plus a commit
index. The protocol, end to end:

- The **leader** is an ordinary ``KVStore`` with a ``ReplicationHub``
  attached through ``add_wal_tap``: every journaled mutation hands the
  hub its exact WAL line (newline-terminated bytes), under the store
  lock, in version order. The hub only buffers there; shipping happens
  on one thread per follower.
- Each **follower** is a ``KVStore`` in replica mode wrapped in a
  ``FollowerReplica``. Shipped lines are journaled verbatim into the
  follower's own WAL (durable before the ack — that journaled version
  is what quorum counts) and applied to the live mirror only up to the
  leader's **commit index**, so the follower's watch cache serves
  exactly the committed prefix and never a torn or unacked record.
- The **commit index** is the highest version durable on a majority of
  the cluster (leader + followers). Leader write acks gate on it via
  ``KVStore.set_commit_gate`` — fsync-before-ack extended to
  quorum-before-ack — and ``ReplicationHub.wait_committed``
  additionally waits until enough followers have *learned* the index,
  so a write acked to a client survives any single-process death and a
  promoted follower exposes it.
- **Failover**: ``FollowerReplica.promote()`` truncates the
  uncommitted journaled tail out of the WAL (PR 15's torn-line
  recovery oracle, extended to replication) and flips the store
  writable. A new ``ReplicationHub`` can then be attached to the
  promoted store to re-form the cluster.

Links come in two transports: ``LocalLink`` (in-process, the soak/
bench/test harness) and ``HTTPLink`` (POSTs to a follower apiserver's
``/replication/append``, riding the same HTTP plane as every other
verb). Both are driven by the hub's per-follower shipper threads, so a
slow follower lags alone instead of convoying the others.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from kubernetes_tpu.store.kvstore import KVStore, StoreError
from kubernetes_tpu.utils import metrics, sanitizer

COMMIT_INDEX = metrics.DEFAULT.gauge(
    "replication_commit_index",
    "Highest store version durable on a quorum of replicas",
    labels=("role",),
)
FOLLOWER_LAG = metrics.DEFAULT.gauge(
    "replication_follower_lag_versions",
    "Versions the follower's durable log trails the leader by",
    labels=("follower",),
)


class ReplicationError(StoreError):
    """Replication-plane failure (quorum timeout, stale-leader append,
    dead link)."""


class LocalLink:
    """In-process link to a FollowerReplica (tests, soak, bench)."""

    def __init__(self, replica: "FollowerReplica", name: str = "follower"):
        self.name = name
        self._replica = replica

    def append(self, lines: List[str], commit: int) -> int:
        return self._replica.append(lines, commit)

    def commit(self, commit: int) -> int:
        return self._replica.append([], commit)

    def status(self) -> dict:
        return self._replica.status()


class HTTPLink:
    """Link to a follower apiserver over the existing HTTP plane.

    POSTs {"lines": [...], "commit": N} to /replication/append on the
    follower's base URL; the follower answers {"journaled": N}. Uses a
    dedicated keep-alive connection (NOT the client transport's pool:
    replication must keep flowing while user traffic rotates away from
    a sick endpoint)."""

    def __init__(self, base_url: str, name: Optional[str] = None,
                 timeout: float = 10.0):
        from urllib.parse import urlparse

        u = urlparse(base_url)
        self.host, self.port = u.hostname, u.port or 80
        self.name = name or f"{self.host}:{self.port}"
        self.timeout = timeout
        self._conn = None

    def _request(self, body: dict) -> dict:
        import http.client

        payload = json.dumps(body)
        for attempt in (0, 1):  # one free replay for a stale keep-alive
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout
                )
            try:
                self._conn.request(
                    "POST", "/replication/append", body=payload,
                    headers={"Content-Type": "application/json"},
                )
                resp = self._conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    raise ReplicationError(
                        f"follower {self.name}: HTTP {resp.status} "
                        f"{data[:200]!r}"
                    )
                return json.loads(data)
            except (OSError, http.client.HTTPException):
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
                if attempt:
                    raise

    def append(self, lines: List[str], commit: int) -> int:
        return int(self._request({"lines": lines, "commit": commit})[
            "journaled"
        ])

    def commit(self, commit: int) -> int:
        return self.append([], commit)

    def status(self) -> dict:
        return self._request({"lines": [], "commit": -1})


class _Follower:
    """Hub-side state for one link (all fields guarded by the hub CV)."""

    def __init__(self, link, start: int):
        self.link = link
        self.next = start  # buffer offset of the next line to ship
        self.acked = 0  # highest version durable in the follower's log
        self.commit_known = 0  # highest commit index delivered to it
        self.alive = True
        self.thread: Optional[threading.Thread] = None


class ReplicationHub:
    """Leader-side shipping plane over one KVStore.

    attach() taps the store's WAL and (by default) gates its write
    acks on the quorum commit index. Followers are added with
    add_follower(link, bootstrap=...); each gets a shipper thread that
    streams new lines + the current commit index, retrying dead links
    with bounded backoff. stop() detaches the gate and retires the
    shippers (a crashed leader never stops cleanly — that path is the
    follower's promote())."""

    def __init__(self, store: KVStore, ack_timeout_s: float = 5.0,
                 name: str = "leader"):
        self.name = name
        self.store = store
        self.ack_timeout_s = ack_timeout_s
        self._lock = sanitizer.lock("replication.hub")
        self._cv = threading.Condition(self._lock)
        self._buf: deque = deque()  # raw lines, in version order
        self._base = 0  # buffer offset of _buf[0]
        self._last_version = 0  # highest version tapped (or bootstrapped)
        self._commit = 0
        self._followers: List[_Follower] = []
        self._stopped = False
        self._attached = False

    # -- wiring -------------------------------------------------------

    def attach(self, gate_writes: bool = True) -> "ReplicationHub":
        """Tap the store's WAL; optionally gate its acks on quorum."""
        with self._cv:
            if self._attached:
                return self
            self._attached = True
            self._last_version = self.store.version
            self._commit = self._last_version
        self.store.add_wal_tap(self._tap)
        if gate_writes:
            self.store.set_commit_gate(self._gate)
        COMMIT_INDEX.set(self._commit, role="leader")
        return self

    def _tap(self, version: int, data: str) -> None:
        # Runs UNDER the store lock — buffer + wake shippers, nothing
        # else. The hub CV nests inside the store lock here and is
        # never held while calling into the store, so the order is DAG.
        with self._cv:
            self._buf.append(data)
            self._last_version = version
            # Single-node cluster (no followers yet): local fsync IS
            # quorum — advance the commit index here or the gate would
            # park forever waiting on nobody.
            self._recompute_commit_locked()
            self._trim_locked()
            self._cv.notify_all()

    def add_follower(self, link, bootstrap: bool = True) -> None:
        """Register a follower link. bootstrap=True ships a full
        dump_state() first (late joiners — the WAL tap only carries
        lines since attach), through the link's replica if local or a
        /replication/bootstrap POST for HTTP links."""
        if bootstrap:
            state = self.store.dump_state()
            if isinstance(link, LocalLink):
                link._replica.bootstrap(state)
            else:
                link._request({"bootstrap": state})  # type: ignore[attr-defined]
        with self._cv:
            f = _Follower(link, start=self._base + len(self._buf))
            f.acked = self.store.version if bootstrap else 0
            self._followers.append(f)
            self._recompute_commit_locked()
            f.thread = threading.Thread(
                target=self._ship_loop, args=(f,), daemon=True,
                name=f"repl-ship-{link.name}",
            )
            f.thread.start()

    def stop(self) -> None:
        self.store.set_commit_gate(None)
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    # -- commit plumbing ----------------------------------------------

    def _majority(self) -> int:
        return (len(self._followers) + 1) // 2 + 1

    def _recompute_commit_locked(self) -> bool:
        """Commit index = highest version durable on a majority (the
        leader's own fsync-before-ack covers its vote)."""
        need = self._majority() - 1  # follower votes beyond the leader
        if need <= 0:
            commit = self._last_version
        else:
            acks = sorted((f.acked for f in self._followers), reverse=True)
            commit = acks[need - 1] if len(acks) >= need else 0
        commit = min(commit, self._last_version)
        if commit > self._commit:
            self._commit = commit
            COMMIT_INDEX.set(commit, role="leader")
            self._cv.notify_all()
            return True
        return False

    @property
    def commit_index(self) -> int:
        with self._cv:
            return self._commit

    def wait_committed(self, version: int,
                       timeout: Optional[float] = None) -> int:
        """Block until `version` is quorum-durable AND enough followers
        have learned a commit index covering it — the full before-ack
        barrier (a follower promoted the instant this returns must
        expose the write). Raises ReplicationError on timeout: the
        write is journaled locally but NOT acked, exactly a raft
        leader losing its quorum."""
        deadline = time.monotonic() + (
            self.ack_timeout_s if timeout is None else timeout
        )
        need = None
        with self._cv:
            while True:
                need = self._majority() - 1
                known = sum(
                    1 for f in self._followers if f.commit_known >= version
                )
                if self._commit >= version and known >= need:
                    return self._commit
                left = deadline - time.monotonic()
                if left <= 0 or self._stopped:
                    raise ReplicationError(
                        f"write v{version} not committed within "
                        f"{self.ack_timeout_s}s (commit={self._commit}, "
                        f"followers knowing={known}/{need})"
                    )
                self._cv.wait(timeout=min(left, 0.5))

    def _gate(self) -> None:
        # store.version is >= the acking write's version; waiting for
        # it over-waits by at most the in-flight concurrent writes —
        # the raft-lite simplification that keeps the store's write
        # paths version-agnostic.
        self.wait_committed(self.store.version)

    # -- shipping -----------------------------------------------------

    def _ship_loop(self, f: _Follower) -> None:
        backoff = 0.05
        while True:
            with self._cv:
                while (
                    not self._stopped
                    and f.next >= self._base + len(self._buf)
                    and f.commit_known >= self._commit
                ):
                    self._cv.wait(timeout=0.5)
                if self._stopped:
                    return
                lines = list(
                    itertools.islice(
                        self._buf, max(0, f.next - self._base), None
                    )
                )
                sent_upto = self._base + len(self._buf)
                commit = self._commit
            try:
                acked = f.link.append(lines, commit)
            except Exception:
                f.alive = False
                time.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
                continue
            backoff = 0.05
            f.alive = True
            with self._cv:
                f.next = sent_upto
                f.commit_known = max(f.commit_known, commit)
                if acked > f.acked:
                    f.acked = acked
                FOLLOWER_LAG.set(
                    max(0, self._last_version - f.acked),
                    follower=f.link.name,
                )
                self._recompute_commit_locked()
                self._trim_locked()
                self._cv.notify_all()

    def _trim_locked(self) -> None:
        """Drop buffered lines every follower has been sent (late
        joiners bootstrap from dump_state, never from this buffer —
        with no followers the buffer stays empty)."""
        floor = min(
            (f.next for f in self._followers),
            default=self._base + len(self._buf),
        )
        while self._base < floor and self._buf:
            self._buf.popleft()
            self._base += 1

    # -- introspection ------------------------------------------------

    def status(self) -> dict:
        with self._cv:
            return {
                "role": "leader",
                "name": self.name,
                "version": self._last_version,
                "commitIndex": self._commit,
                "followers": [
                    {
                        "name": f.link.name,
                        "acked": f.acked,
                        "commitKnown": f.commit_known,
                        "lagVersions": max(0, self._last_version - f.acked),
                        "alive": f.alive,
                    }
                    for f in self._followers
                ],
            }


class FollowerReplica:
    """Follower-side ingest over one replica-mode KVStore."""

    def __init__(self, store: Optional[KVStore] = None,
                 name: str = "follower"):
        self.name = name
        self.store = store if store is not None else KVStore()
        self.store.set_replica_mode(True)
        # io_gate: append() fsyncs the follower WAL under this lock by
        # design — it serializes the (single-shipper) ingest order.
        self._lock = sanitizer.lock("replication.follower", io_gate=True)
        self._commit = 0
        self._promoted = False

    def bootstrap(self, state: dict) -> None:
        """Install a leader dump_state() snapshot (late join)."""
        with self._lock:
            self.store.load_state(state)
            self._commit = state["version"]
            COMMIT_INDEX.set(self._commit, role=f"follower:{self.name}")

    def append(self, lines: List[str], commit: int) -> int:
        """Journal shipped lines + apply the committed prefix; returns
        the journaled (quorum-countable) version. commit=-1 is a pure
        status probe."""
        with self._lock:
            if self._promoted:
                raise ReplicationError(
                    f"follower {self.name} was promoted; stale leader?"
                )
            if commit < 0:
                return self.store.journaled_version
            self._commit = max(self._commit, commit)
            journaled, _applied = self.store.replicate(lines, self._commit)
            COMMIT_INDEX.set(
                min(self._commit, journaled), role=f"follower:{self.name}"
            )
            return journaled

    @property
    def commit_index(self) -> int:
        with self._lock:
            return min(self._commit, self.store.journaled_version)

    def promote(self) -> KVStore:
        """Leader died: discard the uncommitted tail and hand back the
        store as a writable leader serving exactly the committed
        prefix."""
        with self._lock:
            self._promoted = True
            self.store.promote_replica()
            COMMIT_INDEX.set(self.store.version, role="leader")
            return self.store

    def status(self) -> dict:
        with self._lock:
            version = self.store.version
            journaled = self.store.journaled_version
            commit = (
                version if self._promoted else min(self._commit, journaled)
            )
            return {
                "role": "leader" if self._promoted else "follower",
                "name": self.name,
                "version": version,
                "journaled": journaled,
                "commitIndex": commit,
                "followers": [],
            }
