"""Versioned object store with CAS and watch streams.

Plays the role of etcd + the reference's EtcdHelper
(pkg/tools/etcd_helper.go): a single source of truth with a global
logical clock (resourceVersion), compare-and-swap updates, and
history-replayable watch streams. In-process by design — the control
plane is one process with many threads; durability is via snapshot
checkpoints (everything device-side is reconstructible, SURVEY.md §5).
"""

from kubernetes_tpu.store.kvstore import (
    AbortedError,
    CompactedError,
    ConflictError,
    KVStore,
    NotFoundError,
    AlreadyExistsError,
)
from kubernetes_tpu.store.watch import Event, ADDED, MODIFIED, DELETED, ERROR

__all__ = [
    "KVStore",
    "AbortedError",
    "ConflictError",
    "NotFoundError",
    "AlreadyExistsError",
    "CompactedError",
    "Event",
    "ADDED",
    "MODIFIED",
    "DELETED",
    "ERROR",
]
