"""Watch events and streams.

Behavioral parity with pkg/watch/ (Event{Added,Modified,Deleted,Error},
watch.Interface) and the etcd->watch translation in
pkg/tools/etcd_helper_watch.go. A WatchStream is a bounded queue the
store pushes into; consumers iterate or poll with timeouts.
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional

from kubernetes_tpu.utils import faults, metrics

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"

_LOG = logging.getLogger("kubernetes_tpu.store.watch")

#: Slow-consumer watch streams dropped (each forces the consumer to
#: re-list). This drop used to be SILENT — a bulk churn drill would
#: quietly lose its watch and report rates that excluded fan-out cost.
STREAMS_DROPPED = metrics.DEFAULT.counter(
    "watch_streams_dropped_total",
    "Watch streams dropped for falling behind (slow consumers)",
    ("resource",),
)

#: Sampled event-queue depth per resource — a rough backpressure gauge
#: (deep queues mean consumers are trailing the dispatcher and drops
#: are near). Updated every 64 queued events and at the drop site, NOT
#: per push: the fan-out path is the hot path PR 6 burst-coalesced,
#: and a healthy (shallow) queue is exactly the case that needs zero
#: added cost. One gauge per resource, not per stream: label
#: cardinality must not scale with watcher count.
QUEUE_DEPTH = metrics.DEFAULT.gauge(
    "watch_stream_queue_depth",
    "Sampled watch stream queue depth (every 64 queued events and at "
    "slow-consumer drops), by resource",
    ("resource",),
)


def resource_of_prefix(prefix: str) -> str:
    """The resource name inside a registry key prefix
    ('/registry/pods/default/' -> 'pods'); the prefix itself when the
    shape is foreign (metric label fallback)."""
    parts = prefix.strip("/").split("/")
    if len(parts) >= 2 and parts[0] == "registry":
        return parts[1]
    return prefix


@dataclass
class Event:
    type: str
    object: Any  # wire-form dict (or Status dict for ERROR)
    version: int = 0  # store logical clock at event time

    @property
    def key(self) -> str:
        meta = self.object.get("metadata", {}) if isinstance(self.object, dict) else {}
        ns = meta.get("namespace", "")
        return f"{ns}/{meta.get('name', '')}" if ns else meta.get("name", "")


class WatchStream:
    """One consumer's view of a watch. Closed by either side."""

    def __init__(self, maxsize: int = 4096, resource: str = ""):
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize=maxsize)
        self._closed = threading.Event()
        # Version floor: events at or below it are silently dropped.
        # The store sets it at registration time so the async dispatch
        # thread's backlog (events the registration-time replay already
        # covered) can never be double-delivered or re-ordered.
        self.floor = 0
        #: Resource this stream watches (metric label for the drop
        #: counter / depth gauge; "" for anonymous broadcast streams).
        self.resource = resource

    def push(self, ev: Event) -> bool:
        if self._closed.is_set():
            return False
        if ev.version and ev.version <= self.floor:
            return True  # already covered by replay — drop, stay open
        if faults.enabled() and self.resource and self.resource != "broadcast":
            # Chaos seams, store-fed streams only (anonymous broadcast
            # streams have no re-list recovery path to exercise): a
            # forced slow-consumer drop takes the exact branch below;
            # the delay site stalls delivery on the dispatcher thread.
            if faults.fire(faults.WATCH_DROP, self.resource):
                return self._drop_slow_consumer()
            faults.fire(faults.WATCH_DELAY, self.resource)
        try:
            self._q.put_nowait(ev)
            depth = self._q.qsize()
            if not depth & 63:  # sampled: zero cost while shallow
                QUEUE_DEPTH.set(depth, resource=self.resource)
            return True
        except queue.Full:
            return self._drop_slow_consumer()

    def _drop_slow_consumer(self) -> bool:
        # Slow consumer: drop the stream (reference watchers are also
        # terminated and must re-list; Reflector handles that) —
        # OBSERVABLY: the counter + warn log are what tell an
        # operator the churn figures just stopped including this
        # consumer's fan-out cost.
        STREAMS_DROPPED.inc(resource=self.resource)
        QUEUE_DEPTH.set(self._q.qsize(), resource=self.resource)
        _LOG.warning(
            "dropping slow watch consumer (resource=%r, version "
            "floor=%d, queue depth=%d/%d); it must re-list",
            self.resource, self.floor, self._q.qsize(),
            self._q.maxsize,
        )
        self.close()
        return False

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None on close/timeout."""
        if self._closed.is_set() and self._q.empty():
            return None
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._q.put_nowait(None)  # wake blocked consumers
            except queue.Full:
                pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self.next()
            if ev is None:
                return
            yield ev


class Broadcaster:
    """Fan-out of events to many streams (reference: pkg/watch/mux.go)."""

    def __init__(self):
        from kubernetes_tpu.utils import sanitizer

        self._lock = sanitizer.lock("watch.broadcaster")
        self._streams: List[WatchStream] = []

    def watch(self, maxsize: int = 4096) -> WatchStream:
        s = WatchStream(maxsize=maxsize, resource="broadcast")
        with self._lock:
            self._streams.append(s)
        return s

    def action(self, ev: Event) -> None:
        with self._lock:
            live = []
            for s in self._streams:
                if s.push(ev) or not s.closed:
                    if not s.closed:
                        live.append(s)
            self._streams = live

    def close(self) -> None:
        with self._lock:
            for s in self._streams:
                s.close()
            self._streams = []
