"""Watch events and streams.

Behavioral parity with pkg/watch/ (Event{Added,Modified,Deleted,Error},
watch.Interface) and the etcd->watch translation in
pkg/tools/etcd_helper_watch.go. A WatchStream is a bounded queue the
store pushes into; consumers iterate or poll with timeouts.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Any, Iterator, List, Optional

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"


@dataclass
class Event:
    type: str
    object: Any  # wire-form dict (or Status dict for ERROR)
    version: int = 0  # store logical clock at event time

    @property
    def key(self) -> str:
        meta = self.object.get("metadata", {}) if isinstance(self.object, dict) else {}
        ns = meta.get("namespace", "")
        return f"{ns}/{meta.get('name', '')}" if ns else meta.get("name", "")


class WatchStream:
    """One consumer's view of a watch. Closed by either side."""

    def __init__(self, maxsize: int = 4096):
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue(maxsize=maxsize)
        self._closed = threading.Event()
        # Version floor: events at or below it are silently dropped.
        # The store sets it at registration time so the async dispatch
        # thread's backlog (events the registration-time replay already
        # covered) can never be double-delivered or re-ordered.
        self.floor = 0

    def push(self, ev: Event) -> bool:
        if self._closed.is_set():
            return False
        if ev.version and ev.version <= self.floor:
            return True  # already covered by replay — drop, stay open
        try:
            self._q.put_nowait(ev)
            return True
        except queue.Full:
            # Slow consumer: drop the stream (reference watchers are also
            # terminated and must re-list; Reflector handles that).
            self.close()
            return False

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event, or None on close/timeout."""
        if self._closed.is_set() and self._q.empty():
            return None
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev

    def close(self) -> None:
        if not self._closed.is_set():
            self._closed.set()
            try:
                self._q.put_nowait(None)  # wake blocked consumers
            except queue.Full:
                pass

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self.next()
            if ev is None:
                return
            yield ev


class Broadcaster:
    """Fan-out of events to many streams (reference: pkg/watch/mux.go)."""

    def __init__(self):
        from kubernetes_tpu.utils import sanitizer

        self._lock = sanitizer.lock("watch.broadcaster")
        self._streams: List[WatchStream] = []

    def watch(self, maxsize: int = 4096) -> WatchStream:
        s = WatchStream(maxsize=maxsize)
        with self._lock:
            self._streams.append(s)
        return s

    def action(self, ev: Event) -> None:
        with self._lock:
            live = []
            for s in self._streams:
                if s.push(ev) or not s.closed:
                    if not s.closed:
                        live.append(s)
            self._streams = live

    def close(self) -> None:
        with self._lock:
            for s in self._streams:
                s.close()
            self._streams = []
