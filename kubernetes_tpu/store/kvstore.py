"""The versioned KV store.

Semantics mirror the reference's etcd usage through EtcdHelper
(pkg/tools/etcd_helper.go):

- A single global, monotonically increasing logical clock. Every write
  bumps it; every object carries the version of its last write in
  metadata.resourceVersion (pkg/tools/etcd_object.go).
- Create fails if the key exists (AlreadyExists); CompareAndSwap update
  fails on version mismatch (Conflict); `guaranteed_update` is the CAS
  retry loop of EtcdHelper.GuaranteedUpdate (etcd_helper.go:510-600).
- Watch(prefix, since) replays buffered history after `since`, then
  streams live events in version order (etcd_helper_watch.go:73-165).
  Asking for a version older than the history window raises
  CompactedError (clients must re-list, like etcd index cleared errors).
- Values are wire-form dicts (deep-copied on the way in and out), so
  storage is serialization-faithful like etcd's JSON payloads.
- Optional per-key TTL (events registry uses it, reference: event TTL).

Thread-safe; many reader/writer threads, one lock (control-plane rates
are tiny next to the TPU solver's work).
"""

from __future__ import annotations

import copy
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.store.watch import ADDED, DELETED, Event, MODIFIED, WatchStream


class StoreError(Exception):
    pass


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


class ConflictError(StoreError):
    pass


class CompactedError(StoreError):
    """Watch window no longer covers the requested version."""


class KVStore:
    def __init__(self, history_limit: int = 10000):
        self._lock = threading.RLock()
        self._data: Dict[str, Tuple[dict, int]] = {}  # key -> (wire obj, version)
        self._ttl: Dict[str, float] = {}  # key -> expiry monotonic time
        self._version = 0
        # History ring for watch replay: (version, type, key, obj).
        self._history: deque = deque(maxlen=history_limit)
        self._oldest = 0  # lowest version NOT compacted out of history
        self._watchers: List[Tuple[str, WatchStream]] = []  # (prefix, stream)

    # -- version plumbing ---------------------------------------------

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def _bump(self) -> int:
        self._version += 1
        return self._version

    @staticmethod
    def _stamp(obj: dict, version: int) -> dict:
        obj.setdefault("metadata", {})["resourceVersion"] = str(version)
        return obj

    def _expire_locked(self) -> None:
        if not self._ttl:
            return
        now = time.monotonic()
        expired = [k for k, t in self._ttl.items() if t <= now]
        for k in expired:
            del self._ttl[k]
            if k in self._data:
                obj, _ = self._data.pop(k)
                v = self._bump()
                self._record(v, DELETED, k, obj)

    def _record(self, version: int, etype: str, key: str, obj: dict) -> None:
        # History and watch consumers get their own copies: stored state
        # must never be reachable (hence mutable) through an event.
        obj = copy.deepcopy(obj)
        if not self._history:
            self._oldest = version
        self._history.append((version, etype, key, obj))
        if len(self._history) == self._history.maxlen:
            self._oldest = self._history[0][0]
        live = []
        for prefix, stream in self._watchers:
            if stream.closed:
                continue  # prune dead watchers as we go
            if key.startswith(prefix):
                stream.push(Event(etype, copy.deepcopy(obj), version))
            if not stream.closed:
                live.append((prefix, stream))
        self._watchers = live

    # -- CRUD ---------------------------------------------------------

    def create(self, key: str, obj: dict, ttl: Optional[float] = None) -> dict:
        with self._lock:
            self._expire_locked()
            if key in self._data:
                raise AlreadyExistsError(key)
            obj = copy.deepcopy(obj)
            v = self._bump()
            self._stamp(obj, v)
            self._data[key] = (obj, v)
            if ttl is not None:
                self._ttl[key] = time.monotonic() + ttl
            self._record(v, ADDED, key, obj)
            return copy.deepcopy(obj)

    def get(self, key: str) -> dict:
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            return copy.deepcopy(self._data[key][0])

    def set(
        self, key: str, obj: dict, expected_version: Optional[int] = None
    ) -> dict:
        """Update; CAS when expected_version is given (etcd CompareAndSwap)."""
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            _, cur_v = self._data[key]
            if expected_version is not None and cur_v != expected_version:
                raise ConflictError(
                    f"{key}: version {expected_version} != current {cur_v}"
                )
            obj = copy.deepcopy(obj)
            v = self._bump()
            self._stamp(obj, v)
            self._data[key] = (obj, v)
            self._record(v, MODIFIED, key, obj)
            return copy.deepcopy(obj)

    def delete(self, key: str, expected_version: Optional[int] = None) -> dict:
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            obj, cur_v = self._data[key]
            if expected_version is not None and cur_v != expected_version:
                raise ConflictError(
                    f"{key}: version {expected_version} != current {cur_v}"
                )
            del self._data[key]
            self._ttl.pop(key, None)
            v = self._bump()
            self._record(v, DELETED, key, obj)
            return copy.deepcopy(obj)

    def list(self, prefix: str) -> Tuple[List[dict], int]:
        """All objects under prefix + the store version (for watch resume)."""
        with self._lock:
            self._expire_locked()
            out = [
                copy.deepcopy(obj)
                for key, (obj, _) in sorted(self._data.items())
                if key.startswith(prefix)
            ]
            return out, self._version

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            self._expire_locked()
            return sorted(k for k in self._data if k.startswith(prefix))

    # -- GuaranteedUpdate (etcd_helper.go:510-600) ---------------------

    def guaranteed_update(
        self, key: str, update_fn: Callable[[dict], dict], max_retries: int = 16
    ) -> dict:
        """Read-modify-write with CAS retry. update_fn gets a private copy
        and returns the new object (or raises to abort)."""
        for _ in range(max_retries):
            with self._lock:
                self._expire_locked()
                if key not in self._data:
                    raise NotFoundError(key)
                cur, cur_v = self._data[key]
                cur = copy.deepcopy(cur)
            new = update_fn(cur)
            try:
                return self.set(key, new, expected_version=cur_v)
            except ConflictError:
                continue
        raise ConflictError(f"{key}: too many CAS retries")

    # -- Watch --------------------------------------------------------

    def watch(self, prefix: str, since: int = 0, maxsize: int = 4096) -> WatchStream:
        """Stream events for keys under prefix with version > since.

        since=0 means "from now". History older than the replay buffer
        raises CompactedError — caller must re-list (Reflector does).
        """
        with self._lock:
            self._expire_locked()
            if since and self._history and since + 1 < self._oldest:
                raise CompactedError(
                    f"version {since} compacted (oldest {self._oldest})"
                )
            stream = WatchStream(maxsize=maxsize)
            self._watchers = [(p, s) for p, s in self._watchers if not s.closed]
            self._watchers.append((prefix, stream))
            if since:
                for v, etype, key, obj in self._history:
                    if v > since and key.startswith(prefix):
                        stream.push(Event(etype, copy.deepcopy(obj), v))
            return stream

    def stop_watch(self, stream: WatchStream) -> None:
        stream.close()
        with self._lock:
            self._watchers = [(p, s) for p, s in self._watchers if not s.closed]

    def close(self) -> None:
        with self._lock:
            for _, s in self._watchers:
                s.close()
            self._watchers = []
