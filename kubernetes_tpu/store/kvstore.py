"""The versioned KV store.

Semantics mirror the reference's etcd usage through EtcdHelper
(pkg/tools/etcd_helper.go):

- A single global, monotonically increasing logical clock. Every write
  bumps it; every object carries the version of its last write in
  metadata.resourceVersion (pkg/tools/etcd_object.go).
- Create fails if the key exists (AlreadyExists); CompareAndSwap update
  fails on version mismatch (Conflict); `guaranteed_update` is the CAS
  retry loop of EtcdHelper.GuaranteedUpdate (etcd_helper.go:510-600).
- Watch(prefix, since) replays buffered history after `since`, then
  streams live events in version order (etcd_helper_watch.go:73-165).
  Asking for a version older than the history window raises
  CompactedError (clients must re-list, like etcd index cleared errors).
- Values are wire-form dicts (deep-copied on the way in and out), so
  storage is serialization-faithful like etcd's JSON payloads.
- Optional per-key TTL (events registry uses it, reference: event TTL).
- Optional durability (`data_dir=`): every mutation is appended to a
  JSON-lines write-ahead log and the full state is periodically
  snapshotted; construction replays snapshot + WAL so an apiserver
  restarted on the same --data-dir recovers every object, binding and
  allocator lease with the resourceVersion clock intact. This is the
  role etcd plays for the reference (pkg/tools/etcd_helper.go:101,
  hack/local-up-cluster.sh:152-153): master state must survive process
  death. TTLs are wall-clock deadlines so they age across restarts.
  fsync-before-ack is the DEFAULT (etcd's contract: acked writes
  survive power loss, not just process death), group-committed so N
  concurrent writers share a disk flush; fsync=False (daemon flag
  --no-data-fsync) trades that for write latency.

Thread-safe; many reader/writer threads over one lock with short holds
(TTL expiry via a heap, watch fan-out off-thread behind a sharded
watcher index, bulk applies for the scheduler's commit path). For
thread-herd hosts (1000 in-process kubelets) `serialized_writes=True`
funnels mutations through one hot applier thread instead — etcd's
single raft-apply loop, in spirit.
"""

from __future__ import annotations

import copy
import fcntl
import json
import heapq
import math
import os
import queue as _queue
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.store.watch import ADDED, DELETED, Event, MODIFIED, WatchStream
from kubernetes_tpu.utils import faults, sanitizer


class StoreError(Exception):
    pass


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


class ConflictError(StoreError):
    pass


class CompactedError(StoreError):
    """Watch window no longer covers the requested version."""


class AbortedError(StoreError):
    """An atomic batch aborted before this item was applied (some other
    item in the batch failed); nothing in the batch was committed."""


class StoreClosedError(StoreError):
    """The store was closed while this write was queued/in flight; the
    write was NOT applied (serialized-writer shutdown path)."""


def _copy_obj(obj: dict) -> dict:
    """Private copy of a wire-form object. Wire objects are JSON by
    construction (they ride the WAL and the HTTP API as JSON), and a
    C-accelerated json round-trip is ~2x faster than copy.deepcopy on
    pod-sized dicts; anything non-JSON (test doubles) falls back.

    Contract caveat: json.dumps coerces rather than rejects two
    non-wire shapes — int dict keys become strings and tuples become
    lists — so the fallback won't fire for them. That's the store's
    documented JSON-object contract (same coercion the WAL and the
    HTTP tier already apply); don't put non-wire values in the store."""
    try:
        return json.loads(json.dumps(obj))
    except (TypeError, ValueError):
        return copy.deepcopy(obj)


def _dispatch_thread(store_ref: "weakref.ref", q: "_queue.SimpleQueue") -> None:
    """Drains a store's dispatch queue until a None sentinel (close) or
    the store itself is collected."""
    while True:
        item = q.get()
        if item is None:
            return
        store = store_ref()
        if store is None:
            return
        store._dispatch_event(item)
        del store  # don't pin the store across the blocking get()


def _filter_event(
    pred: Optional[Callable], etype: str, obj: dict, prev: Optional[dict], version: int
) -> Optional[Event]:
    """etcd's filtered-watch translation (pkg/tools/etcd_helper_watch.go
    sendModify/sendDelete): a selector-filtered watcher sees ADDED/
    MODIFIED only while the object matches, a synthesized DELETED when a
    modification takes it out of the filter (so a spec.nodeName=""
    watcher sees pods leave its view when the scheduler binds them), and
    nothing at all for objects that never concerned it. With no previous
    state to consult (history replay), a non-matching MODIFIED degrades
    to a spurious DELETED — a harmless no-op for consumers."""
    if pred is None:
        return Event(etype, obj, version)
    if etype == ADDED:
        return Event(ADDED, obj, version) if pred(obj) else None
    if etype == MODIFIED:
        if pred(obj):
            return Event(MODIFIED, obj, version)
        if prev is None or pred(prev):
            return Event(DELETED, obj, version)
        return None
    # DELETED: obj is the last stored state — deliver iff it was visible.
    return Event(DELETED, obj, version) if pred(obj) else None


def _drain_write_queue(q, batch=()) -> None:
    """Shutdown path: fail every not-yet-applied queued entry so no
    writer thread is stranded in ev.wait() forever (the None sentinel
    used to retire the applier mid-batch, silently dropping already-
    dequeued entries)."""
    err = StoreClosedError("store closed before this write was applied")
    pending = list(batch)
    while True:
        try:
            pending.append(q.get_nowait())
        except _queue.Empty:
            break
    for entry in pending:
        if entry is None:
            continue
        _fn, ev, cell = entry
        cell.append((False, err))
        ev.set()


def _write_thread(store_ref, q) -> None:
    """Serialized write-combining loop (etcd's single raft-apply
    thread, in spirit): drains queued mutations and executes them with
    ONE thread. Under a thread herd (1000 kubelets' status writers on
    one core), per-caller lock acquisition makes every write pay a
    full wake+GIL-handoff latency and system write throughput
    collapses to ~1/wake-latency; with a single applier the writes
    themselves proceed at full speed and only each caller's own
    wake-up is laggy.

    Group commit rides the batch: after applying a drained batch the
    thread fsyncs the WAL ONCE (advancing _synced_seq past every record
    the batch appended) and only then wakes the callers — their own
    _wal_sync finds the work already done, so N queued writers pay one
    disk flush instead of racing N.

    Shutdown: on the None sentinel every already-dequeued and still-
    queued entry is failed with StoreClosedError (events always set) —
    a write racing close() must error out, never hang."""
    spin_s = 0.004  # stay runnable briefly between batches (see below)
    while True:
        item = q.get()
        if item is None:
            _drain_write_queue(q)
            return
        while True:
            batch = [item]
            while len(batch) < 256:
                try:
                    batch.append(q.get_nowait())
                except _queue.Empty:
                    break
            store = store_ref()
            if store is None:
                return
            if None in batch:
                # Sentinel mid-batch: fail the whole drained batch and
                # everything still queued, then retire.
                _drain_write_queue(q, batch)
                return
            done = []
            for entry in batch:
                fn, ev, cell = entry
                try:
                    cell.append((True, fn()))
                except BaseException as e:
                    cell.append((False, e))
                done.append(ev)
            # One fsync covers the whole drained batch before any
            # caller is woken (their _wal_sync then no-ops). Failures
            # fall through: each caller's own _wal_sync retries and
            # surfaces the real error.
            try:
                store._sync_batch_locked_free()
            except Exception:
                pass
            for ev in done:
                ev.set()
            del store
            # Spin-drain: a blocking get() puts this thread to SLEEP,
            # and under a runnable herd each wake-up costs many GIL
            # quanta — the writer's throughput became 1/wake-latency
            # (~75 ops/s observed) no matter how fast the writes were.
            # Yielding but staying runnable keeps the pump hot while
            # load continues; after a quiet spell it blocks for real.
            deadline = time.monotonic() + spin_s
            item = None
            while item is None and time.monotonic() < deadline:
                try:
                    nxt = q.get_nowait()
                except _queue.Empty:
                    time.sleep(0)  # yield the GIL, stay runnable
                    continue
                if nxt is None:
                    # Shutdown sentinel (close/GC finalizer): fail any
                    # entries that raced in behind it.
                    _drain_write_queue(q)
                    return
                item = nxt
            if item is None:
                break  # idle: go back to the blocking get


class KVStore:
    def __init__(
        self,
        history_limit: int = 10000,
        data_dir: Optional[str] = None,
        fsync: bool = True,
        snapshot_every: int = 4096,
        serialized_writes: bool = False,
    ):
        self._lock = sanitizer.rlock("kvstore.lock")
        self._data: Dict[str, Tuple[dict, int]] = {}  # key -> (wire obj, version)
        self._ttl: Dict[str, float] = {}  # key -> expiry wall-clock time
        self._version = 0
        # History ring for watch replay: (version, type, key, obj).
        self._history: deque = deque(maxlen=history_limit)
        self._oldest = 0  # lowest version NOT compacted out of history
        # (prefix, pred-or-None, stream). Selector predicates live HERE,
        # not above the store: a filtered watcher (kubelet watching
        # spec.nodeName=X) must not even be offered the other 99 nodes'
        # events — at 100 kubelets that fan-out was the control plane's
        # wall, not the solver.
        # watcher tuple: (prefix, pred, stream, shard) where shard is
        # None or (extract_fn, value) — see _dispatch_event.
        self._watchers: List[tuple] = []
        self._unsharded: List[tuple] = []
        self._shard_buckets: Dict[tuple, List[tuple]] = {}
        self._shard_fns: tuple = ()
        # Event subscribers (the apiserver's watch cache): called on
        # the DISPATCHER thread for every event, before watcher
        # fan-out, with the stored (read-only) object — no copy. See
        # subscribe().
        self._subscribers: tuple = ()
        # WAL taps (the replication hub's feed): called UNDER self._lock
        # with (version, raw_line) for every journaled mutation, in
        # version order, with the exact bytes the WAL got — the line a
        # follower must append verbatim for its log to be byte-identical
        # to the leader's. Taps run only after the local append
        # succeeded, so a torn (unacked) record is never shipped. See
        # add_wal_tap().
        self._wal_taps: tuple = ()
        # Optional quorum gate: when set (by the replication hub on the
        # leader), every write ack additionally waits for the record to
        # reach the replicated commit index — fsync-before-ack extended
        # to quorum-before-ack. See set_commit_gate().
        self._commit_gate = None
        # Fan-out rides its own thread: writers only append to this
        # queue under the lock; the dispatcher does the per-event copy
        # and per-watcher predicate work OFF the write path, so write
        # latency is independent of watcher count. Ordering: single
        # dispatcher = version order; replay/live races are settled by
        # each stream's version floor (WatchStream.push).
        self._dispatch_q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        # The thread holds only (weakref, queue): a dropped store is
        # still collectable even if close() was never called (tests
        # build thousands of throwaway stores).
        self._dispatcher = threading.Thread(
            target=_dispatch_thread,
            args=(weakref.ref(self), self._dispatch_q),
            daemon=True,
        )
        self._dispatcher.start()
        # A store dropped WITHOUT close() must still retire its thread:
        # the weakref alone makes the object collectable, but the
        # thread would park in q.get() forever. The finalizer holds
        # only the queue, so it doesn't resurrect the store.
        weakref.finalize(self, self._dispatch_q.put, None)
        # Optional serialized write path (see _write_thread). Off by
        # default: the queue hop + event wake adds ~100us of latency
        # per write, only worth paying when HUNDREDS of threads would
        # otherwise contend the lock (the 1000-kubelet shape).
        self._write_q = None
        if serialized_writes:
            self._write_q = _queue.SimpleQueue()
            threading.Thread(
                target=_write_thread,
                args=(weakref.ref(self), self._write_q),
                daemon=True,
            ).start()
            weakref.finalize(self, self._write_q.put, None)
        # TTL fast path: earliest pending expiry; ops skip all expiry
        # work until the clock actually reaches it. The heap carries
        # (expiry, key) with lazy invalidation (see _expire_locked).
        self._next_expiry = math.inf
        self._ttl_heap: List[Tuple[float, str]] = []
        # Durability (off when data_dir is None — tests/benches that
        # want a pure in-memory store keep the old behavior).
        # TTL clock: wall time for durable stores (deadlines must age
        # across restarts), monotonic for in-memory ones (immune to
        # NTP steps — the pre-durability behavior).
        self._now = time.time if data_dir else time.monotonic
        # Replica mode (set_replica_mode): the store is a follower
        # mirror — direct writes are refused (mutations arrive only
        # through replicate()) and TTL entries never expire locally
        # (the leader's expiry lands as a replicated DELETED record; a
        # local expiry would fork the version clock). The journal/
        # apply split: _repl_pending holds journaled-but-uncommitted
        # (version, raw_line) entries; _repl_journaled is the highest
        # journaled version (what the leader's quorum counts).
        self._replica = False
        self._repl_pending: deque = deque()
        self._repl_journaled = 0
        self._fsync = fsync
        self._snapshot_every = snapshot_every
        self._wal_file = None
        self._wal_count = 0
        self._wal_seq = 0  # records appended (group-commit cursor)
        self._synced_seq = 0  # records known durable
        # io_gate: this lock EXISTS to serialize the group-commit fsync
        # (ktsan's blocking-under-lock check exempts it by declaration).
        self._sync_lock = sanitizer.lock("kvstore.sync", io_gate=True)
        self._closed = False
        self._lockfd: Optional[int] = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._data_dir = data_dir
            self._snap_path = os.path.join(data_dir, "snapshot.json")
            self._wal_path = os.path.join(data_dir, "wal.log")
            # Exclusive advisory lock on the data dir: two stores
            # appending the same WAL / racing snapshot.json via
            # os.replace would silently interleave state (etcd
            # serializes this for the reference — one member owns the
            # dir). Held for the process lifetime; the OS releases it
            # on any death, so a kill -9'd owner never wedges restart.
            self._lockfd = os.open(
                os.path.join(data_dir, "LOCK"), os.O_CREAT | os.O_RDWR, 0o644
            )
            try:
                fcntl.flock(self._lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(self._lockfd)
                self._lockfd = None
                raise StoreError(
                    f"data dir {data_dir!r} is locked by another KVStore "
                    "(apiserver already running against it?)"
                )
            os.ftruncate(self._lockfd, 0)  # clear any longer stale pid
            os.write(self._lockfd, str(os.getpid()).encode())
            replayed = self._recover()
            self._repl_journaled = self._version
            self._ttl_heap = [(t, k) for k, t in self._ttl.items()]
            heapq.heapify(self._ttl_heap)
            self._next_expiry = min(self._ttl.values(), default=math.inf)
            self._wal_file = open(self._wal_path, "a", encoding="utf-8")
            if replayed:
                # Compact on boot: fold the replayed tail into a fresh
                # snapshot so the next recovery is O(snapshot).
                self._snapshot_locked()
            # Age out TTL'd keys that expired while we were down; goes
            # through the normal delete path so the WAL records it.
            self._expire_locked()
            if self._fsync:
                self._fsync_dir()  # make the WAL's dir entry durable

    # -- durability ---------------------------------------------------

    def _recover(self) -> int:
        """Load snapshot then replay WAL records newer than it.

        Tolerates a torn final WAL line (the process died mid-append;
        that write was never acknowledged... the apiserver responds
        only after create/set/delete return, which is after the append)
        by truncating the file back to the last intact record, so the
        next append never fuses onto torn bytes. Returns the number of
        WAL records replayed.
        """
        snap_version = 0
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "r", encoding="utf-8") as f:
                snap = json.load(f)
            snap_version = snap["version"]
            for key, obj, ver, exp in snap["items"]:
                self._data[key] = (obj, ver)
                if exp is not None:
                    self._ttl[key] = exp
            # Recovery runs in __init__, before the store is shared
            # with any other thread.  # ktlint: disable=KT002
            self._version = snap_version
        replayed = 0
        if os.path.exists(self._wal_path):
            torn = False
            with open(self._wal_path, "rb") as f:
                good_offset = 0
                for raw in f:
                    if not raw.endswith(b"\n"):
                        torn = True  # mid-append crash, unacked
                        break
                    line = raw.strip()
                    if line:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            torn = True
                            break
                        v = rec["v"]
                        if v > snap_version:  # else folded into snapshot
                            key = rec["k"]
                            if rec["t"] == DELETED:
                                self._data.pop(key, None)
                                self._ttl.pop(key, None)
                            else:
                                self._data[key] = (rec["o"], v)
                                if rec.get("e") is not None:
                                    self._ttl[key] = rec["e"]
                                else:
                                    self._ttl.pop(key, None)
                            # Same: pre-share WAL replay, no
                            # readers yet.  # ktlint: disable=KT002
                            self._version = max(self._version, v)
                            replayed += 1
                    good_offset += len(raw)
            if torn:
                with open(self._wal_path, "r+b") as f:
                    f.truncate(good_offset)
        return replayed

    def _wal_append_locked(
        self, version: int, etype: str, key: str, obj: dict,
        flush: bool = True,
    ) -> None:
        if self._wal_file is None and not self._wal_taps:
            return
        rec = {"v": version, "t": etype, "k": key}
        if etype != DELETED:
            rec["o"] = obj
            exp = self._ttl.get(key)
            if exp is not None:
                rec["e"] = exp
        data = json.dumps(rec, separators=(",", ":")) + "\n"
        if self._wal_file is not None:
            if faults.enabled() and faults.fire(faults.WAL_TORN_WRITE, key):
                # Mid-append process death: a PREFIX of the record
                # reaches the file (no newline), the write is never
                # acked (raise), and recovery must truncate back to the
                # last intact record. The store is DEAD from here
                # (_closed): a torn line only exists because the
                # process died mid-write, so later appends must never
                # fuse onto the torn bytes — a live continuation would
                # make replay truncate ACKED records that landed after
                # it. Pair with crash() + a fresh store on the same
                # data dir. The raise also happens BEFORE the WAL taps:
                # a torn record must never reach a follower.
                self._wal_file.write(data[: max(1, len(data) // 2)])
                self._wal_file.flush()
                self._closed = True
                raise faults.FaultInjected(
                    f"kvstore.wal.torn_write: died mid-append of {key}"
                )
            self._wal_file.write(data)
            # flush=False is the batch path (create_many/
            # atomic_update_many and friends): records accumulate in
            # the file object's buffer and _wal_flush_locked writes
            # them as ONE append at the end of the lock hold — the
            # "single WAL append" half of group commit.
            if flush:
                self._wal_file.flush()
            # fsync does NOT happen here (we hold self._lock): callers
            # ack through _wal_sync after releasing it — the group-
            # commit seam.
            self._wal_seq += 1
            self._wal_count += 1
            if self._wal_count >= self._snapshot_every:
                self._snapshot_locked()
        for tap in self._wal_taps:
            # O(append-to-buffer) by contract: taps enqueue the raw
            # line for an off-thread shipper; the actual network send
            # never happens under this lock.
            try:
                tap(version, data)
            except Exception:
                pass  # a broken replication link must not fail writes

    def _wal_flush_locked(self) -> None:
        """Flush buffered batch appends to the OS (one write syscall
        for the whole batch); the fsync still happens in _wal_sync."""
        if self._wal_file is not None:
            self._wal_file.flush()

    def _sync_batch_locked_free(self) -> None:
        """One group-commit fsync covering everything appended so far
        (the serialized write thread's per-batch flush). Caller must
        NOT hold self._lock. No-op for in-memory / fsync=off stores.
        Deliberately NOT _ack_write: the applier thread must never park
        on the replication quorum — each caller waits for its own
        commit in _ack_write instead."""
        with self._lock:
            seq = self._wal_seq
        self._wal_sync(seq)

    def _ack_write(self, seq: int) -> None:
        """The full before-ack pipeline for one local write: group-
        commit fsync (_wal_sync), then — when a replication hub gates
        this store — quorum commit. Every public mutation funnels its
        ack through here, so "acked" always means "durable on this
        node AND on a quorum of replicas" once replication is attached.
        Callers must NOT hold self._lock."""
        self._wal_sync(seq)
        gate = self._commit_gate
        if gate is not None:
            gate()

    def _wal_sync(self, seq: int) -> None:
        """Group commit: make WAL record `seq` durable before the
        caller acks. One fsync covers every record flushed before it,
        so N concurrent writers pay ~1 disk flush, not N — the batching
        etcd does on its WAL. Callers must NOT hold self._lock (appends
        proceed while the disk flushes; that concurrency IS the
        amortization). No-op when fsync is off or the store is
        in-memory (seq stays 0)."""
        if not self._fsync or seq == 0:
            return
        # The documented contract, now enforced: holding self._lock
        # here would serialize every writer behind the disk flush and
        # deadlock against _snapshot_locked's handle rotation — the
        # group-commit amortization depends on appends proceeding WHILE
        # the fsync runs. (RLock._is_owned is the same probe
        # threading.Condition uses.)
        owned = getattr(self._lock, "_is_owned", None)
        if owned is not None and owned():
            raise AssertionError(
                "_wal_sync must not be called while holding self._lock "
                "(group-commit contract; see the _wal_sync docstring)"
            )
        with self._sync_lock:
            while True:
                if self._synced_seq >= seq:
                    return  # a peer's fsync / snapshot / close covered us
                with self._lock:
                    wal = self._wal_file
                    flushed = self._wal_seq
                if wal is None:
                    # Closed underneath us. close() fsyncs the WAL and
                    # advances _synced_seq BEFORE dropping the handle —
                    # but the loop-top check may predate close(), so
                    # re-check before refusing: only a close whose
                    # fsync FAILED leaves _synced_seq behind seq.
                    if self._synced_seq >= seq:
                        return
                    raise StoreError(
                        "store closed before this write became durable"
                    )
                try:
                    # Chaos seam: an injected fsync failure surfaces to
                    # the acking writer as a real I/O error — flushed
                    # but not durable. INSIDE this try on purpose: like
                    # a genuine OSError, it must be forgiven when a
                    # concurrent snapshot rotation already made the
                    # write durable (the rotated-handle branch below).
                    faults.fire(faults.WAL_FSYNC)
                    os.fsync(wal.fileno())
                except (ValueError, OSError):
                    with self._lock:
                        rotated = wal is not self._wal_file
                    if not rotated:
                        raise  # real I/O failure on the live handle
                    # A concurrent _snapshot_locked rotated the handle
                    # between capture and fsync. The snapshot fsync'd
                    # everything appended before it and advanced
                    # _synced_seq — loop and re-check instead of
                    # surfacing a bogus failure for a durable write.
                    continue
                if flushed > self._synced_seq:
                    self._synced_seq = flushed
                return

    def _snapshot_locked(self) -> None:
        """Write the full state atomically, then truncate the WAL.

        Crash-safe in both orders: a crash after the rename but before
        the truncate leaves WAL records with v <= snapshot version,
        which _recover skips.

        Runs (fsyncs included) under self._lock on purpose: compaction
        is stop-the-world for writers — rotating the WAL handle while
        appends proceed would lose records. The ktsan allow_blocking
        grant below documents that exception; everything else in the
        store honors "no blocking I/O under kvstore.lock".
        """
        items = [
            [key, obj, ver, self._ttl.get(key)]
            for key, (obj, ver) in sorted(self._data.items())
        ]
        tmp = self._snap_path + ".tmp"
        with sanitizer.allow_blocking(
            "snapshot compaction is stop-the-world by design"
        ):
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"version": self._version, "items": items}, f)
                f.flush()
                os.fsync(f.fileno())
            # Chaos seam: crash-before-rename leaves only the .tmp file
            # — recovery must keep serving the previous snapshot plus
            # the (untruncated) WAL.
            faults.fire(faults.SNAPSHOT_RENAME)
            os.replace(tmp, self._snap_path)
        if self._wal_file is not None:
            self._wal_file.close()
        self._wal_file = open(self._wal_path, "w", encoding="utf-8")
        self._wal_count = 0
        if self._fsync:
            # Power-loss ordering: the snapshot rename's directory
            # entry must be durable BEFORE new WAL appends land, or a
            # crash could pair the old snapshot with a truncated WAL.
            with sanitizer.allow_blocking(
                "snapshot compaction is stop-the-world by design"
            ):
                self._fsync_dir()
            # Everything appended so far is folded into the (fsync'd)
            # snapshot: waiting group-commit callers are already
            # durable without touching the fresh WAL.
            self._synced_seq = self._wal_seq

    def _fsync_dir(self) -> None:
        fd = os.open(self._data_dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def snapshot(self) -> None:
        """Force a snapshot + WAL truncation (no-op for in-memory stores)."""
        with self._lock:
            if self._wal_file is not None:
                self._snapshot_locked()

    # -- version plumbing ---------------------------------------------

    @property
    def closed(self) -> bool:
        """Whether close() ran — the healthz kvstore subcheck (a closed
        store still answers reads from memory, so liveness must be
        asked, not probed)."""
        return self._closed

    def dispatcher_alive(self) -> bool:
        """Liveness of the watch fan-out thread (healthz watch-hub
        subcheck): a dead dispatcher freezes every watcher — scheduler,
        kubelets, controllers — while writes still succeed, which is
        exactly the failure a plain write probe cannot see."""
        return self._dispatcher.is_alive()

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def journaled_version(self) -> int:
        """Highest version durable in this store's log — the replica
        ack the leader counts toward quorum (>= version while an
        uncommitted replicated tail is pending)."""
        with self._lock:
            return max(self._repl_journaled, self._version)

    def _bump(self) -> int:
        # Every mutation funnels through here under self._lock. A
        # closed store must REFUSE writes rather than ack them with
        # the WAL handle already gone — an in-flight HTTP handler
        # racing server shutdown would otherwise ack a write that no
        # recovery will ever see.
        if self._closed:
            raise StoreError("store is closed")
        if self._replica:
            # Follower mirrors take mutations ONLY through
            # apply_replicated: a local write would mint a version the
            # leader also mints, forking the logical clock. This is
            # also the store-tier fencing backstop — a stale leader's
            # late write against a demoted store is refused here.
            raise StoreError("store is a read-only replica")
        # Every caller holds self._lock (the apply paths); _bump is
        # the locked clock's helper.  # ktlint: disable=KT002
        self._version += 1
        return self._version

    @staticmethod
    def _stamp(obj: dict, version: int) -> dict:
        obj.setdefault("metadata", {})["resourceVersion"] = str(version)
        return obj

    def _expire_locked(self) -> None:
        if self._replica:
            return  # expiry replicates from the leader as DELETED records
        if self._now() < self._next_expiry:
            return  # nothing can have expired yet — O(1) common path
        now = self._now()
        # Heap of (expiry, key) with lazy invalidation (the _ttl dict
        # is authoritative): expiry work is O(expired log n). The old
        # full scan of _ttl was O(all TTL entries) under the store
        # lock EVERY write once any entry was due — with tens of
        # thousands of TTL'd events continuously expiring at 1000-node
        # scale, that scan WAS the store's write ceiling.
        heap = self._ttl_heap
        while heap and heap[0][0] <= now:
            exp, k = heapq.heappop(heap)
            cur = self._ttl.get(k)
            if cur is None or cur != exp:
                continue  # refreshed or already gone: stale heap entry
            del self._ttl[k]
            if k in self._data:
                obj, _ = self._data.pop(k)
                v = self._bump()
                self._record_locked(v, DELETED, k, obj)
        self._next_expiry = heap[0][0] if heap else math.inf

    def _record_locked(
        self, version: int, etype: str, key: str, obj: dict,
        prev: Optional[dict] = None, flush: bool = True,
    ) -> None:
        """Journal one mutation; the _locked suffix IS the contract
        (callers hold self._lock; ktsan checks it interprocedurally). The write
        path only appends: WAL, history ring, dispatch queue. The
        per-event copy and per-watcher filter/push work happens on the
        dispatcher thread, so a write's lock hold is O(obj-serialize)
        for durable stores and O(1) otherwise — independent of watcher
        count. `obj` is the just-stored object (never mutated in place
        after storage); history shares the ref and replay copies it
        per delivery (watch())."""
        try:
            self._wal_append_locked(version, etype, key, obj, flush=flush)
        except faults.FaultInjected:
            # Torn-write chaos site: the "process" died mid-append, so
            # the in-memory apply (made by the caller just before this
            # journal step) must roll back — the dead store's reads
            # would otherwise serve an object watchers never saw and
            # replay will not reconstruct. Stored objects carry their
            # stamped resourceVersion, so the previous tuple rebuilds
            # exactly. (TTL bookkeeping is left to the heap's lazy
            # invalidation; the version-counter gap is harmless.)
            if etype == ADDED:
                self._data.pop(key, None)
                self._ttl.pop(key, None)
            elif etype == MODIFIED and prev is not None:
                self._data[key] = (
                    prev,
                    int(prev.get("metadata", {}).get("resourceVersion", 0)),
                )
            elif etype == DELETED:
                self._data[key] = (
                    obj,
                    int(obj.get("metadata", {}).get("resourceVersion", 0)),
                )
            raise
        self._publish_locked(version, etype, key, obj, prev)

    def _publish_locked(
        self, version: int, etype: str, key: str, obj: dict,
        prev: Optional[dict] = None,
    ) -> None:
        """History-ring + dispatch half of _record_locked — shared with
        the replicated-apply path, which journals raw leader bytes
        instead of re-serializing but must feed watchers identically."""
        if not self._history:
            self._oldest = version
        self._history.append((version, etype, key, obj))
        if len(self._history) == self._history.maxlen:
            self._oldest = self._history[0][0]
        self._dispatch_q.put((version, etype, key, obj, prev))

    def _dispatch_event(self, item: tuple) -> None:
        """Watch fan-out for one event, off the write path. ALL watchers
        share ONE private copy per event: stored state stays unreachable
        through events, and the copy cost doesn't scale with watcher
        count (at 100 kubelets a per-watcher deepcopy under the store
        lock was the control plane's wall, not the solver). Event
        objects are read-only by contract — every consumer either
        JSON-encodes them (HTTP watch) or decodes them into fresh typed
        objects (serde.from_wire rebuilds every container).

        Sharded watchers (watch(..., shard=(fn, value))) are indexed by
        their shard value and only offered events whose object (or
        previous state) maps to that value — at 1000 kubelets each
        watching spec.nodeName=<self>, per-event fan-out would
        otherwise cost O(watchers) filter evaluations, and 90k pod
        events x 1000 watchers of dispatch work WAS the 1000-node
        drill's wall. Routing is conservative: a watcher's pred can
        only match (directly or through the DELETED translation) when
        obj or prev carries its shard value, so skipped watchers would
        have produced no event anyway."""
        version, etype, key, obj, prev = item
        for sub in self._subscribers:
            # Subscribers see every event in version order before the
            # watcher fan-out (they feed read caches, so they must be
            # at least as fresh as anything a watcher could observe).
            # obj is the stored object — read-only by contract.
            try:
                sub(version, etype, key, obj, prev)
            except Exception:
                pass  # a broken cache must not stall watch fan-out
        with self._lock:
            watchers = list(self._unsharded)
            for fn in self._shard_fns:  # distinct extractors (usually 1)
                vals = {fn(obj)}
                if prev is not None:
                    vals.add(fn(prev))
                for v in vals:
                    watchers.extend(self._shard_buckets.get((fn, v), ()))
        delivered = None  # lazily copied: most events match few watchers
        saw_closed = False
        for prefix, pred, stream, _shard in watchers:
            if stream.closed:
                saw_closed = True
                continue
            if key.startswith(prefix):
                ev = _filter_event(pred, etype, obj, prev, version)
                if ev is not None:
                    if delivered is None:
                        delivered = _copy_obj(obj)
                    stream.push(Event(ev.type, delivered, version))
            if stream.closed:
                saw_closed = True  # push() just dropped a slow consumer
        if saw_closed:
            with self._lock:
                self._watchers = [
                    w for w in self._watchers if not w[2].closed
                ]
                self._rebuild_watch_index_locked()

    def _rebuild_watch_index_locked(self) -> None:
        self._unsharded = []
        self._shard_buckets = {}
        for w in self._watchers:
            shard = w[3]
            if shard is None:
                self._unsharded.append(w)
            else:
                self._shard_buckets.setdefault(tuple(shard), []).append(w)
        self._shard_fns = tuple({fn for fn, _v in self._shard_buckets})

    # -- CRUD ---------------------------------------------------------

    def create(self, key: str, obj: dict, ttl: Optional[float] = None) -> dict:
        obj = _copy_obj(obj)  # before the lock: O(obj) work stays outside

        def op():
            with self._lock:
                self._expire_locked()
                if key in self._data:
                    raise AlreadyExistsError(key)
                v = self._bump()
                self._stamp(obj, v)
                self._data[key] = (obj, v)
                if ttl is not None:
                    exp = self._now() + ttl
                    self._ttl[key] = exp
                    heapq.heappush(self._ttl_heap, (exp, key))
                    self._next_expiry = min(self._next_expiry, exp)
                self._record_locked(v, ADDED, key, obj)
                return self._wal_seq

        seq = self._apply_write(op)
        self._ack_write(seq)  # fsync-before-ack, amortized across writers
        return _copy_obj(obj)

    def create_many(
        self,
        entries: List[Tuple[str, dict, Optional[float]]],
        copy: bool = True,
    ) -> List:
        """Create a batch of objects under ONE lock hold, ONE buffered
        WAL append, and ONE group-commit fsync — the bulk write fast
        path (a 512-pod bulk POST pays one commit, not 512). Per-item
        results: the stored object (a ref — callers must not mutate)
        or the exception instance (AlreadyExistsError) for items that
        failed; failures never abort the rest of the batch. Versions
        are assigned in list order, so watchers observe the batch's
        ADDED events in exactly the submitted order.

        copy=False trusts the caller to hand over PRIVATE dicts (the
        HTTP tier's just-parsed request body) and skips the defensive
        per-object copy — the dominant per-item cost at bulk rates."""
        if copy:
            entries = [(k, _copy_obj(o), t) for k, o, t in entries]

        def op():
            out = []
            with self._lock:
                self._expire_locked()
                for key, obj, ttl in entries:
                    if key in self._data:
                        out.append(AlreadyExistsError(key))
                        continue
                    v = self._bump()
                    self._stamp(obj, v)
                    self._data[key] = (obj, v)
                    if ttl is not None:
                        exp = self._now() + ttl
                        self._ttl[key] = exp
                        heapq.heappush(self._ttl_heap, (exp, key))
                        self._next_expiry = min(self._next_expiry, exp)
                    self._record_locked(v, ADDED, key, obj, flush=False)
                    out.append(obj)
                self._wal_flush_locked()
                return out, self._wal_seq

        results, seq = self._apply_write(op)
        self._ack_write(seq)  # ONE fsync for the whole batch
        return results

    def delete_many(self, keys: List[str]) -> List:
        """Delete a batch of keys under one lock hold / WAL append /
        fsync (the bulk-churn drain path). Per-item results: the
        deleted object or NotFoundError."""

        def op():
            out = []
            with self._lock:
                self._expire_locked()
                for key in keys:
                    if key not in self._data:
                        out.append(NotFoundError(key))
                        continue
                    obj, _ = self._data.pop(key)
                    self._ttl.pop(key, None)
                    v = self._bump()
                    self._record_locked(v, DELETED, key, obj, flush=False)
                    out.append(obj)
                self._wal_flush_locked()
                return out, self._wal_seq

        results, seq = self._apply_write(op)
        self._ack_write(seq)
        return results

    def get(self, key: str) -> dict:
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            obj = self._data[key][0]
        # Copy OUTSIDE the lock: stored tuples are rebound, never
        # mutated in place, so the ref is a consistent snapshot — and
        # the store's one lock must not be held for O(object) copies.
        return _copy_obj(obj)

    def set(
        self, key: str, obj: dict, expected_version: Optional[int] = None
    ) -> dict:
        """Update; CAS when expected_version is given (etcd CompareAndSwap)."""
        obj = _copy_obj(obj)  # before the lock: O(obj) work stays outside

        def op():
            with self._lock:
                self._expire_locked()
                if key not in self._data:
                    raise NotFoundError(key)
                prev, cur_v = self._data[key]
                if expected_version is not None and cur_v != expected_version:
                    raise ConflictError(
                        f"{key}: version {expected_version} != current {cur_v}"
                    )
                v = self._bump()
                self._stamp(obj, v)
                self._data[key] = (obj, v)
                self._record_locked(v, MODIFIED, key, obj, prev=prev)
                return self._wal_seq

        seq = self._apply_write(op)
        self._ack_write(seq)
        return _copy_obj(obj)

    def delete(self, key: str, expected_version: Optional[int] = None) -> dict:
        def op():
            with self._lock:
                self._expire_locked()
                if key not in self._data:
                    raise NotFoundError(key)
                obj, cur_v = self._data[key]
                if expected_version is not None and cur_v != expected_version:
                    raise ConflictError(
                        f"{key}: version {expected_version} != current {cur_v}"
                    )
                del self._data[key]
                self._ttl.pop(key, None)
                v = self._bump()
                self._record_locked(v, DELETED, key, obj)
                return obj, self._wal_seq

        obj, seq = self._apply_write(op)
        self._ack_write(seq)
        return _copy_obj(obj)

    def list(self, prefix: str, copy: bool = True) -> Tuple[List[dict], int]:
        """All objects under prefix + the store version (for watch
        resume). copy=False hands out the stored objects themselves
        (read-only contract — for callers that only serialize)."""
        with self._lock:
            self._expire_locked()
            # Snapshot refs under the lock (cheap), copy outside it: a
            # 3000-pod list must not stall every writer for the copy.
            snap = [
                obj
                for key, (obj, _) in sorted(self._data.items())
                if key.startswith(prefix)
            ]
            version = self._version
        if not copy:
            return snap, version
        return [_copy_obj(o) for o in snap], version

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            self._expire_locked()
            return sorted(k for k in self._data if k.startswith(prefix))

    def _apply_write(self, op):
        """Run a mutation closure directly, or through the serialized
        writer when enabled. `op` takes the store lock itself (short
        hold); exceptions propagate to the caller either way.

        Shutdown-safe: close() retires the applier thread (which fails
        every queued entry with StoreClosedError) and nulls _write_q so
        late writers fall back to the direct path (where _bump refuses
        with "store is closed"). The wait is bounded with a closed-
        store re-check so a write racing close() can never block its
        thread forever."""
        q = self._write_q
        if q is None:
            return op()
        ev = threading.Event()
        cell: list = []
        q.put((op, ev, cell))
        while not ev.wait(timeout=5.0):
            if self._closed and not cell:
                # Applier retired without reaching this entry (close()
                # raced the enqueue above the sentinel-drain window).
                raise StoreClosedError(
                    "store closed before this write was applied"
                )
        ok, val = cell[0]
        if ok:
            return val
        raise val

    def _atomic_update_locked(
        self, key: str, update_fn, flush: bool = True, copy: bool = True
    ) -> dict:
        """Caller holds self._lock.

        copy=False is the trusted bulk-replace path: update_fn receives
        the STORED object itself (READ-ONLY — it must not mutate it)
        and must return a PRIVATE dict (the HTTP tier's parsed request
        body qualifies), which is stored without the two defensive
        json round-trips — at bulk-update rates those copies were the
        batch's dominant cost."""
        if key not in self._data:
            raise NotFoundError(key)
        cur, _ = self._data[key]
        if copy:
            # Stored state must be PRIVATE: update_fn may graft caller-
            # owned sub-dicts into its return (update_status splices the
            # request body's status), so the stored object is a copy —
            # same invariant set() keeps by copying its input.
            stored = _copy_obj(update_fn(_copy_obj(cur)))
        else:
            stored = update_fn(cur)
        v = self._bump()
        self._stamp(stored, v)
        self._data[key] = (stored, v)
        self._record_locked(v, MODIFIED, key, stored, prev=cur, flush=flush)
        return stored

    def atomic_update(self, key: str, update_fn: Callable[[dict], dict]) -> dict:
        """Single-hold read-modify-write: update_fn runs under the store
        lock on a private copy, so no CAS retry loop and ONE lock
        acquisition per write instead of guaranteed_update's two. This
        is the high-traffic write path (status PUTs, bindings): on a
        single-core host a 100-kubelet status burst queues hundreds of
        threads on this lock, and every extra lock handoff costs up to
        a GIL switch interval. update_fn must be small and must not
        call back into the store."""

        def op():
            with self._lock:
                self._expire_locked()
                stored = self._atomic_update_locked(key, update_fn)
                return stored, self._wal_seq

        stored, seq = self._apply_write(op)
        self._ack_write(seq)
        return _copy_obj(stored)

    def atomic_update_many(
        self, ops: List[Tuple[str, Callable[[dict], dict]]],
        atomic: bool = False,
        copy: bool = True,
        copy_results: Optional[bool] = None,
    ) -> List:
        """Batch of single-hold read-modify-writes under ONE lock
        acquisition (and one serialized-writer hop). The batch solver
        commits a whole backlog's bindings through this: per-binding
        lock acquisitions would queue the scheduler behind every
        kubelet status writer once per pod — at 1000 nodes that
        convoy, not the solve, was the bind-rate ceiling. Per-item
        results: the stored object, or the exception instance for
        items whose update raised (APIError-style callers translate).

        atomic=True makes the batch all-or-nothing (the gang-bind
        path): every update_fn runs against a staged copy first, and
        only when ALL succeed are the staged objects committed —
        versions bumped, watches fanned out. On the first failure
        nothing has been applied; the failing item carries its own
        exception and every other item an AbortedError. Check-then-
        commit under the one lock hold is strictly stronger than
        apply-then-roll-back: no watcher can ever observe a state
        that is later undone."""

        def batch():
            out = []
            with self._lock:
                self._expire_locked()
                if not atomic:
                    for key, update_fn in ops:
                        try:
                            out.append(
                                self._atomic_update_locked(
                                    key, update_fn, flush=False, copy=copy
                                )
                            )
                        except Exception as e:  # per-item outcome, not abort
                            out.append(e)
                    self._wal_flush_locked()
                    return out, self._wal_seq
                # Atomic: stage everything, commit only if all succeed.
                # `staged` doubles as an overlay so a batch touching the
                # same key twice sees its own earlier (uncommitted) write.
                staged: Dict[str, dict] = {}
                order: List[Tuple[str, dict, dict]] = []
                failure: Optional[Exception] = None
                for key, update_fn in ops:
                    cur = staged.get(key)
                    if cur is None:
                        if key not in self._data:
                            failure = NotFoundError(key)
                            break
                        cur = self._data[key][0]
                    try:
                        stored = _copy_obj(update_fn(_copy_obj(cur)))
                    except Exception as e:
                        failure = e
                        break
                    staged[key] = stored
                    order.append((key, stored, cur))
                if failure is not None:
                    n_done = len(order)
                    for i in range(len(ops)):
                        if i == n_done:
                            out.append(failure)
                        else:
                            out.append(
                                AbortedError(
                                    "atomic batch aborted; nothing applied"
                                )
                            )
                    return out, self._wal_seq
                for key, stored, cur in order:
                    v = self._bump()
                    self._stamp(stored, v)
                    self._data[key] = (stored, v)
                    self._record_locked(v, MODIFIED, key, stored, prev=cur, flush=False)
                    out.append(stored)
                self._wal_flush_locked()
                return out, self._wal_seq

        results, seq = self._apply_write(batch)
        self._ack_write(seq)
        # copy_results=False hands back the STORED objects (read-only
        # contract) — callers that only inspect status/metadata (the
        # bind commit path, bulk update) skip a per-item json round
        # trip, which at 50k-pod bulk binds was a full copy of the
        # cluster per commit.
        if copy_results is None:
            copy_results = copy
        if not copy_results:
            return results
        return [
            r if isinstance(r, Exception) else _copy_obj(r) for r in results
        ]

    # -- GuaranteedUpdate (etcd_helper.go:510-600) ---------------------

    def guaranteed_update(
        self, key: str, update_fn: Callable[[dict], dict], max_retries: int = 16
    ) -> dict:
        """Read-modify-write with CAS retry. update_fn gets a private copy
        and returns the new object (or raises to abort)."""
        for _ in range(max_retries):
            with self._lock:
                self._expire_locked()
                if key not in self._data:
                    raise NotFoundError(key)
                cur, cur_v = self._data[key]
            cur = _copy_obj(cur)  # private copy, made outside the lock
            new = update_fn(cur)
            try:
                return self.set(key, new, expected_version=cur_v)
            except ConflictError:
                continue
        raise ConflictError(f"{key}: too many CAS retries")

    # -- Watch --------------------------------------------------------

    def subscribe(self, fn: Callable) -> None:
        """Register an event subscriber: fn(version, etype, key, obj,
        prev) is invoked on the dispatcher thread for EVERY event, in
        version order, before watcher fan-out. `obj` is the stored
        object itself (read-only by contract — subscribers must not
        mutate and must copy before handing out). This is the
        apiserver watch cache's feed: one hook, no extra threads, no
        per-event copies."""
        with self._lock:
            self._subscribers = self._subscribers + (fn,)

    # -- Replication (store/replication.py rides these seams) ---------

    def add_wal_tap(self, fn: Callable) -> None:
        """Register a WAL tap: fn(version, raw_line) is invoked UNDER
        self._lock, in version order, with the exact newline-terminated
        bytes the local WAL received — the replication hub's feed. Taps
        must only enqueue (no I/O, no store calls)."""
        with self._lock:
            self._wal_taps = self._wal_taps + (fn,)

    def set_commit_gate(self, fn: Optional[Callable]) -> None:
        """Install (or clear, with None) the quorum gate: a zero-arg
        callable every write ack runs AFTER its fsync, off-lock. The
        replication hub points this at its wait-committed barrier so a
        leader acks at raft-lite quorum, not just local durability."""
        self._commit_gate = fn

    def set_replica_mode(self, replica: bool) -> None:
        """Mark this store a follower mirror (writes refused, TTLs
        passive — see _bump/_expire_locked) or promote it back to a
        writable leader."""
        with self._lock:
            self._replica = replica

    @property
    def replica(self) -> bool:
        return self._replica

    def replicate(self, raw_lines: List[str], commit: int) -> Tuple[int, int]:
        """Follower ingest — raft's log/state-machine split on one
        store. Leader-shipped WAL lines are journaled VERBATIM (byte-
        identical follower logs are the promotion oracle; no re-
        serialization can drift) and made durable before return, so the
        leader may count this follower toward quorum for every
        journaled version. Only the prefix at or below `commit` (the
        leader's commit index) is applied to the live mirror — memory,
        history ring, subscribers, watchers — exactly as _recover
        would replay it, so a follower apiserver's watch cache stays
        warm while the uncommitted tail stays invisible. Lines at or
        below the journaled version are skipped (idempotent under link
        retries). Returns (journaled_version, applied_version)."""

        def op():
            with self._lock:
                if self._closed:
                    raise StoreError("store is closed")
                for data in raw_lines:
                    v = json.loads(data)["v"]
                    if v <= self._repl_journaled:
                        continue
                    # Pending BEFORE journal: _wal_raw_locked's deferred-
                    # compaction guard must already see this entry.
                    self._repl_pending.append((v, data))
                    self._wal_raw_locked(v, data)
                    self._repl_journaled = v
                self._commit_replicated_locked(commit)
                self._wal_flush_locked()
                return self._repl_journaled, self._version, self._wal_seq

        journaled, applied, seq = self._apply_write(op)
        self._wal_sync(seq)
        return journaled, applied

    def _commit_replicated_locked(self, commit: int) -> None:
        """Apply journaled entries up to the leader commit index."""
        while self._repl_pending and self._repl_pending[0][0] <= commit:
            v, data = self._repl_pending.popleft()
            rec = json.loads(data)
            key, etype = rec["k"], rec["t"]
            if etype == DELETED:
                prev_t = self._data.pop(key, None)
                self._ttl.pop(key, None)
                obj = prev_t[0] if prev_t is not None else {
                    "metadata": {"name": key.rsplit("/", 1)[-1]}
                }
                prev = None
            else:
                obj = rec["o"]
                prev_t = self._data.get(key)
                prev = prev_t[0] if prev_t is not None else None
                self._data[key] = (obj, v)
                exp = rec.get("e")
                if exp is not None:
                    self._ttl[key] = exp
                    heapq.heappush(self._ttl_heap, (exp, key))
                    self._next_expiry = min(self._next_expiry, exp)
                else:
                    self._ttl.pop(key, None)
            self._version = v
            self._publish_locked(v, etype, key, obj, prev)

    def promote_replica(self) -> int:
        """Promote this follower to a writable leader exposing EXACTLY
        the committed prefix: the journaled-but-uncommitted tail is
        discarded (truncated out of the WAL — an unacked record must
        never surface after failover, the crash-recovery oracle
        extended to replication) and replica mode flips off. Returns
        the version the new leader serves from."""
        with self._lock:
            dropped = sum(
                len(d.encode("utf-8")) for _v, d in self._repl_pending
            )
            self._repl_pending.clear()
            self._repl_journaled = self._version
            if self._wal_file is not None and dropped:
                with sanitizer.allow_blocking(
                    "promotion truncates the uncommitted tail; "
                    "stop-the-world like snapshot compaction"
                ):
                    self._wal_file.flush()
                    size = os.path.getsize(self._wal_path)
                    os.truncate(self._wal_path, max(0, size - dropped))
                    if self._fsync:
                        os.fsync(self._wal_file.fileno())
            self._replica = False
            return self._version

    def _wal_raw_locked(self, version: int, data: str) -> None:
        """Journal one leader-shipped line byte-for-byte (the verbatim
        half of replicate; flush batched by the caller). Compaction is
        deferred while uncommitted entries are pending: a snapshot
        folds MEMORY state and truncates the WAL, which would silently
        drop the journaled-not-applied tail."""
        if self._wal_file is not None:
            self._wal_file.write(data)
            self._wal_seq += 1
            self._wal_count += 1
            if (
                self._wal_count >= self._snapshot_every
                and not self._repl_pending
            ):
                self._snapshot_locked()
        for tap in self._wal_taps:  # chained replication stays possible
            try:
                tap(version, data)
            except Exception:
                pass

    def dump_state(self) -> dict:
        """Consistent bootstrap snapshot for a late-joining follower —
        same shape as the on-disk snapshot ({version, items:[key, obj,
        version, expiry]}). Objects are copied: the dump outlives this
        lock hold and usually crosses a process/HTTP boundary."""
        with self._lock:
            self._expire_locked()
            items = [
                [k, obj, ver, self._ttl.get(k)]
                for k, (obj, ver) in sorted(self._data.items())
            ]
            version = self._version
        return {
            "version": version,
            "items": [[k, _copy_obj(o), v, e] for k, o, v, e in items],
        }

    def load_state(self, state: dict) -> None:
        """Install a leader bootstrap snapshot into this (empty)
        follower; durable followers immediately fold it into their own
        snapshot file so a restart recovers to the same point."""
        with self._lock:
            if self._data or self._version:
                raise StoreError("load_state requires an empty store")
            for key, obj, ver, exp in state["items"]:
                self._data[key] = (obj, ver)
                if exp is not None:
                    self._ttl[key] = exp
                    heapq.heappush(self._ttl_heap, (exp, key))
                    self._next_expiry = min(self._next_expiry, exp)
            self._version = state["version"]
            self._repl_journaled = self._version
            if self._wal_file is not None:
                self._snapshot_locked()

    def expire_now(self) -> None:
        """Process due TTL expirations (O(1) when none are due). Read
        caches call this before serving: expiry normally piggybacks on
        writes, so a quiet store could otherwise serve TTL'd objects
        past their deadline from a cache."""
        with self._lock:
            self._expire_locked()

    def watch(
        self,
        prefix: str,
        since: int = 0,
        maxsize: int = 4096,
        pred: Optional[Callable[[dict], bool]] = None,
        shard: Optional[tuple] = None,
    ) -> WatchStream:
        """Stream events for keys under prefix with version > since.

        since=0 means "from now". History older than the replay buffer
        raises CompactedError — caller must re-list (Reflector does).
        `pred` is a selector filter applied INSIDE the fan-out with
        etcd's modified-out-of-filter -> DELETED translation
        (_filter_event): non-matching events are never copied or queued
        for this watcher.

        `shard` = (extract_fn, value): a routing hint asserting this
        watcher's pred can only match objects whose extract_fn(obj)
        equals `value` (directly or via the previous state). The
        dispatcher then indexes the watcher by value instead of
        evaluating it against every event — O(1) fan-out for the
        1000-kubelets-each-watching-their-node shape. extract_fn must
        be a shared (module-level) callable so equal shards hash
        together.
        """
        with self._lock:
            self._expire_locked()
            # The replayable floor: with history, anything >= oldest-1;
            # without (fresh boot / post-restart), only "now" — an older
            # `since` has missed events that no longer exist, so 410.
            if since and since < self._version:
                if not self._history or since + 1 < self._oldest:
                    raise CompactedError(
                        f"version {since} compacted "
                        f"(oldest {self._oldest if self._history else self._version})"
                    )
            from kubernetes_tpu.store.watch import resource_of_prefix

            stream = WatchStream(
                maxsize=maxsize, resource=resource_of_prefix(prefix)
            )
            if since:
                for v, etype, key, obj in self._history:
                    if v > since and key.startswith(prefix):
                        # History has no prev state: replay uses the
                        # spurious-DELETED degradation (_filter_event).
                        # History entries share stored objects, so each
                        # delivery gets its own copy.
                        ev = _filter_event(pred, etype, obj, None, v)
                        if ev is not None:
                            stream.push(Event(ev.type, _copy_obj(obj), v))
            # Replay covered everything <= the current version; the
            # floor makes the dispatcher's not-yet-fanned-out backlog
            # (all <= it, since writes need this lock) a no-op for this
            # stream instead of a duplicate. Registration happens only
            # AFTER replay so live events can't interleave mid-replay.
            stream.floor = self._version
            self._watchers = [
                w for w in self._watchers if not w[2].closed
            ]
            self._watchers.append((prefix, pred, stream, shard))
            self._rebuild_watch_index_locked()
            return stream

    def stop_watch(self, stream: WatchStream) -> None:
        stream.close()
        with self._lock:
            self._watchers = [
                w for w in self._watchers if not w[2].closed
            ]
            self._rebuild_watch_index_locked()

    def crash(self) -> None:
        """Abandon the store the way a killed process would (the chaos
        harness's kill -9 analog): watchers close, queued serialized
        writes fail with StoreClosedError, the flock releases — and
        unlike close(), NOTHING is fsynced and _synced_seq does not
        advance, so a writer racing the crash is refused its durability
        ack ("store closed before this write became durable") exactly
        as it would be by a real death.

        Fidelity note: file buffers still flush on handle close (we
        share the page cache with any successor store, so OS-level loss
        of flushed-not-fsynced bytes is not simulatable in-process).
        The WAL_TORN_WRITE fault site models death MID-append; this
        method models death between append and fsync."""
        with self._lock:
            self._closed = True
            for w in self._watchers:
                w[2].close()
            self._watchers = []
            self._unsharded = []
            self._shard_buckets = {}
            if self._write_q is not None:
                self._write_q.put(None)
                self._write_q = None
            self._dispatch_q.put(None)
            if self._wal_file is not None:
                try:
                    self._wal_file.close()
                except OSError:
                    pass
                self._wal_file = None
            if self._lockfd is not None:
                os.close(self._lockfd)  # the OS releases a dead owner's flock
                self._lockfd = None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for w in self._watchers:
                w[2].close()
            self._watchers = []
            self._unsharded = []
            self._shard_buckets = {}
            if self._write_q is not None:
                # Retire the serialized writer (it fails every queued
                # entry with StoreClosedError) and null the queue so
                # late writers take the direct path, where _bump
                # refuses writes on a closed store.
                self._write_q.put(None)
                self._write_q = None
            self._dispatch_q.put(None)  # retire the dispatcher thread
            if self._wal_file is not None:
                # fsync-before-close: a writer that appended its record
                # but hasn't reached _wal_sync yet must still find its
                # bytes durable (its wal-is-None path checks
                # _synced_seq). Without this, a write racing close()
                # would be acked flushed-but-not-fsync'd — exactly what
                # fsync-by-default promises can't happen.
                if self._fsync:
                    try:
                        with sanitizer.allow_blocking(
                            "close() is terminal; no writer can make "
                            "progress past a closed store anyway"
                        ):
                            self._wal_file.flush()
                            os.fsync(self._wal_file.fileno())
                        self._synced_seq = self._wal_seq
                    except OSError:
                        pass  # racing writers will refuse their acks
                self._wal_file.close()
                self._wal_file = None
            if self._lockfd is not None:
                os.close(self._lockfd)  # releases the flock
                self._lockfd = None
