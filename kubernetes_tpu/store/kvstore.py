"""The versioned KV store.

Semantics mirror the reference's etcd usage through EtcdHelper
(pkg/tools/etcd_helper.go):

- A single global, monotonically increasing logical clock. Every write
  bumps it; every object carries the version of its last write in
  metadata.resourceVersion (pkg/tools/etcd_object.go).
- Create fails if the key exists (AlreadyExists); CompareAndSwap update
  fails on version mismatch (Conflict); `guaranteed_update` is the CAS
  retry loop of EtcdHelper.GuaranteedUpdate (etcd_helper.go:510-600).
- Watch(prefix, since) replays buffered history after `since`, then
  streams live events in version order (etcd_helper_watch.go:73-165).
  Asking for a version older than the history window raises
  CompactedError (clients must re-list, like etcd index cleared errors).
- Values are wire-form dicts (deep-copied on the way in and out), so
  storage is serialization-faithful like etcd's JSON payloads.
- Optional per-key TTL (events registry uses it, reference: event TTL).
- Optional durability (`data_dir=`): every mutation is appended to a
  JSON-lines write-ahead log and the full state is periodically
  snapshotted; construction replays snapshot + WAL so an apiserver
  restarted on the same --data-dir recovers every object, binding and
  allocator lease with the resourceVersion clock intact. This is the
  role etcd plays for the reference (pkg/tools/etcd_helper.go:101,
  hack/local-up-cluster.sh:152-153): master state must survive process
  death. TTLs are wall-clock deadlines so they age across restarts.
  fsync-before-ack is the DEFAULT (etcd's contract: acked writes
  survive power loss, not just process death), group-committed so N
  concurrent writers share a disk flush; fsync=False (daemon flag
  --no-data-fsync) trades that for write latency.

Thread-safe; many reader/writer threads, one lock (control-plane rates
are tiny next to the TPU solver's work).
"""

from __future__ import annotations

import copy
import fcntl
import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.store.watch import ADDED, DELETED, Event, MODIFIED, WatchStream


class StoreError(Exception):
    pass


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


class ConflictError(StoreError):
    pass


class CompactedError(StoreError):
    """Watch window no longer covers the requested version."""


class KVStore:
    def __init__(
        self,
        history_limit: int = 10000,
        data_dir: Optional[str] = None,
        fsync: bool = True,
        snapshot_every: int = 4096,
    ):
        self._lock = threading.RLock()
        self._data: Dict[str, Tuple[dict, int]] = {}  # key -> (wire obj, version)
        self._ttl: Dict[str, float] = {}  # key -> expiry wall-clock time
        self._version = 0
        # History ring for watch replay: (version, type, key, obj).
        self._history: deque = deque(maxlen=history_limit)
        self._oldest = 0  # lowest version NOT compacted out of history
        self._watchers: List[Tuple[str, WatchStream]] = []  # (prefix, stream)
        # Durability (off when data_dir is None — tests/benches that
        # want a pure in-memory store keep the old behavior).
        # TTL clock: wall time for durable stores (deadlines must age
        # across restarts), monotonic for in-memory ones (immune to
        # NTP steps — the pre-durability behavior).
        self._now = time.time if data_dir else time.monotonic
        self._fsync = fsync
        self._snapshot_every = snapshot_every
        self._wal_file = None
        self._wal_count = 0
        self._wal_seq = 0  # records appended (group-commit cursor)
        self._synced_seq = 0  # records known durable
        self._sync_lock = threading.Lock()
        self._closed = False
        self._lockfd: Optional[int] = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self._data_dir = data_dir
            self._snap_path = os.path.join(data_dir, "snapshot.json")
            self._wal_path = os.path.join(data_dir, "wal.log")
            # Exclusive advisory lock on the data dir: two stores
            # appending the same WAL / racing snapshot.json via
            # os.replace would silently interleave state (etcd
            # serializes this for the reference — one member owns the
            # dir). Held for the process lifetime; the OS releases it
            # on any death, so a kill -9'd owner never wedges restart.
            self._lockfd = os.open(
                os.path.join(data_dir, "LOCK"), os.O_CREAT | os.O_RDWR, 0o644
            )
            try:
                fcntl.flock(self._lockfd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                os.close(self._lockfd)
                self._lockfd = None
                raise StoreError(
                    f"data dir {data_dir!r} is locked by another KVStore "
                    "(apiserver already running against it?)"
                )
            os.ftruncate(self._lockfd, 0)  # clear any longer stale pid
            os.write(self._lockfd, str(os.getpid()).encode())
            replayed = self._recover()
            self._wal_file = open(self._wal_path, "a", encoding="utf-8")
            if replayed:
                # Compact on boot: fold the replayed tail into a fresh
                # snapshot so the next recovery is O(snapshot).
                self._snapshot_locked()
            # Age out TTL'd keys that expired while we were down; goes
            # through the normal delete path so the WAL records it.
            self._expire_locked()
            if self._fsync:
                self._fsync_dir()  # make the WAL's dir entry durable

    # -- durability ---------------------------------------------------

    def _recover(self) -> int:
        """Load snapshot then replay WAL records newer than it.

        Tolerates a torn final WAL line (the process died mid-append;
        that write was never acknowledged... the apiserver responds
        only after create/set/delete return, which is after the append)
        by truncating the file back to the last intact record, so the
        next append never fuses onto torn bytes. Returns the number of
        WAL records replayed.
        """
        snap_version = 0
        if os.path.exists(self._snap_path):
            with open(self._snap_path, "r", encoding="utf-8") as f:
                snap = json.load(f)
            snap_version = snap["version"]
            for key, obj, ver, exp in snap["items"]:
                self._data[key] = (obj, ver)
                if exp is not None:
                    self._ttl[key] = exp
            self._version = snap_version
        replayed = 0
        if os.path.exists(self._wal_path):
            torn = False
            with open(self._wal_path, "rb") as f:
                good_offset = 0
                for raw in f:
                    if not raw.endswith(b"\n"):
                        torn = True  # mid-append crash, unacked
                        break
                    line = raw.strip()
                    if line:
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            torn = True
                            break
                        v = rec["v"]
                        if v > snap_version:  # else folded into snapshot
                            key = rec["k"]
                            if rec["t"] == DELETED:
                                self._data.pop(key, None)
                                self._ttl.pop(key, None)
                            else:
                                self._data[key] = (rec["o"], v)
                                if rec.get("e") is not None:
                                    self._ttl[key] = rec["e"]
                                else:
                                    self._ttl.pop(key, None)
                            self._version = max(self._version, v)
                            replayed += 1
                    good_offset += len(raw)
            if torn:
                with open(self._wal_path, "r+b") as f:
                    f.truncate(good_offset)
        return replayed

    def _wal_append(self, version: int, etype: str, key: str, obj: dict) -> None:
        if self._wal_file is None:
            return
        rec = {"v": version, "t": etype, "k": key}
        if etype != DELETED:
            rec["o"] = obj
            exp = self._ttl.get(key)
            if exp is not None:
                rec["e"] = exp
        self._wal_file.write(json.dumps(rec, separators=(",", ":")) + "\n")
        self._wal_file.flush()
        # fsync does NOT happen here (we hold self._lock): callers ack
        # through _wal_sync after releasing it — the group-commit seam.
        self._wal_seq += 1
        self._wal_count += 1
        if self._wal_count >= self._snapshot_every:
            self._snapshot_locked()

    def _wal_sync(self, seq: int) -> None:
        """Group commit: make WAL record `seq` durable before the
        caller acks. One fsync covers every record flushed before it,
        so N concurrent writers pay ~1 disk flush, not N — the batching
        etcd does on its WAL. Callers must NOT hold self._lock (appends
        proceed while the disk flushes; that concurrency IS the
        amortization). No-op when fsync is off or the store is
        in-memory (seq stays 0)."""
        if not self._fsync or seq == 0:
            return
        with self._sync_lock:
            if self._synced_seq >= seq:
                return  # a peer's fsync (or a snapshot) covered us
            with self._lock:
                wal = self._wal_file
                flushed = self._wal_seq
            if wal is None:
                return  # closed underneath us; writes were refused
            os.fsync(wal.fileno())
            if flushed > self._synced_seq:
                self._synced_seq = flushed

    def _snapshot_locked(self) -> None:
        """Write the full state atomically, then truncate the WAL.

        Crash-safe in both orders: a crash after the rename but before
        the truncate leaves WAL records with v <= snapshot version,
        which _recover skips.
        """
        items = [
            [key, obj, ver, self._ttl.get(key)]
            for key, (obj, ver) in sorted(self._data.items())
        ]
        tmp = self._snap_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": self._version, "items": items}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        if self._wal_file is not None:
            self._wal_file.close()
        self._wal_file = open(self._wal_path, "w", encoding="utf-8")
        self._wal_count = 0
        if self._fsync:
            # Power-loss ordering: the snapshot rename's directory
            # entry must be durable BEFORE new WAL appends land, or a
            # crash could pair the old snapshot with a truncated WAL.
            self._fsync_dir()
            # Everything appended so far is folded into the (fsync'd)
            # snapshot: waiting group-commit callers are already
            # durable without touching the fresh WAL.
            self._synced_seq = self._wal_seq

    def _fsync_dir(self) -> None:
        fd = os.open(self._data_dir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def snapshot(self) -> None:
        """Force a snapshot + WAL truncation (no-op for in-memory stores)."""
        with self._lock:
            if self._wal_file is not None:
                self._snapshot_locked()

    # -- version plumbing ---------------------------------------------

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def _bump(self) -> int:
        # Every mutation funnels through here under self._lock. A
        # closed store must REFUSE writes rather than ack them with
        # the WAL handle already gone — an in-flight HTTP handler
        # racing server shutdown would otherwise ack a write that no
        # recovery will ever see.
        if self._closed:
            raise StoreError("store is closed")
        self._version += 1
        return self._version

    @staticmethod
    def _stamp(obj: dict, version: int) -> dict:
        obj.setdefault("metadata", {})["resourceVersion"] = str(version)
        return obj

    def _expire_locked(self) -> None:
        if not self._ttl:
            return
        now = self._now()
        expired = [k for k, t in self._ttl.items() if t <= now]
        for k in expired:
            del self._ttl[k]
            if k in self._data:
                obj, _ = self._data.pop(k)
                v = self._bump()
                self._record(v, DELETED, k, obj)

    def _record(self, version: int, etype: str, key: str, obj: dict) -> None:
        # History and watch consumers get their own copies: stored state
        # must never be reachable (hence mutable) through an event.
        obj = copy.deepcopy(obj)
        self._wal_append(version, etype, key, obj)
        if not self._history:
            self._oldest = version
        self._history.append((version, etype, key, obj))
        if len(self._history) == self._history.maxlen:
            self._oldest = self._history[0][0]
        live = []
        for prefix, stream in self._watchers:
            if stream.closed:
                continue  # prune dead watchers as we go
            if key.startswith(prefix):
                stream.push(Event(etype, copy.deepcopy(obj), version))
            if not stream.closed:
                live.append((prefix, stream))
        self._watchers = live

    # -- CRUD ---------------------------------------------------------

    def create(self, key: str, obj: dict, ttl: Optional[float] = None) -> dict:
        with self._lock:
            self._expire_locked()
            if key in self._data:
                raise AlreadyExistsError(key)
            obj = copy.deepcopy(obj)
            v = self._bump()
            self._stamp(obj, v)
            self._data[key] = (obj, v)
            if ttl is not None:
                self._ttl[key] = self._now() + ttl
            self._record(v, ADDED, key, obj)
            out = copy.deepcopy(obj)
            seq = self._wal_seq
        self._wal_sync(seq)  # fsync-before-ack, amortized across writers
        return out

    def get(self, key: str) -> dict:
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            return copy.deepcopy(self._data[key][0])

    def set(
        self, key: str, obj: dict, expected_version: Optional[int] = None
    ) -> dict:
        """Update; CAS when expected_version is given (etcd CompareAndSwap)."""
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            _, cur_v = self._data[key]
            if expected_version is not None and cur_v != expected_version:
                raise ConflictError(
                    f"{key}: version {expected_version} != current {cur_v}"
                )
            obj = copy.deepcopy(obj)
            v = self._bump()
            self._stamp(obj, v)
            self._data[key] = (obj, v)
            self._record(v, MODIFIED, key, obj)
            out = copy.deepcopy(obj)
            seq = self._wal_seq
        self._wal_sync(seq)
        return out

    def delete(self, key: str, expected_version: Optional[int] = None) -> dict:
        with self._lock:
            self._expire_locked()
            if key not in self._data:
                raise NotFoundError(key)
            obj, cur_v = self._data[key]
            if expected_version is not None and cur_v != expected_version:
                raise ConflictError(
                    f"{key}: version {expected_version} != current {cur_v}"
                )
            del self._data[key]
            self._ttl.pop(key, None)
            v = self._bump()
            self._record(v, DELETED, key, obj)
            out = copy.deepcopy(obj)
            seq = self._wal_seq
        self._wal_sync(seq)
        return out

    def list(self, prefix: str) -> Tuple[List[dict], int]:
        """All objects under prefix + the store version (for watch resume)."""
        with self._lock:
            self._expire_locked()
            out = [
                copy.deepcopy(obj)
                for key, (obj, _) in sorted(self._data.items())
                if key.startswith(prefix)
            ]
            return out, self._version

    def keys(self, prefix: str = "") -> List[str]:
        with self._lock:
            self._expire_locked()
            return sorted(k for k in self._data if k.startswith(prefix))

    # -- GuaranteedUpdate (etcd_helper.go:510-600) ---------------------

    def guaranteed_update(
        self, key: str, update_fn: Callable[[dict], dict], max_retries: int = 16
    ) -> dict:
        """Read-modify-write with CAS retry. update_fn gets a private copy
        and returns the new object (or raises to abort)."""
        for _ in range(max_retries):
            with self._lock:
                self._expire_locked()
                if key not in self._data:
                    raise NotFoundError(key)
                cur, cur_v = self._data[key]
                cur = copy.deepcopy(cur)
            new = update_fn(cur)
            try:
                return self.set(key, new, expected_version=cur_v)
            except ConflictError:
                continue
        raise ConflictError(f"{key}: too many CAS retries")

    # -- Watch --------------------------------------------------------

    def watch(self, prefix: str, since: int = 0, maxsize: int = 4096) -> WatchStream:
        """Stream events for keys under prefix with version > since.

        since=0 means "from now". History older than the replay buffer
        raises CompactedError — caller must re-list (Reflector does).
        """
        with self._lock:
            self._expire_locked()
            # The replayable floor: with history, anything >= oldest-1;
            # without (fresh boot / post-restart), only "now" — an older
            # `since` has missed events that no longer exist, so 410.
            if since and since < self._version:
                if not self._history or since + 1 < self._oldest:
                    raise CompactedError(
                        f"version {since} compacted "
                        f"(oldest {self._oldest if self._history else self._version})"
                    )
            stream = WatchStream(maxsize=maxsize)
            self._watchers = [(p, s) for p, s in self._watchers if not s.closed]
            self._watchers.append((prefix, stream))
            if since:
                for v, etype, key, obj in self._history:
                    if v > since and key.startswith(prefix):
                        stream.push(Event(etype, copy.deepcopy(obj), v))
            return stream

    def stop_watch(self, stream: WatchStream) -> None:
        stream.close()
        with self._lock:
            self._watchers = [(p, s) for p, s in self._watchers if not s.closed]

    def close(self) -> None:
        with self._lock:
            self._closed = True
            for _, s in self._watchers:
                s.close()
            self._watchers = []
            if self._wal_file is not None:
                self._wal_file.close()
                self._wal_file = None
            if self._lockfd is not None:
                os.close(self._lockfd)  # releases the flock
                self._lockfd = None
