"""ctypes binding for the native columnar kernels (native/columnar.cc).

Loads native/build/libkubetpu.so when present (built via `make -C
native`); every entry point has a NumPy fallback so the framework is
fully functional without the native build — the lib just makes 50k-pod
host lowering cheaper.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_LIB_PATH = os.path.join(_REPO_ROOT, "native", "build", "libkubetpu.so")
_PAUSE_PATH = os.path.join(_REPO_ROOT, "native", "build", "pause")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False

_i64 = ctypes.c_int64
_p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
_p_u32 = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
_p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_p_f32 = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")


_SOURCES = (
    os.path.join(_REPO_ROOT, "native", "columnar.cc"),
    os.path.join(_REPO_ROOT, "native", "Makefile"),
)


def _stale() -> bool:
    """True when a native source is newer than the built .so — loading
    a stale kernel silently runs old semantics (advisor finding r1)."""
    try:
        built = os.path.getmtime(_LIB_PATH)
    except OSError:
        return False
    return any(
        os.path.exists(src) and os.path.getmtime(src) > built
        for src in _SOURCES
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if not os.path.exists(_LIB_PATH) or _stale():
        return None
    try:
        # Load through a unique temp copy: dlopen caches by pathname,
        # so re-loading _LIB_PATH after an in-process rebuild would
        # silently return the OLD mapping. The copy lives NEXT TO the
        # real .so (the system temp dir may be mounted noexec) and is
        # unlinked right after load (the mapping survives the unlink).
        import shutil
        import tempfile

        try:
            # Prefer a sibling of the real .so (system temp may be
            # noexec); fall back to the temp dir for read-only installs.
            fd, tmp = tempfile.mkstemp(
                suffix=".so", prefix="kubetpu-", dir=os.path.dirname(_LIB_PATH)
            )
        except OSError:
            fd, tmp = tempfile.mkstemp(suffix=".so", prefix="kubetpu-")
        os.close(fd)
        shutil.copyfile(_LIB_PATH, tmp)
        try:
            lib = ctypes.CDLL(tmp)
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        lib.pack_bitsets.argtypes = [_i64, _i64, _p_i64, _p_i32, _p_u32]
        lib.or_rows_by_index.argtypes = [_i64, _i64, _p_i32, _p_u32, _p_u32]
        lib.greedy_fit.argtypes = [
            _i64, _p_i32, _p_f32, _p_f32, _p_f32, _p_f32,
            _p_f32, _p_f32, _p_u8, _p_f32, _p_f32, _p_f32,
        ]
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def ensure_built(quiet: bool = True) -> bool:
    """Build the native lib if the toolchain is around (best-effort);
    rebuilds when sources are newer than the .so."""
    if available() and not _stale():
        return True
    try:
        subprocess.run(
            ["make", "-C", os.path.join(_REPO_ROOT, "native"), "lib"],
            check=True, capture_output=quiet,
        )
    except (OSError, subprocess.CalledProcessError):
        return False
    global _lib, _load_attempted
    _lib = None
    _load_attempted = False
    return available()


def pause_binary() -> Optional[str]:
    """Path to the pod-anchor binary (None if not built)."""
    return _PAUSE_PATH if os.path.exists(_PAUSE_PATH) else None


# ---------------------------------------------------------------------------
# Kernels (native with NumPy fallback)
# ---------------------------------------------------------------------------


def pack_bitsets(
    id_lists: Sequence[Sequence[int]], words: int
) -> np.ndarray:
    """Rows of ids -> u32[n_rows, words] bitsets."""
    n = len(id_lists)
    out = np.zeros((n, words), dtype=np.uint32)
    if n == 0:
        return out
    # Typical backlogs have NO hostPorts/volumes on most pods: a
    # truthiness sweep is ~100x cheaper than building the offsets/flat
    # arrays just to discover there is nothing to pack.
    if not any(id_lists):
        return out
    lib = _load()
    if lib is not None:
        counts = np.fromiter(
            (len(ids) for ids in id_lists), dtype=np.int64, count=n
        )
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        flat = np.fromiter(
            (i for ids in id_lists for i in ids),
            dtype=np.int32,
            count=int(offsets[-1]),
        )
        # The C kernel does no bounds checking (it would be heap
        # corruption); match the NumPy fallback's IndexError instead.
        if len(flat) and (
            int(flat.max()) >= words * 32 or int(flat.min()) < 0
        ):
            raise IndexError(
                f"bitset id out of range for {words} words "
                f"(max {int(flat.max())}, min {int(flat.min())})"
            )
        lib.pack_bitsets(n, words, offsets, flat, out)
        return out
    for i, ids in enumerate(id_lists):
        row = out[i]
        for j in ids:
            row[j >> 5] |= np.uint32(1 << (j & 31))
    return out


def or_rows_by_index(
    node_idx: np.ndarray, pod_rows: np.ndarray, node_rows: np.ndarray
) -> None:
    """node_rows[node_idx[i]] |= pod_rows[i] in place (node_idx<0 skipped)."""
    lib = _load()
    node_idx = np.ascontiguousarray(node_idx, dtype=np.int32)
    pod_rows = np.ascontiguousarray(pod_rows, dtype=np.uint32)
    if lib is not None and node_rows.flags["C_CONTIGUOUS"]:
        # Match the NumPy fallback's IndexError; the C kernel would
        # write out of bounds (negative indices are skipped by design).
        if len(node_idx) and int(node_idx.max()) >= node_rows.shape[0]:
            raise IndexError(
                f"node index {int(node_idx.max())} >= {node_rows.shape[0]}"
            )
        lib.or_rows_by_index(
            len(node_idx), pod_rows.shape[1], node_idx, pod_rows, node_rows
        )
        return
    for i, j in enumerate(node_idx):
        if j >= 0:
            node_rows[j] |= pod_rows[i]


def greedy_fit(
    node_idx: np.ndarray,
    cpu: np.ndarray,
    mem: np.ndarray,
    cpu_cap: np.ndarray,
    mem_cap: np.ndarray,
    cpu_fit: np.ndarray,
    mem_fit: np.ndarray,
    over: np.ndarray,
    cpu_used: np.ndarray,
    mem_used: np.ndarray,
    pods_used: np.ndarray,
) -> None:
    """Assigned-pod occupancy sweep, in place (reference
    MapPodsToMachines greedy order; see native/columnar.cc)."""
    lib = _load()
    node_idx = np.ascontiguousarray(node_idx, dtype=np.int32)
    cpu = np.ascontiguousarray(cpu, dtype=np.float32)
    mem = np.ascontiguousarray(mem, dtype=np.float32)
    if lib is not None and over.dtype == np.bool_ and over.flags["C_CONTIGUOUS"]:
        if len(node_idx) and int(node_idx.max()) >= len(cpu_cap):
            raise IndexError(
                f"node index {int(node_idx.max())} >= {len(cpu_cap)}"
            )
        lib.greedy_fit(
            len(node_idx), node_idx, cpu, mem, cpu_cap, mem_cap,
            cpu_fit, mem_fit, over.view(np.uint8), cpu_used, mem_used,
            pods_used,
        )
        return
    for i, j in enumerate(node_idx):
        if j < 0:
            continue
        c, m = cpu[i], mem[i]
        cpu_used[j] += c
        mem_used[j] += m
        pods_used[j] += 1
        fits_cpu = cpu_cap[j] == 0 or cpu_fit[j] + c <= cpu_cap[j]
        fits_mem = mem_cap[j] == 0 or mem_fit[j] + m <= mem_cap[j]
        if fits_cpu and fits_mem:
            cpu_fit[j] += c
            mem_fit[j] += m
        else:
            over[j] = True
