"""Batch scheduling: solve a whole pending-pod backlog at once.

The TPU path (north star): lower the backlog + cluster to a columnar
Snapshot, upload, run the jitted sequential-parity solver, and return
per-pod node assignments. `schedule_backlog_scalar` drives the exact
same problem through the scalar oracle pipeline — it is both the
fallback path (reference: stock FitPredicate path when the sidecar is
unavailable) and the parity yardstick.
"""

from __future__ import annotations

import copy
from typing import List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.algspec import AlgorithmSpec
from kubernetes_tpu.models.columnar import Snapshot, build_snapshot
from kubernetes_tpu.models.objects import Node, Pod, Service
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler, NoNodesError
from kubernetes_tpu.scheduler.plugins import (
    PluginFactoryArgs,
    build_from_spec,
    default_predicates,
    default_priorities,
)
from kubernetes_tpu.scheduler.types import (
    StaticNodeLister,
    StaticPodLister,
    StaticServiceLister,
)
from kubernetes_tpu.utils import sanitizer, tracing


_AUTO_NO_MESH_WARNED = False
_ENV_MESH_WARNED = False


def env_mesh():
    """The KT_MESH_DEVICES=N escape hatch: a host-platform mesh for
    daemons that have no session-threaded mesh yet (ROADMAP item 2).
    Returns a mesh over the first N visible devices via the sanctioned
    matrices seam, or None when the variable is unset, not a valid
    integer >= 2, or fewer than N devices are visible (each non-unset
    failure warns once — a typo'd hatch must not silently fall back to
    the unsharded path). Lazy jax import: the batch module stays
    importable on jax-free control-plane hosts."""
    import os

    raw = os.environ.get("KT_MESH_DEVICES")
    if raw is None:
        return None
    global _ENV_MESH_WARNED

    def _warn_once(msg):
        global _ENV_MESH_WARNED
        if not _ENV_MESH_WARNED:
            _ENV_MESH_WARNED = True
            import logging

            logging.getLogger(__name__).warning(msg)

    try:
        n = int(raw)
    except ValueError:
        _warn_once(
            f"KT_MESH_DEVICES={raw!r} is not an integer — ignoring the "
            "escape hatch (unsharded solve)"
        )
        return None
    if n < 2:
        if n != 1:  # =1 is an explicit "no mesh", not a misconfig
            _warn_once(
                f"KT_MESH_DEVICES={n} < 2 cannot form a mesh — ignoring "
                "the escape hatch (unsharded solve)"
            )
        return None
    from kubernetes_tpu.ops import matrices

    mesh = matrices.host_mesh(n)
    if mesh is None:
        _warn_once(
            f"KT_MESH_DEVICES={n} requested but fewer devices are "
            "visible — ignoring the escape hatch (unsharded solve); on "
            "CPU hosts also set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    return mesh


def resolve_batch_mode(mode: str, mesh=None) -> str:
    """Resolve --batch-mode auto by the topology the solve will
    ACTUALLY run on. No mesh: the scan — exact sequential parity AND
    the fastest path (the pallas kernel keeps the occupancy carry in
    VMEM; ops/pallas_scan.py is single-device only). Sharded over a
    mesh: the wave solver — the scan's per-pod step becomes a
    cross-device argmax+psum round, so a P-pod backlog pays P
    collective latencies (50k steps of ICI round-trips) where wave
    pays ~a dozen windowed commits, and pallas is ineligible anyway
    (docs/performance.md, mesh crossover). Keyed on the mesh the
    caller will pass to the solve, NOT on how many devices are merely
    visible — an unsharded solve on a multi-device host still wants
    the scan.

    Today NO shipped daemon constructs a mesh (ADVICE r5: both
    production call sites pass mesh=None), so in the daemons `auto`
    resolves to scan until ROADMAP item 2 threads a real
    jax.sharding.Mesh through the schedulers — the one-time warning
    below keeps that honest for operators reading logs. The
    KT_MESH_DEVICES=N environment escape hatch (:func:`env_mesh`)
    bridges the gap: when set and no mesh was passed, auto consults a
    host-platform mesh built through the matrices seam."""
    if mode != "auto":
        return mode
    if mesh is None:
        mesh = env_mesh()
    if mesh is None:
        global _AUTO_NO_MESH_WARNED
        if not _AUTO_NO_MESH_WARNED:
            _AUTO_NO_MESH_WARNED = True
            import logging

            logging.getLogger(__name__).warning(
                "--batch-mode auto resolved to 'scan': no device mesh "
                "is threaded through this scheduler (the daemons never "
                "construct one yet — ROADMAP item 2) and KT_MESH_DEVICES "
                "is unset, so auto currently ALWAYS selects scan in "
                "production; the wave path engages only when a solve "
                "runs over a real mesh (or the KT_MESH_DEVICES=N "
                "escape hatch builds one)"
            )
        return "scan"
    return "wave"


def schedule_backlog_scalar(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    spec: Optional[AlgorithmSpec] = None,
) -> List[Optional[str]]:
    """Schedule the backlog one pod at a time through the scalar oracle,
    committing each placement before the next (the reference's
    scheduleOne + AssumePod semantics). Returns node names (None =
    unschedulable). `spec` selects the configured plugin set — the
    fallback path must honor scheduler policy, not silently revert to
    defaults (round-2 VERDICT Weak #1)."""
    # Distinct phase label: whole-backlog scalar plugin-loop times are
    # seconds where device "solve" dispatch is sub-ms — folding them
    # into one histogram series would make its percentiles a mixture
    # nobody can decompose.
    with tracing.phase("solve_scalar", pods=len(pending)):
        return _schedule_backlog_scalar(pending, nodes, assigned, services, spec)


def _schedule_backlog_scalar(pending, nodes, assigned, services, spec):
    committed: List[Pod] = list(assigned)
    pod_lister = StaticPodLister(committed)  # shared, mutated as we commit
    args = PluginFactoryArgs(
        pod_lister=pod_lister,
        service_lister=StaticServiceLister(list(services)),
        node_lister=StaticNodeLister(list(nodes)),
    )
    if spec is not None:
        predicates, priorities = build_from_spec(spec, args)
    else:
        predicates, priorities = default_predicates(args), default_priorities(args)
    scheduler = GenericScheduler(predicates, priorities, pod_lister)
    out: List[Optional[str]] = []
    ready_nodes = StaticNodeLister(
        [n for n in nodes if _node_ready(n)]
    )
    for pod in pending:
        try:
            dest = scheduler.schedule(pod, ready_nodes)
        except (FitError, NoNodesError):
            out.append(None)
            continue
        out.append(dest)
        placed = copy.deepcopy(pod)
        placed.spec.node_name = dest
        pod_lister.pods.append(placed)
    return out


def _node_ready(node: Node) -> bool:
    from kubernetes_tpu.models.columnar import node_is_ready

    return node_is_ready(node)


def schedule_backlog_tpu(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    mesh=None,
    spec: Optional[AlgorithmSpec] = None,
) -> List[Optional[str]]:
    """Schedule the backlog on the accelerator. Same decision semantics
    as schedule_backlog_scalar (>=99% parity target, BASELINE.md).
    A non-default `spec` lowers the configured predicate/priority set
    (raises UnloweredPolicyError if it can't — callers fall back to
    the scalar path WITH the spec)."""
    from kubernetes_tpu.ops import device_snapshot, solve_assignments

    # jit dispatch blocks on device work and (first call per shape
    # bucket) on an XLA compile measured in seconds — ktsan treats it
    # like any other blocking call: never under a sanitized lock.
    sanitizer.check_blocking("jit-dispatch", "schedule_backlog_tpu")
    with tracing.phase("lower", pods=len(pending)):
        snap = build_snapshot(
            pending, nodes, assigned_pods=assigned, services=services, spec=spec
        )
    with tracing.phase("upload"):
        dsnap = device_snapshot(snap, mesh=mesh)
    with tracing.phase("solve", mode="scan"):
        # solve_assignments blocks on the host copy internally, so this
        # phase captures the device time (unlike the async pipeline).
        assignment = solve_assignments(dsnap)
    with tracing.phase("readback"):
        names = snap.nodes.names
        return [names[i] if i >= 0 else None for i in assignment]


def schedule_backlog_wave(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    mesh=None,
) -> List[Optional[str]]:
    """Schedule via the wave-commit solver (ops.wave): ~3x the scan's
    throughput by committing many pods per device step, at the cost of
    exact decision-order parity (placements remain VALID — capacity,
    selectors, ports, volumes all enforced — and quality matches or
    beats sequential; see ops/wave.py). The scan path is the parity
    referee."""
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.wave import wave_assignments

    sanitizer.check_blocking("jit-dispatch", "schedule_backlog_wave")
    with tracing.phase("lower", pods=len(pending)):
        snap = build_snapshot(
            pending, nodes, assigned_pods=assigned, services=services
        )
    with tracing.phase("upload"):
        dsnap = device_snapshot(snap, mesh=mesh)
    # wave_assignments opens the "solve" phase itself (it knows the
    # wave count) and blocks on the strip, so readback is the residue.
    assignment, _waves = wave_assignments(dsnap)
    with tracing.phase("readback"):
        names = snap.nodes.names
        return [names[i] if i >= 0 else None for i in assignment]


def schedule_backlog_sinkhorn(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    mesh=None,
) -> List[Optional[str]]:
    """Schedule via the Sinkhorn-matched wave solver (ops.sinkhorn):
    entropic assignment with capacity-capped congestion prices — the
    north star's "Hungarian/Sinkhorn matching" mode. Fewer device
    steps than the plain wave solver on big backlogs; placements stay
    valid; the scan path remains the parity referee."""
    from kubernetes_tpu.ops import device_snapshot
    from kubernetes_tpu.ops.sinkhorn import sinkhorn_assignments

    sanitizer.check_blocking("jit-dispatch", "schedule_backlog_sinkhorn")
    with tracing.phase("lower", pods=len(pending)):
        snap = build_snapshot(
            pending, nodes, assigned_pods=assigned, services=services
        )
    with tracing.phase("upload"):
        dsnap = device_snapshot(snap, mesh=mesh)
    assignment, _waves = sinkhorn_assignments(dsnap)
    with tracing.phase("readback"):
        names = snap.nodes.names
        return [names[i] if i >= 0 else None for i in assignment]


def schedule_backlog_gang_scalar(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    groups=(),
    spec: Optional[AlgorithmSpec] = None,
):
    """Gang-accepting scalar backlog solve — the parity fallback AND
    yardstick for the device gang path. Returns (destinations,
    accepted_groups, rejected_groups); see scheduler.gang.gang_solve."""
    from kubernetes_tpu.scheduler.gang import gang_solve

    def solver(p, n, a, s):
        return schedule_backlog_scalar(p, n, a, s, spec=spec)

    return gang_solve(solver, pending, nodes, assigned, services, groups)


def schedule_backlog_gang_tpu(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    groups=(),
    mesh=None,
    spec: Optional[AlgorithmSpec] = None,
):
    """Gang-accepting device backlog solve: the scan solver per round,
    group acceptance via the masked segment reduction on device
    (ops.pipeline.gang_member_counts_device). Accepted-group parity
    with schedule_backlog_gang_scalar is inherited from the underlying
    solvers' decision parity — both run the identical acceptance loop."""
    from kubernetes_tpu.ops.pipeline import gang_member_counts_device
    from kubernetes_tpu.scheduler.gang import gang_solve

    def solver(p, n, a, s):
        return schedule_backlog_tpu(p, n, a, s, mesh=mesh, spec=spec)

    return gang_solve(
        solver, pending, nodes, assigned, services, groups,
        counts_fn=gang_member_counts_device,
    )


def preempt_backlog_scalar(
    preemptors: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
):
    """Scalar victim selection — the preemption parity yardstick AND
    the fallback when the device path errors. Implements the canonical
    rule from ops/preemption.py independently (pure python, no device):
    per node, victims are the shortest (priority asc, arrival asc)
    prefix of strictly-dominated live pods whose freed cpu/mem/slots
    fit the preemptor; nodes rank by (max victim priority, count, node
    index); preemptors run highest-priority-first, each grant charging
    the post-eviction node state seen by the next. Returns decisions
    aligned with `preemptors` (None = no preemption granted)."""
    from kubernetes_tpu.models.columnar import (
        mem_to_mib_ceil,
        node_is_ready,
        pod_resource_limits,
    )
    from kubernetes_tpu.models.objects import (
        pod_can_preempt,
        pod_full_key,
        pod_is_terminating,
        pod_priority,
    )
    from kubernetes_tpu.ops.preemption import PreemptionDecision

    INF = float("inf")
    nodes = list(nodes)
    index = {n.metadata.name: j for j, n in enumerate(nodes)}
    free = []  # per node [cpu, mem, pods]
    for node in nodes:
        cap = node.status.capacity or {}
        cpu = cap.get("cpu").milli_value() if cap.get("cpu") else 0
        mem = cap.get("memory").value() // (1024**2) if cap.get("memory") else 0
        pods = cap.get("pods").value() if cap.get("pods") else 0
        free.append([cpu or INF, mem or INF, pods or INF])
    victims = []  # (prio, arrival_idx, node_j, cpu, mem, key, alive)
    for i, pod in enumerate(assigned):
        j = index.get(pod.spec.node_name, -1)
        if j < 0:
            continue
        cpu, mem = pod_resource_limits(pod)
        cpu, mem = float(cpu), float(mem_to_mib_ceil(mem))
        free[j][0] -= cpu
        free[j][1] -= mem
        free[j][2] -= 1
        if pod.status.phase in ("Succeeded", "Failed") or pod_is_terminating(pod):
            continue
        victims.append(
            [pod_priority(pod), i, j, cpu, mem, pod_full_key(pod), True]
        )
    out = [None] * len(preemptors)
    for i in sorted(
        range(len(preemptors)),
        key=lambda t: (-pod_priority(preemptors[t]), t),
    ):
        pod = preemptors[i]
        prio = pod_priority(pod)
        if prio <= 0 or not pod_can_preempt(pod):
            continue
        cpu, mem = pod_resource_limits(pod)
        cpu, mem = float(cpu), float(mem_to_mib_ceil(mem))
        sel = pod.spec.node_selector or {}
        best = None
        for j, node in enumerate(nodes):
            if not node_is_ready(node) or node.spec.unschedulable:
                continue
            labels = node.metadata.labels or {}
            if any(labels.get(k) != v for k, v in sel.items()):
                continue
            f_cpu, f_mem, f_pods = free[j]
            if f_cpu >= cpu and f_mem >= mem and f_pods >= 1:
                continue  # fits without eviction: not a preemption case
            prefix = []
            for v in sorted(
                (v for v in victims if v[6] and v[2] == j and v[0] < prio),
                key=lambda v: (v[0], v[1]),
            ):
                prefix.append(v)
                f_cpu += v[3]
                f_mem += v[4]
                f_pods += 1
                if f_cpu >= cpu and f_mem >= mem and f_pods >= 1:
                    score = (prefix[-1][0], len(prefix), j)
                    if best is None or score < best[0]:
                        best = (score, j, list(prefix))
                    break
        if best is None:
            continue
        _, j, prefix = best
        for v in prefix:
            v[6] = False
            free[j][0] += v[3]
            free[j][1] += v[4]
            free[j][2] += 1
        free[j][0] -= cpu
        free[j][1] -= mem
        free[j][2] -= 1
        out[i] = PreemptionDecision(
            key=pod_full_key(pod),
            node=nodes[j].metadata.name,
            victims=tuple(v[5] for v in prefix),
        )
    return out


def preempt_backlog_tpu(
    preemptors: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
):
    """Device victim selection (ops/preemption.py): same decisions as
    preempt_backlog_scalar — 100% victim-set parity is the contract
    (tests/test_solver_parity.py)."""
    from kubernetes_tpu.ops.preemption import (
        build_preemption_problem,
        solve_preemption_device,
    )

    problem = build_preemption_problem(nodes, assigned)
    return solve_preemption_device(problem, preemptors)


def parity_report(
    scalar: Sequence[Optional[str]], batch: Sequence[Optional[str]]
) -> Tuple[float, List[int]]:
    """Fraction of identical decisions + indices of mismatches."""
    assert len(scalar) == len(batch)
    mismatches = [i for i, (a, b) in enumerate(zip(scalar, batch)) if a != b]
    parity = 1.0 - len(mismatches) / max(1, len(scalar))
    return parity, mismatches
