"""Predicate/priority plugin registry, algorithm providers, policy files.

Reference: plugin/pkg/scheduler/factory/plugins.go (registries),
plugin/pkg/scheduler/algorithmprovider/defaults/defaults.go (default
provider), plugin/pkg/scheduler/api/types.go (policy file schema).

Factories receive PluginFactoryArgs so predicates can capture listers,
mirroring the reference's PluginFactoryArgs{PodLister, ServiceLister,
NodeLister, NodeInfo}.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from kubernetes_tpu.models.algspec import (
    AlgorithmSpec,
    spec_from_keys,
    spec_from_policy,
)
from kubernetes_tpu.scheduler import predicates as preds
from kubernetes_tpu.scheduler import priorities as prios
from kubernetes_tpu.scheduler.types import PriorityConfig


@dataclass
class PluginFactoryArgs:
    pod_lister: object
    service_lister: object
    node_lister: object


FitPredicateFactory = Callable[[PluginFactoryArgs], Callable]
PriorityFunctionFactory = Callable[[PluginFactoryArgs], Callable]


_lock = threading.Lock()
_fit_predicates: Dict[str, FitPredicateFactory] = {}
_priority_functions: Dict[str, PriorityFunctionFactory] = {}
_algorithm_providers: Dict[str, "AlgorithmProvider"] = {}


@dataclass
class AlgorithmProvider:
    predicate_keys: List[str]
    priority_keys: Dict[str, int]  # name -> weight


def register_fit_predicate(name: str, factory: FitPredicateFactory) -> str:
    with _lock:
        _fit_predicates[name] = factory
    return name


def register_priority_function(name: str, factory: PriorityFunctionFactory) -> str:
    with _lock:
        _priority_functions[name] = factory
    return name


def register_algorithm_provider(
    name: str, predicate_keys: Sequence[str], priority_keys: Dict[str, int]
) -> str:
    with _lock:
        _algorithm_providers[name] = AlgorithmProvider(
            list(predicate_keys), dict(priority_keys)
        )
    return name


def get_algorithm_provider(name: str) -> AlgorithmProvider:
    with _lock:
        if name not in _algorithm_providers:
            raise KeyError(f"algorithm provider {name!r} not registered")
        return _algorithm_providers[name]


def get_fit_predicates(keys: Sequence[str], args: PluginFactoryArgs) -> Dict[str, Callable]:
    with _lock:
        missing = [k for k in keys if k not in _fit_predicates]
        if missing:
            raise KeyError(f"fit predicates not registered: {missing}")
        return {k: _fit_predicates[k](args) for k in keys}


def get_priority_configs(
    keys: Dict[str, int], args: PluginFactoryArgs
) -> List[PriorityConfig]:
    with _lock:
        missing = [k for k in keys if k not in _priority_functions]
        if missing:
            raise KeyError(f"priority functions not registered: {missing}")
        return [
            PriorityConfig(function=_priority_functions[k](args), weight=w)
            for k, w in keys.items()
            if w != 0
        ]


# ---------------------------------------------------------------------------
# Built-in registrations (reference: defaults.go:29-79 init()).
# ---------------------------------------------------------------------------

register_fit_predicate("PodFitsPorts", lambda args: preds.pod_fits_ports)
register_fit_predicate(
    "PodFitsResources", lambda args: preds.ResourceFit(args.node_lister)
)
register_fit_predicate("NoDiskConflict", lambda args: preds.no_disk_conflict)
register_fit_predicate(
    "MatchNodeSelector", lambda args: preds.NodeSelectorMatches(args.node_lister)
)
register_fit_predicate("HostName", lambda args: preds.pod_fits_host)

register_priority_function(
    "LeastRequestedPriority", lambda args: prios.least_requested_priority
)
register_priority_function(
    "BalancedResourceAllocation", lambda args: prios.balanced_resource_allocation
)
register_priority_function(
    "ServiceSpreadingPriority",
    lambda args: prios.ServiceSpread(args.service_lister),
)
register_priority_function("EqualPriority", lambda args: prios.equal_priority)

DEFAULT_PROVIDER = "DefaultProvider"

register_algorithm_provider(
    DEFAULT_PROVIDER,
    # defaults.go:38-48
    ["PodFitsPorts", "PodFitsResources", "NoDiskConflict", "MatchNodeSelector", "HostName"],
    # defaults.go:51-60
    {
        "LeastRequestedPriority": 1,
        "BalancedResourceAllocation": 1,
        "ServiceSpreadingPriority": 1,
    },
)


def default_predicates(args: PluginFactoryArgs) -> Dict[str, Callable]:
    provider = get_algorithm_provider(DEFAULT_PROVIDER)
    return get_fit_predicates(provider.predicate_keys, args)


def default_priorities(args: PluginFactoryArgs) -> List[PriorityConfig]:
    provider = get_algorithm_provider(DEFAULT_PROVIDER)
    return get_priority_configs(provider.priority_keys, args)


# ---------------------------------------------------------------------------
# Policy file support (reference: plugin/pkg/scheduler/api/types.go:25-104).
# ---------------------------------------------------------------------------


def build_from_policy(policy: dict, args: PluginFactoryArgs):
    """Construct (predicates, priorities) from a policy document:

    {"kind": "Policy", "predicates": [{"name": ..., "argument": {...}}],
     "priorities": [{"name": ..., "weight": N, "argument": {...}}]}

    Custom arguments mirror the reference: serviceAffinity{labels},
    labelsPresence{labels, presence}, serviceAntiAffinity{label},
    labelPreference{label, presence}.
    """
    predicates: Dict[str, Callable] = {}
    for p in policy.get("predicates", []):
        name = p["name"]
        arg = p.get("argument") or {}
        if "serviceAffinity" in arg:
            predicates[name] = preds.ServiceAffinity(
                args.pod_lister,
                args.service_lister,
                args.node_lister,
                arg["serviceAffinity"].get("labels", []),
            )
        elif "labelsPresence" in arg:
            predicates[name] = preds.NodeLabelChecker(
                args.node_lister,
                arg["labelsPresence"].get("labels", []),
                arg["labelsPresence"].get("presence", True),
            )
        else:
            predicates.update(get_fit_predicates([name], args))
    priorities: List[PriorityConfig] = []
    for p in policy.get("priorities", []):
        name = p["name"]
        weight = p.get("weight", 1)
        arg = p.get("argument") or {}
        if "serviceAntiAffinity" in arg:
            fn = prios.ServiceAntiAffinity(
                args.service_lister, arg["serviceAntiAffinity"].get("label", "")
            )
            priorities.append(PriorityConfig(function=fn, weight=weight))
        elif "labelPreference" in arg:
            fn = prios.NodeLabelPrioritizer(
                arg["labelPreference"].get("label", ""),
                arg["labelPreference"].get("presence", True),
            )
            priorities.append(PriorityConfig(function=fn, weight=weight))
        else:
            priorities.extend(get_priority_configs({name: weight}, args))
    return predicates, priorities


# ---------------------------------------------------------------------------
# AlgorithmSpec bridge: the spec is the shared source of truth between
# this scalar construction and the TPU lowering (models.algspec) —
# the batch daemon consults it to pick device vs scalar execution.
# ---------------------------------------------------------------------------


def spec_for_provider(name: str) -> AlgorithmSpec:
    provider = get_algorithm_provider(name)
    return spec_from_keys(provider.predicate_keys, provider.priority_keys)


def spec_for_policy(policy: dict) -> AlgorithmSpec:
    return spec_from_policy(policy)


def build_from_spec(spec: AlgorithmSpec, args: PluginFactoryArgs):
    """Construct the scalar (predicates, priorities) from a spec.
    Argumented kinds build their classes directly; plain kinds resolve
    through the registry, so user-registered custom plugins still run
    on the scalar path even though they can't lower to the device."""
    predicates: Dict[str, Callable] = {}
    for i, p in enumerate(spec.predicates):
        if p.kind == "ServiceAffinity":
            predicates[f"ServiceAffinity#{i}"] = preds.ServiceAffinity(
                args.pod_lister,
                args.service_lister,
                args.node_lister,
                list(p.labels),
            )
        elif p.kind == "NodeLabelPresence":
            predicates[f"NodeLabelPresence#{i}"] = preds.NodeLabelChecker(
                args.node_lister, list(p.labels), p.presence
            )
        else:
            predicates.update(get_fit_predicates([p.kind], args))
    priorities: List[PriorityConfig] = []
    for p in spec.priorities:
        if p.weight == 0:
            continue
        if p.kind == "ServiceAntiAffinity":
            priorities.append(
                PriorityConfig(
                    function=prios.ServiceAntiAffinity(
                        args.service_lister, p.label
                    ),
                    weight=p.weight,
                )
            )
        elif p.kind == "LabelPreference":
            priorities.append(
                PriorityConfig(
                    function=prios.NodeLabelPrioritizer(p.label, p.presence),
                    weight=p.weight,
                )
            )
        else:
            priorities.extend(get_priority_configs({p.kind: p.weight}, args))
    return predicates, priorities
