"""Scheduler: scalar reference path (the parity oracle) + TPU batch path.

Reference: plugin/pkg/scheduler/. The scalar path mirrors the
reference's predicate/priority formulas exactly (including integer
truncation and greedy capacity re-simulation) and serves as the
semantic oracle; the TPU path solves the same problem as dense
pod x node matrices (kubernetes_tpu.ops) and is checked against the
oracle at >=99% decision parity.
"""

from kubernetes_tpu.scheduler.types import (
    HostPriority,
    StaticNodeLister,
    StaticPodLister,
    StaticServiceLister,
)
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler, NoNodesError
from kubernetes_tpu.scheduler.plugins import (
    default_predicates,
    default_priorities,
    get_algorithm_provider,
    register_algorithm_provider,
    register_fit_predicate,
    register_priority_function,
)

__all__ = [
    "HostPriority",
    "StaticNodeLister",
    "StaticPodLister",
    "StaticServiceLister",
    "GenericScheduler",
    "FitError",
    "NoNodesError",
    "default_predicates",
    "default_priorities",
    "get_algorithm_provider",
    "register_algorithm_provider",
    "register_fit_predicate",
    "register_priority_function",
]
