"""Generic scheduler: filter -> score -> select.

Reference: plugin/pkg/scheduler/generic_scheduler.go:60-171. One
deliberate deviation: selectHost breaks score ties by picking the
lowest node index in list order (optionally seeded-random like the
reference's `random.Int() % len(hosts)`), so the scalar and TPU batch
paths are bit-for-bit comparable. The reference randomizes ties.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Set

from kubernetes_tpu.models.objects import Pod
from kubernetes_tpu.scheduler.types import (
    FitPredicate,
    HostPriority,
    PriorityConfig,
    StaticNodeLister,
    StaticPodLister,
    map_pods_to_machines,
)
from kubernetes_tpu.scheduler.priorities import equal_priority


class NoNodesError(Exception):
    """ErrNoNodesAvailable."""


class FitError(Exception):
    """No node fits; carries per-node failed predicate names."""

    def __init__(self, pod: Pod, failed_predicates: Dict[str, Set[str]]):
        self.pod = pod
        self.failed_predicates = failed_predicates
        super().__init__(
            f"pod {pod.metadata.name!r} fits on no node: "
            + "; ".join(
                f"{node}: {sorted(names)}"
                for node, names in sorted(failed_predicates.items())
            )
        )


def find_nodes_that_fit(
    pod: Pod,
    pod_lister: StaticPodLister,
    predicates: Dict[str, FitPredicate],
    nodes: List,
):
    """generic_scheduler.go:106-134 — the O(pods x nodes x predicates)
    hot loop the TPU path matricizes."""
    filtered = []
    machine_to_pods = map_pods_to_machines(pod_lister)
    failed: Dict[str, Set[str]] = {}
    for node in nodes:
        name = node.metadata.name
        fits = True
        for pred_name, predicate in predicates.items():
            if not predicate(pod, machine_to_pods.get(name, []), name):
                fits = False
                failed.setdefault(name, set()).add(pred_name)
                break
        if fits:
            filtered.append(node)
    return filtered, failed


def prioritize_nodes(
    pod: Pod,
    pod_lister: StaticPodLister,
    priority_configs: Sequence[PriorityConfig],
    minion_lister: StaticNodeLister,
) -> List[HostPriority]:
    """generic_scheduler.go:142-171: weighted sum of per-function scores."""
    if not priority_configs:
        return equal_priority(pod, pod_lister, minion_lister)
    combined: Dict[str, int] = {}
    for config in priority_configs:
        if config.weight == 0:
            continue
        for entry in config.function(pod, pod_lister, minion_lister):
            combined[entry.host] = combined.get(entry.host, 0) + entry.score * config.weight
    return [HostPriority(host, score) for host, score in combined.items()]


class GenericScheduler:
    def __init__(
        self,
        predicates: Dict[str, FitPredicate],
        prioritizers: Sequence[PriorityConfig],
        pod_lister: StaticPodLister,
        rng: Optional[random.Random] = None,
    ):
        self.predicates = predicates
        self.prioritizers = list(prioritizers)
        self.pod_lister = pod_lister
        self.rng = rng  # None => deterministic first-best tie-break

    def schedule(self, pod: Pod, minion_lister: StaticNodeLister) -> str:
        nodes = minion_lister.list()
        if not nodes:
            raise NoNodesError()
        filtered, failed = find_nodes_that_fit(
            pod, self.pod_lister, self.predicates, nodes
        )
        priority_list = prioritize_nodes(
            pod, self.pod_lister, self.prioritizers, StaticNodeLister(filtered)
        )
        if not priority_list:
            raise FitError(pod, failed)
        return self.select_host(priority_list)

    def select_host(self, priority_list: List[HostPriority]) -> str:
        """generic_scheduler.go:90-102; ties broken deterministically by
        list order unless an rng is supplied."""
        if not priority_list:
            raise ValueError("empty priority list")
        best = max(e.score for e in priority_list)
        hosts = [e.host for e in priority_list if e.score == best]
        if self.rng is not None:
            return hosts[self.rng.randrange(len(hosts))]
        return hosts[0]
