"""Scheduler interfaces: predicate/priority signatures and listers.

Reference: plugin/pkg/scheduler/algorithm/{types.go,listers.go,
scheduler_interface.go}.

FitPredicate(pod, existing_pods_on_node, node_name) -> bool
PriorityFunction(pod, pod_lister, minion_lister) -> [HostPriority]
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models.objects import Node, Pod, Service

FitPredicate = Callable[[Pod, List[Pod], str], bool]


@dataclass
class HostPriority:
    host: str
    score: int


PriorityFunction = Callable[
    [Pod, "StaticPodLister", "StaticNodeLister"], List[HostPriority]
]


@dataclass
class PriorityConfig:
    function: PriorityFunction
    weight: int = 1


class StaticPodLister:
    """PodLister over a fixed list (reference: FakePodLister; the real
    one wraps an informer store — daemon.py builds those)."""

    def __init__(self, pods: Sequence[Pod]):
        self.pods = list(pods)

    def list(self, selector: Optional[labelpkg.Selector] = None) -> List[Pod]:
        if selector is None or selector.empty():
            return list(self.pods)
        return [p for p in self.pods if selector.matches(p.metadata.labels)]


class StaticNodeLister:
    """MinionLister (reference: FakeMinionLister)."""

    def __init__(self, nodes: Sequence[Node]):
        self.nodes = list(nodes)

    def list(self) -> List[Node]:
        return list(self.nodes)

    def get(self, name: str) -> Node:
        for n in self.nodes:
            if n.metadata.name == name:
                return n
        raise KeyError(f"node {name!r} not found")


class StaticServiceLister:
    """ServiceLister with GetPodServices (reference: listers.go)."""

    def __init__(self, services: Sequence[Service]):
        self.services = list(services)

    def list(self) -> List[Service]:
        return list(self.services)

    def get_pod_services(self, pod: Pod) -> List[Service]:
        out = []
        for svc in self.services:
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector
            if not sel:
                continue
            if labelpkg.selector_from_set(sel).matches(pod.metadata.labels or {}):
                out.append(svc)
        return out


def map_pods_to_machines(pod_lister: StaticPodLister) -> Dict[str, List[Pod]]:
    """Pivot all pods into host -> pods, skipping terminal phases.

    Reference: MapPodsToMachines + filterNonRunningPods
    (predicates.go:361-392).
    """
    machine_to_pods: Dict[str, List[Pod]] = {}
    for pod in pod_lister.list():
        if pod.status.phase in ("Succeeded", "Failed"):
            continue
        machine_to_pods.setdefault(pod.spec.node_name, []).append(pod)
    return machine_to_pods
