"""Scalar priority functions — exact reference semantics including
integer truncation.

Reference: plugin/pkg/scheduler/algorithm/priorities/{priorities.go,
spreading.go}. Scores are ints 0-10; weighted sums combine them
(generic_scheduler.go:151-166).
"""

from __future__ import annotations

import math
from typing import Dict, List

from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models.objects import Node, Pod, RESOURCE_CPU, RESOURCE_MEMORY
from kubernetes_tpu.scheduler.types import (
    HostPriority,
    StaticNodeLister,
    StaticPodLister,
    map_pods_to_machines,
)


def _limits_total(pods: List[Pod], pod: Pod) -> tuple:
    """Sum container limits over existing pods + the incoming pod
    (calculateOccupancy, priorities.go:44-58)."""
    total_cpu = 0
    total_mem = 0
    for existing in pods:
        for c in existing.spec.containers:
            limits = c.resources.limits
            if RESOURCE_CPU in limits:
                total_cpu += limits[RESOURCE_CPU].milli_value()
            if RESOURCE_MEMORY in limits:
                total_mem += limits[RESOURCE_MEMORY].value()
    for c in pod.spec.containers:
        limits = c.resources.limits
        if RESOURCE_CPU in limits:
            total_cpu += limits[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in limits:
            total_mem += limits[RESOURCE_MEMORY].value()
    return total_cpu, total_mem


def _node_capacity(node: Node) -> tuple:
    cap = node.status.capacity or {}
    cpu = cap[RESOURCE_CPU].milli_value() if RESOURCE_CPU in cap else 0
    mem = cap[RESOURCE_MEMORY].value() if RESOURCE_MEMORY in cap else 0
    return cpu, mem


def calculate_score(requested: int, capacity: int) -> int:
    """(cap - req) * 10 / cap with integer truncation; 0 when cap == 0
    or req > cap (priorities.go:31-40)."""
    if capacity == 0:
        return 0
    if requested > capacity:
        return 0
    return ((capacity - requested) * 10) // capacity


def least_requested_priority(
    pod: Pod, pod_lister: StaticPodLister, minion_lister: StaticNodeLister
) -> List[HostPriority]:
    """LeastRequestedPriority (priorities.go:83-95): average of cpu and
    memory scores, integer-truncated."""
    pods_to_machines = map_pods_to_machines(pod_lister)
    out = []
    for node in minion_lister.list():
        total_cpu, total_mem = _limits_total(
            pods_to_machines.get(node.metadata.name, []), pod
        )
        cap_cpu, cap_mem = _node_capacity(node)
        cpu_score = calculate_score(total_cpu, cap_cpu)
        mem_score = calculate_score(total_mem, cap_mem)
        out.append(
            HostPriority(node.metadata.name, (cpu_score + mem_score) // 2)
        )
    return out


def _fraction_of_capacity(requested: int, capacity: int) -> float:
    if capacity == 0:
        return 1.0
    return float(requested) / float(capacity)


def balanced_resource_allocation(
    pod: Pod, pod_lister: StaticPodLister, minion_lister: StaticNodeLister
) -> List[HostPriority]:
    """BalancedResourceAllocation (priorities.go:146-205):
    int(10 - |cpuFraction - memFraction| * 10); 0 if either >= 1."""
    pods_to_machines = map_pods_to_machines(pod_lister)
    out = []
    for node in minion_lister.list():
        total_cpu, total_mem = _limits_total(
            pods_to_machines.get(node.metadata.name, []), pod
        )
        cap_cpu, cap_mem = _node_capacity(node)
        cpu_frac = _fraction_of_capacity(total_cpu, cap_cpu)
        mem_frac = _fraction_of_capacity(total_mem, cap_mem)
        if cpu_frac >= 1 or mem_frac >= 1:
            score = 0
        else:
            diff = abs(cpu_frac - mem_frac)
            score = int(10 - diff * 10)
        out.append(HostPriority(node.metadata.name, score))
    return out


def equal_priority(
    pod: Pod, pod_lister: StaticPodLister, minion_lister: StaticNodeLister
) -> List[HostPriority]:
    """EqualPriority (generic_scheduler.go:176-190): all nodes score 1."""
    return [HostPriority(n.metadata.name, 1) for n in minion_lister.list()]


class NodeLabelPrioritizer:
    """CalculateNodeLabelPriority (priorities.go:113-138): 10 when the
    label's presence matches the preference, else 0."""

    def __init__(self, label: str, presence: bool):
        self.label = label
        self.presence = presence

    def __call__(
        self, pod: Pod, pod_lister: StaticPodLister, minion_lister: StaticNodeLister
    ) -> List[HostPriority]:
        out = []
        for minion in minion_lister.list():
            exists = self.label in (minion.metadata.labels or {})
            success = (exists and self.presence) or (not exists and not self.presence)
            out.append(HostPriority(minion.metadata.name, 10 if success else 0))
        return out


def _ns_service_pods(pod: Pod, pod_lister, service_lister) -> List[Pod]:
    """First matching service's pods in the pod's namespace
    (spreading.go:44-57)."""
    services = service_lister.get_pod_services(pod)
    if not services:
        return []
    selector = labelpkg.selector_from_set(services[0].spec.selector)
    return [
        p
        for p in pod_lister.list(selector)
        if p.metadata.namespace == pod.metadata.namespace
    ]


class ServiceSpread:
    """CalculateSpreadPriority (spreading.go:38-87):
    10 * (maxCount - count) / maxCount, float32 then int-truncated."""

    def __init__(self, service_lister):
        self.service_lister = service_lister

    def __call__(
        self, pod: Pod, pod_lister: StaticPodLister, minion_lister: StaticNodeLister
    ) -> List[HostPriority]:
        ns_service_pods = _ns_service_pods(pod, pod_lister, self.service_lister)
        counts: Dict[str, int] = {}
        max_count = 0
        for p in ns_service_pods:
            counts[p.spec.node_name] = counts.get(p.spec.node_name, 0) + 1
            max_count = max(max_count, counts[p.spec.node_name])
        out = []
        for minion in minion_lister.list():
            fscore = 10.0
            if max_count > 0:
                fscore = 10 * (
                    (max_count - counts.get(minion.metadata.name, 0)) / max_count
                )
            out.append(HostPriority(minion.metadata.name, int(fscore)))
        return out


class ServiceAntiAffinity:
    """CalculateAntiAffinityPriority (spreading.go:105-169): spread
    service pods across values of a node label; unlabeled nodes get 0."""

    def __init__(self, service_lister, label: str):
        self.service_lister = service_lister
        self.label = label

    def __call__(
        self, pod: Pod, pod_lister: StaticPodLister, minion_lister: StaticNodeLister
    ) -> List[HostPriority]:
        ns_service_pods = _ns_service_pods(pod, pod_lister, self.service_lister)

        other_minions: List[str] = []
        labeled_minions: Dict[str, str] = {}
        for minion in minion_lister.list():
            node_labels = minion.metadata.labels or {}
            if self.label in node_labels:
                labeled_minions[minion.metadata.name] = node_labels[self.label]
            else:
                other_minions.append(minion.metadata.name)

        pod_counts: Dict[str, int] = {}
        for p in ns_service_pods:
            label = labeled_minions.get(p.spec.node_name)
            if label is None:
                continue
            pod_counts[label] = pod_counts.get(label, 0) + 1

        num_service_pods = len(ns_service_pods)
        out = []
        for minion in labeled_minions:
            fscore = 10.0
            if num_service_pods > 0:
                fscore = 10 * (
                    (num_service_pods - pod_counts.get(labeled_minions[minion], 0))
                    / num_service_pods
                )
            out.append(HostPriority(minion, int(fscore)))
        for minion in other_minions:
            out.append(HostPriority(minion, 0))
        return out
