"""Warm-standby scheduler: lease-gated failover without the cold start.

A cold scheduler failover pays three latencies in series: the LIST+watch
resync of every informer, the SolverSession build (host staging + device
upload), and the first bucket compile. PR 12 made the session always-
resident for the LIVE daemon; this module keeps the SAME state resident
on a follower. The standby runs its informers hot (started + synced) and
holds a prewarmed-but-NOT-started ``IncrementalBatchScheduler``: watch
deltas accumulate in the daemon's event queue via the
``SchedulerConfig.cluster_events`` hook, so the device-resident session
is at most one replay behind the cluster. Activation is then just
``daemon.start()`` — the first tick drains the accumulated deltas
(handlers are idempotent) and solves the backlog immediately, which is
what puts failover-to-first-bind under the 1 s SLO
(``utils/slo.py: failover_to_first_bind_s``).

``HAScheduler`` ties the standby to a fencing lease (utils/lease.py):
``on_elected`` activates, ``on_lost`` kills the daemon abruptly (a
deposed leader must stop binding NOW — its fencing token is stale) and
rebuilds a fresh warm standby so the process can stand for election
again. The kill-then-rebuild shape follows utils/leaderelect.py's
HAHotStandby ``_up``/``_down`` idempotent factory pattern.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.scheduler.daemon import (
    IncrementalBatchScheduler,
    SchedulerConfig,
)
from kubernetes_tpu.utils import metrics
from kubernetes_tpu.utils.lease import LeaseClient, LeaseElector

_LOG = logging.getLogger("kubernetes_tpu.scheduler.standby")

#: Seconds from lease activation to the standby daemon running — the
#: control-plane half of failover_to_first_bind_s (the rest is the
#: first tick's solve + bind, measured end-to-end by bench/soak).
_ACTIVATION_LATENCY = metrics.DEFAULT.summary(
    "scheduler_standby_activation_seconds",
    "Warm-standby activation latency (lease grant to daemon running)",
)


class WarmStandbyScheduler:
    """A prewarmed-but-idle IncrementalBatchScheduler.

    Lifecycle: ``prewarm()`` starts the informers, waits for sync and
    builds the device session; ``activate()`` starts the solve loop;
    ``kill()``/``stop()`` tear down. Each instance activates at most
    once — a deposed leader builds a FRESH standby (the killed daemon's
    session may hold charges for binds that never landed)."""

    def __init__(
        self,
        client,
        sync_timeout: float = 10.0,
        daemon_factory: Optional[
            Callable[[SchedulerConfig], IncrementalBatchScheduler]
        ] = None,
        **config_kw,
    ):
        self.client = client
        self.sync_timeout = sync_timeout
        # raw cache default: the incremental daemon never decodes
        # scheduled pods it discards by key (SchedulerConfig docstring).
        config_kw.setdefault("raw_scheduled_cache", True)
        self.config = SchedulerConfig(client, **config_kw)
        # Daemon construction installs the cluster_events hook — MUST
        # precede config.start() so no delta is missed.
        if daemon_factory is not None:
            self.daemon = daemon_factory(self.config)
        else:
            self.daemon = IncrementalBatchScheduler(self.config)
        self._warm = False
        self._active = False
        self.activated_mono: Optional[float] = None

    @property
    def warm(self) -> bool:
        return self._warm

    @property
    def active(self) -> bool:
        return self._active

    def prewarm(self) -> "WarmStandbyScheduler":
        """Start informers, sync, build the device session. Watch
        deltas from here on queue in the daemon (not applied — the
        daemon is not started) and replay on activation."""
        if self._warm:
            return self
        self.config.start()
        if not self.config.wait_for_sync(self.sync_timeout):
            raise TimeoutError("standby informers failed to sync")
        # Session built from the freshly synced caches; deltas that
        # raced the build replay idempotently at activation.
        self.daemon.prewarm()
        self._warm = True
        return self

    def activate(self) -> IncrementalBatchScheduler:
        """Start the solve loop. Idempotent; returns the live daemon."""
        if self._active:
            return self.daemon
        if not self._warm:
            self.prewarm()
        self.daemon.start()
        self._active = True
        self.activated_mono = time.monotonic()
        return self.daemon

    def stop(self) -> None:
        """Graceful teardown (flushes the commit pipeline)."""
        if self._active:
            self.daemon.stop()
            self._active = False
        if self._warm:
            self.config.stop()
            self._warm = False

    def kill(self) -> None:
        """Abrupt teardown — the deposed-leader / chaos path. Queued
        commits are dropped (daemon.kill()); a dead leader binds
        nothing after its lease is gone."""
        if self._active:
            self.daemon.kill()
            self._active = False
        if self._warm:
            try:
                self.config.stop()
            except Exception:
                _LOG.debug("standby config stop failed", exc_info=True)
            self._warm = False


class HAScheduler:
    """Lease-elected scheduler with a warm standby behind it.

    Run one per control-plane replica. Exactly one replica's lease
    acquisition succeeds (fencing token bumps per election —
    ``leader_elections_total{tier="scheduler"}``); that replica
    activates its prewarmed daemon. On lease loss the daemon is killed
    abruptly and a fresh standby is prewarmed, so the replica re-enters
    the election warm."""

    def __init__(
        self,
        client,
        identity: str,
        lease_name: str = "kt-scheduler",
        lease_duration: float = 5.0,
        renew_period: float = 1.0,
        retry_period: float = 1.0,
        standby_factory: Optional[
            Callable[[], WarmStandbyScheduler]
        ] = None,
        on_activated: Optional[Callable[[int], None]] = None,
    ):
        self.client = client
        self.identity = identity
        self._factory = standby_factory or (
            lambda: WarmStandbyScheduler(client)
        )
        self._on_activated = on_activated or (lambda _t: None)
        self.lease = LeaseClient(
            client,
            lease_name,
            identity,
            tier="scheduler",
            lease_duration=lease_duration,
        )
        self.elector = LeaseElector(
            self.lease,
            renew_period=renew_period,
            retry_period=retry_period,
            on_elected=self._elected,
            on_lost=self._deposed,
        )
        self.standby: Optional[WarmStandbyScheduler] = None
        self.token: Optional[int] = None
        # Serializes elected/deposed transitions against start/stop —
        # elector callbacks run on the elector thread.
        self._transition = threading.Lock()
        self._stopping = False

    @property
    def is_leader(self) -> bool:
        return self.token is not None

    @property
    def daemon(self) -> Optional[IncrementalBatchScheduler]:
        sb = self.standby
        return sb.daemon if sb is not None and sb.active else None

    def start(self) -> "HAScheduler":
        """Prewarm the standby FIRST, then stand for election — a
        replica that wins before it is warm would pay the cold start
        the standby exists to avoid."""
        with self._transition:
            self._stopping = False
            if self.standby is None:
                self.standby = self._factory().prewarm()
        self.elector.start()
        return self

    def stop(self) -> None:
        with self._transition:
            self._stopping = True
        self.elector.stop()  # fires on_lost if leading
        with self._transition:
            sb, self.standby = self.standby, None
            if sb is not None:
                sb.stop()

    # -- elector callbacks (elector thread) ---------------------------

    def _elected(self, token: int) -> None:
        with self._transition:
            if self._stopping:
                return
            self.token = token
            sb = self.standby
            if sb is None:
                sb = self.standby = self._factory().prewarm()
            granted = time.monotonic()
            sb.activate()
            _ACTIVATION_LATENCY.observe(time.monotonic() - granted)
            _LOG.info(
                "%s: scheduler leadership acquired (token %d); warm "
                "standby activated", self.identity, token,
            )
        try:
            self._on_activated(token)
        except Exception:
            _LOG.debug("on_activated callback failed", exc_info=True)

    def _deposed(self) -> None:
        with self._transition:
            self.token = None
            sb, self.standby = self.standby, None
            if sb is not None:
                # Stale fencing token: stop binding NOW (abrupt).
                sb.kill()
            _LOG.warning(
                "%s: scheduler leadership lost; daemon killed",
                self.identity,
            )
            if self._stopping:
                return
            # Re-enter the election warm.
            try:
                self.standby = self._factory().prewarm()
            except Exception:
                _LOG.warning(
                    "%s: standby rebuild failed; will retry on next "
                    "election", self.identity, exc_info=True,
                )
