"""Scalar fit predicates — exact reference semantics.

Reference: plugin/pkg/scheduler/algorithm/predicates/predicates.go.
These are the parity oracle for the TPU matrix path; every behavioral
quirk of the original is preserved on purpose:

- resources come from container LIMITS (getResourceRequest,
  predicates.go:106-114 — v0.19 predates requests-based scheduling);
- a zero-request pod fits iff the node has pod-count headroom
  (predicates.go:146-148);
- capacity checking greedily re-simulates packing the existing pods in
  order, so pods that overflow an overcommitted node stop counting
  (CheckPodsExceedingCapacity, predicates.go:116-136);
- zero capacity for a resource means "unlimited" for that resource but
  scores 0 later (predicates.go:123-124);
- GCE PD conflicts exempt pairs where BOTH mounts are read-only; AWS
  EBS conflicts regardless (isVolumeConflict, predicates.go:53-78).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.models import labels as labelpkg
from kubernetes_tpu.models.objects import Node, Pod, RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_PODS
from kubernetes_tpu.scheduler.types import StaticNodeLister


def get_resource_request(pod: Pod) -> Tuple[int, int]:
    """(milliCPU, memory bytes) summed over container limits."""
    milli_cpu = 0
    memory = 0
    for c in pod.spec.containers:
        limits = c.resources.limits
        if RESOURCE_CPU in limits:
            milli_cpu += limits[RESOURCE_CPU].milli_value()
        if RESOURCE_MEMORY in limits:
            memory += limits[RESOURCE_MEMORY].value()
    return milli_cpu, memory


def _capacity(node: Node) -> Tuple[int, int, int]:
    cap = node.status.capacity or {}
    cpu = cap[RESOURCE_CPU].milli_value() if RESOURCE_CPU in cap else 0
    mem = cap[RESOURCE_MEMORY].value() if RESOURCE_MEMORY in cap else 0
    pods = cap[RESOURCE_PODS].value() if RESOURCE_PODS in cap else 0
    return cpu, mem, pods


def check_pods_exceeding_capacity(
    pods: Sequence[Pod], capacity: Tuple[int, int]
) -> Tuple[List[Pod], List[Pod]]:
    """Greedy packing simulation (predicates.go:116-136)."""
    total_cpu, total_mem = capacity
    cpu_used = 0
    mem_used = 0
    fitting: List[Pod] = []
    not_fitting: List[Pod] = []
    for pod in pods:
        cpu_req, mem_req = get_resource_request(pod)
        fits_cpu = total_cpu == 0 or (total_cpu - cpu_used) >= cpu_req
        fits_mem = total_mem == 0 or (total_mem - mem_used) >= mem_req
        if not fits_cpu or not fits_mem:
            not_fitting.append(pod)
            continue
        cpu_used += cpu_req
        mem_used += mem_req
        fitting.append(pod)
    return fitting, not_fitting


class ResourceFit:
    """PodFitsResources (predicates.go:139-156)."""

    def __init__(self, node_lister: StaticNodeLister):
        self.node_lister = node_lister

    def __call__(self, pod: Pod, existing_pods: List[Pod], node: str) -> bool:
        cpu_req, mem_req = get_resource_request(pod)
        info = self.node_lister.get(node)
        cap_cpu, cap_mem, cap_pods = _capacity(info)
        if cpu_req == 0 and mem_req == 0:
            return len(existing_pods) < cap_pods
        pods = list(existing_pods) + [pod]
        _, exceeding = check_pods_exceeding_capacity(pods, (cap_cpu, cap_mem))
        if exceeding or len(pods) > cap_pods:
            return False
        return True


def pod_matches_node_labels(pod: Pod, node: Node) -> bool:
    """predicates.go:172-178."""
    if not pod.spec.node_selector:
        return True
    selector = labelpkg.selector_from_set(pod.spec.node_selector)
    return selector.matches(node.metadata.labels or {})


class NodeSelectorMatches:
    """PodSelectorMatches / MatchNodeSelector (predicates.go:184-190)."""

    def __init__(self, node_lister: StaticNodeLister):
        self.node_lister = node_lister

    def __call__(self, pod: Pod, existing_pods: List[Pod], node: str) -> bool:
        return pod_matches_node_labels(pod, self.node_lister.get(node))


def pod_fits_host(pod: Pod, existing_pods: List[Pod], node: str) -> bool:
    """PodFitsHost / HostName (predicates.go:192-197)."""
    if not pod.spec.node_name:
        return True
    return pod.spec.node_name == node


def _is_volume_conflict(volume, pod: Pod) -> bool:
    """isVolumeConflict (predicates.go:53-78)."""
    if volume.gce_persistent_disk is not None:
        disk = volume.gce_persistent_disk
        for v in pod.spec.volumes:
            if (
                v.gce_persistent_disk is not None
                and v.gce_persistent_disk.pd_name == disk.pd_name
                and not (v.gce_persistent_disk.read_only and disk.read_only)
            ):
                return True
    if volume.aws_elastic_block_store is not None:
        volume_id = volume.aws_elastic_block_store.volume_id
        for v in pod.spec.volumes:
            if (
                v.aws_elastic_block_store is not None
                and v.aws_elastic_block_store.volume_id == volume_id
            ):
                return True
    return False


def no_disk_conflict(pod: Pod, existing_pods: List[Pod], node: str) -> bool:
    """NoDiskConflict (predicates.go:85-95)."""
    for volume in pod.spec.volumes:
        for existing in existing_pods:
            if _is_volume_conflict(volume, existing):
                return False
    return True


def get_used_ports(*pods: Pod) -> Dict[int, bool]:
    """predicates.go:351-360 — note hostPort 0 is recorded too (and
    ignored by the caller)."""
    ports: Dict[int, bool] = {}
    for pod in pods:
        for container in pod.spec.containers:
            for port in container.ports:
                ports[port.host_port] = True
    return ports


def pod_fits_ports(pod: Pod, existing_pods: List[Pod], node: str) -> bool:
    """PodFitsPorts (predicates.go:337-349)."""
    existing_ports = get_used_ports(*existing_pods)
    want_ports = get_used_ports(pod)
    for wport in want_ports:
        if wport == 0:
            continue
        if existing_ports.get(wport):
            return False
    return True


class NodeLabelChecker:
    """CheckNodeLabelPresence (predicates.go:226-240)."""

    def __init__(self, node_lister: StaticNodeLister, labels: List[str], presence: bool):
        self.node_lister = node_lister
        self.labels = labels
        self.presence = presence

    def __call__(self, pod: Pod, existing_pods: List[Pod], node: str) -> bool:
        minion = self.node_lister.get(node)
        minion_labels = minion.metadata.labels or {}
        for label in self.labels:
            exists = label in minion_labels
            if (exists and not self.presence) or (not exists and self.presence):
                return False
        return True


class ServiceAffinity:
    """CheckServiceAffinity (predicates.go:268-335)."""

    def __init__(self, pod_lister, service_lister, node_lister, labels: List[str]):
        self.pod_lister = pod_lister
        self.service_lister = service_lister
        self.node_lister = node_lister
        self.labels = labels

    def __call__(self, pod: Pod, existing_pods: List[Pod], node: str) -> bool:
        affinity_labels: Dict[str, str] = {}
        node_selector = pod.spec.node_selector or {}
        labels_exist = True
        for l in self.labels:
            if l in node_selector:
                affinity_labels[l] = node_selector[l]
            else:
                labels_exist = False

        if not labels_exist:
            services = self.service_lister.get_pod_services(pod)
            if services:
                selector = labelpkg.selector_from_set(services[0].spec.selector)
                service_pods = self.pod_lister.list(selector)
                ns_service_pods = [
                    p
                    for p in service_pods
                    if p.metadata.namespace == pod.metadata.namespace
                ]
                if ns_service_pods:
                    try:
                        other = self.node_lister.get(ns_service_pods[0].spec.node_name)
                    except KeyError:
                        return False
                    other_labels = other.metadata.labels or {}
                    for l in self.labels:
                        if l in affinity_labels:
                            continue
                        if l in other_labels:
                            affinity_labels[l] = other_labels[l]

        minion = self.node_lister.get(node)
        if not affinity_labels:
            return True
        return labelpkg.selector_from_set(affinity_labels).matches(
            minion.metadata.labels or {}
        )
