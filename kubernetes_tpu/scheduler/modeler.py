"""System modeler: optimistic assumed-pod cache.

Reference: plugin/pkg/scheduler/modeler.go — after a successful bind
the scheduler "assumes" the pod onto its node so in-flight bindings
count against capacity before the apiserver watch confirms them
(scheduler.go:142-157). Assumptions live in a TTL cache (30s) and are
dropped early when the real pod shows up via watch
(factory.go:91-114)."""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.models.objects import Pod
from kubernetes_tpu.models import labels as labelpkg


class SimpleModeler:
    def __init__(
        self,
        scheduled_pods: Callable[[], List[Pod]],
        ttl: float = 30.0,
    ):
        from kubernetes_tpu.utils import sanitizer

        self._scheduled = scheduled_pods
        self._ttl = ttl
        self._lock = sanitizer.lock("scheduler.modeler")
        self._assumed: Dict[str, tuple] = {}  # key -> (pod, expiry)

    @staticmethod
    def _key(pod: Pod) -> str:
        return f"{pod.metadata.namespace}/{pod.metadata.name}"

    def assume_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed[self._key(pod)] = (pod, time.monotonic() + self._ttl)

    def forget_pod(self, pod: Pod) -> None:
        with self._lock:
            self._assumed.pop(self._key(pod), None)

    def _live_assumed(self) -> List[Pod]:
        now = time.monotonic()
        with self._lock:
            self._assumed = {
                k: v for k, v in self._assumed.items() if v[1] > now
            }
            return [pod for pod, _ in self._assumed.values()]

    def pod_lister(self):
        """Merged lister: scheduled pods U live assumptions not yet
        visible as scheduled (modeler.go:134-179)."""
        modeler = self

        class _Lister:
            def list(self, selector: Optional[labelpkg.Selector] = None) -> List[Pod]:
                scheduled = modeler._scheduled()
                seen = {modeler._key(p) for p in scheduled}
                out = list(scheduled)
                for pod in modeler._live_assumed():
                    key = modeler._key(pod)
                    if key in seen:
                        modeler.forget_pod(pod)  # confirmed by the watch
                        continue
                    out.append(pod)
                if selector is not None and not selector.empty():
                    out = [p for p in out if selector.matches(p.metadata.labels)]
                return out

        return _Lister()
