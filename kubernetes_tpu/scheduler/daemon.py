"""The scheduler daemon: watch-fed caches -> schedule -> bind loop.

Reference: plugin/pkg/scheduler/scheduler.go (Scheduler.Run /
scheduleOne), plugin/pkg/scheduler/factory/factory.go (ConfigFactory:
unassigned-pod FIFO, node/service caches, binder, backoff requeue).

Two operating modes share this daemon:
- scalar: one pod per scheduleOne (the reference's shape);
- batch (TPU): drain the FIFO, solve the whole backlog as matrices,
  then bind the returned assignment (see kubernetes_tpu.ops.solver);
  falls back to scalar when the device path errors.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.client.cache import FIFO, Informer, Reflector, ThreadSafeStore
from kubernetes_tpu.models import serde
from kubernetes_tpu.models.objects import Node, Pod, Service
from kubernetes_tpu.scheduler.generic import FitError, GenericScheduler, NoNodesError
from kubernetes_tpu.scheduler.modeler import SimpleModeler
from kubernetes_tpu.models.algspec import UnloweredPolicyError, lower_spec
from kubernetes_tpu.scheduler.plugins import (
    DEFAULT_PROVIDER,
    PluginFactoryArgs,
    build_from_spec,
    spec_for_policy,
    spec_for_provider,
)

_LOG = logging.getLogger("kubernetes_tpu.scheduler")
from kubernetes_tpu.scheduler.types import StaticNodeLister, StaticServiceLister
from kubernetes_tpu.server.api import APIError
from kubernetes_tpu.utils import (
    faults,
    flightrecorder,
    metrics,
    profiler,
    sanitizer,
    sli,
    tracing,
)
from kubernetes_tpu.utils.ratelimit import Backoff, TokenBucket

# Histograms (were summaries): bucketed latencies aggregate across
# daemons and expose the +le series the SLO checks interpolate; the
# per-phase breakdown lives in scheduler_phase_seconds (utils/tracing).
_E2E_LATENCY = metrics.DEFAULT.histogram(
    "scheduler_e2e_scheduling_latency_seconds",
    "E2e scheduling latency (scheduling algorithm + binding)",
)
_ALGO_LATENCY = metrics.DEFAULT.histogram(
    "scheduler_scheduling_algorithm_latency_seconds", "Scheduling algorithm latency"
)
_BIND_LATENCY = metrics.DEFAULT.histogram(
    "scheduler_binding_latency_seconds", "Binding latency"
)
_SCHEDULED = metrics.DEFAULT.counter(
    "scheduler_pods_scheduled_total", "Pods successfully bound", ("result",)
)
# Preemption series (ktlint KT005 PREEMPTION_METRICS family).
_PREEMPT_VICTIMS = metrics.DEFAULT.counter(
    "preemption_victims_total",
    "Pods evicted to make room for higher-priority pods",
)
_PREEMPT_OUTCOMES = metrics.DEFAULT.counter(
    "preemption_solve_outcomes_total",
    "Per-preemptor preemption solve outcomes by kind",
    ("outcome",),
)
_PREEMPT_NOMINATED = metrics.DEFAULT.gauge(
    "preemption_active_nominations",
    "Pending pods currently holding a nominated node",
)

#: Seconds past the victims' grace a nomination stays live before the
#: preemptor is allowed to preempt again (covers kubelet confirm lag).
NOMINATION_SLACK_SECONDS = 10.0


def _decode_pod(wire: dict) -> Pod:
    return serde.from_wire(Pod, wire)


def _decode_node(wire: dict) -> Node:
    return serde.from_wire(Node, wire)


def _decode_service(wire: dict) -> Service:
    return serde.from_wire(Service, wire)


def _decode_podgroup(wire: dict):
    from kubernetes_tpu.models.objects import PodGroup

    return serde.from_wire(PodGroup, wire)


class _StorePodLister:
    def __init__(self, store: ThreadSafeStore):
        self.store = store

    def list(self, selector=None) -> List[Pod]:
        pods = self.store.list()
        if selector is not None and not selector.empty():
            pods = [p for p in pods if selector.matches(p.metadata.labels)]
        return pods


class _StoreNodeLister:
    """Ready-filtered node lister (reference: StoreToNodeLister with
    NodeCondition filtering, factory.go:166,209)."""

    def __init__(self, store: ThreadSafeStore):
        self.store = store

    @staticmethod
    def _ready(node: Node) -> bool:
        if node.spec.unschedulable:
            return False
        for c in node.status.conditions:
            if c.type == "Ready":
                return c.status == "True"
        return True

    def list(self) -> List[Node]:
        return [n for n in self.store.list() if self._ready(n)]

    def get(self, name: str) -> Node:
        # Nodes are cluster-scoped: store key is the bare name -> O(1).
        node = self.store.get(name)
        if node is None:
            raise KeyError(f"node {name!r} not found")
        return node


class SchedulerConfig:
    """Wires caches + algorithm (reference: factory.CreateFromKeys)."""

    def __init__(
        self,
        client,
        provider_name: str = DEFAULT_PROVIDER,
        policy: Optional[dict] = None,
        bind_qps: float = 0.0,
        assume_ttl: float = 30.0,
        raw_scheduled_cache: bool = False,
    ):
        self.client = client
        # raw_scheduled_cache: keep the scheduled-pods cache in WIRE
        # form and decode lazily. The incremental batch daemon tracks
        # its own bound pods in the device session, so fully decoding
        # every bind/delete event (most of which it discards by key)
        # was the reflector threads' main cost under 1k/s churn. Typed
        # consumers (scalar fallback, session rebuild) decode on access.
        self.raw_scheduled_cache = raw_scheduled_cache
        # Unassigned pods -> FIFO (factory.go:180-186, field selector
        # "spec.nodeName="). DELETED events (pod bound or removed) only
        # need the key to drop the FIFO entry — skip their decode.
        self.pod_queue = FIFO()
        self._pod_reflector = Reflector(
            client,
            "pods",
            self.pod_queue,
            field_selector="spec.nodeName=",
            decode=_decode_pod,
            decode_deleted=False,
        )

        # Cluster-event hook: the incremental batch scheduler subscribes
        # to watch DELTAS (not just cache state) to keep its device-
        # resident session in step. Set before start(); called from the
        # reflector threads, so subscribers must only enqueue.
        self.cluster_events: Optional[Callable[[str, str, object], None]] = None

        def _emit(kind: str, etype: str):
            def handler(obj, _k=kind, _e=etype):
                cb = self.cluster_events
                if cb is not None:
                    cb(_k, _e, obj)

            return handler

        # Scheduled pods cache (for occupancy).
        self.scheduled_pods = Informer(
            client, "pods", field_selector="spec.nodeName!=",
            decode=None if raw_scheduled_cache else _decode_pod,
            on_add=_emit("pod", "ADDED"),
            on_update=_emit("pod", "MODIFIED"),
            on_delete=_emit("pod", "DELETED"),
            decode_deleted=False,
        )
        # Nodes + services caches (factory.go:187-193).
        self.nodes = Informer(
            client, "nodes", decode=_decode_node,
            on_add=_emit("node", "ADDED"),
            on_update=_emit("node", "MODIFIED"),
            on_delete=_emit("node", "DELETED"),
        )
        self.services = Informer(
            client, "services", decode=_decode_service,
            on_add=_emit("service", "ADDED"),
            on_update=_emit("service", "MODIFIED"),
            on_delete=_emit("service", "DELETED"),
        )
        # PodGroup cache: the gang partitioner reads specs from HERE
        # instead of a per-tick cluster-wide LIST (at churn rates the
        # repeated full fetch was pure API-plane load; the informer
        # costs one watch). Cache misses fall back to one read-through
        # LIST (see BatchScheduler._gang_groups).
        self.podgroups = Informer(
            client, "podgroups", decode=_decode_podgroup,
        )

        def _scheduled_typed() -> List[Pod]:
            # With the raw cache, items are wire dicts: decode at the
            # (rare) access points — scalar fallback, session rebuild.
            return [
                _decode_pod(p) if isinstance(p, dict) else p
                for p in self.scheduled_pods.store.list()
            ]

        self.modeler = SimpleModeler(
            scheduled_pods=_scheduled_typed,
            ttl=assume_ttl,
        )
        self.pod_lister = self.modeler.pod_lister()
        self.node_lister = _StoreNodeLister(self.nodes.store)
        self.service_lister = _ServiceListerAdapter(self.services.store)

        args = PluginFactoryArgs(
            pod_lister=self.pod_lister,
            service_lister=self.service_lister,
            node_lister=self.node_lister,
        )
        # The AlgorithmSpec is the shared source of truth: the scalar
        # plugin set is built from it here, and the batch daemon
        # consults it to lower the SAME pipeline to the device (or fall
        # back to the scalar path when it can't) — a policy-configured
        # scheduler never silently runs default decisions.
        if policy is not None:
            self.algorithm_spec = spec_for_policy(policy)
        else:
            self.algorithm_spec = spec_for_provider(provider_name)
        self.predicates, self.priorities = build_from_spec(
            self.algorithm_spec, args
        )

        self.algorithm = GenericScheduler(
            self.predicates, self.priorities, self.pod_lister
        )
        self.binder = client
        self.backoff = Backoff(initial=1.0, max_backoff=60.0)
        # Reference hard-codes 15 qps/20 burst (factory.go:43-46); 0
        # disables throttling (the TPU path needs to go far faster).
        self.bind_limiter = TokenBucket(bind_qps, 20) if bind_qps > 0 else None

    def start(self) -> "SchedulerConfig":
        self._pod_reflector.start()
        self.scheduled_pods.start()
        self.nodes.start()
        self.services.start()
        self.podgroups.start()
        return self

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return all(
            x.wait_for_sync(timeout)
            for x in (
                self._pod_reflector, self.scheduled_pods, self.nodes,
                self.services, self.podgroups,
            )
        )

    def stop(self) -> None:
        self.pod_queue.close()
        for x in (
            self._pod_reflector, self.scheduled_pods, self.nodes,
            self.services, self.podgroups,
        ):
            x.stop()


class _ServiceListerAdapter(StaticServiceLister):
    def __init__(self, store: ThreadSafeStore):
        self.store = store

    @property
    def services(self) -> List[Service]:
        return self.store.list()

    def list(self) -> List[Service]:
        return self.store.list()


class Scheduler:
    """The daemon (reference: scheduler.go:109-158)."""

    def __init__(self, config: SchedulerConfig):
        self.config = config
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Capacity-freed signal: retry backoffs are EVENT-waits, not
        # sleeps — a pod DELETED / node joined delta bumps the epoch
        # and every backlogged pod re-solves the tick the capacity
        # appears instead of waiting out a grown backoff. (Only the
        # incremental daemon has a delta feed to bump it; for the
        # others the wait simply runs to its deadline, but stays
        # interruptible.)
        self._capacity_cond = threading.Condition(
            sanitizer.lock("scheduler.capacity")
        )
        self._capacity_epoch = 0

    def _capacity_freed(self) -> None:
        with self._capacity_cond:
            self._capacity_epoch += 1
            self._capacity_cond.notify_all()

    def _backoff_wait(self, delay: float, epoch: Optional[int] = None) -> bool:
        """Wait out a retry backoff, returning EARLY when cluster
        capacity frees (capacity epoch bump) or the daemon stops.
        True = released early by a capacity event.

        ``epoch`` is the baseline to compare against — callers that
        know WHEN the pod's failed solve read the cluster state pass
        the epoch sampled then, so a victim exiting between the solve
        and this wait still releases immediately (the lost-wakeup
        window of sampling at wait start). None = sample now."""
        deadline = time.monotonic() + delay
        with self._capacity_cond:
            base = self._capacity_epoch if epoch is None else epoch
            while not self._stop.is_set():
                if self._capacity_epoch != base:
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._capacity_cond.wait(min(remaining, 5.0))
        return False

    def start(self) -> "Scheduler":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._capacity_cond:
            self._capacity_cond.notify_all()  # wake backoff waiters
        self.config.stop()
        if self._thread:
            self._thread.join(timeout=5)

    def _step(self) -> None:
        self.schedule_one()

    def run(self) -> None:
        # Crash containment (reference: util.HandleCrash wrapping every
        # control loop) — a transient error must not kill the daemon.
        while not self._stop.is_set():
            try:
                self._step()
            except Exception:
                if not self._stop.is_set():
                    self._stop.wait(0.1)

    def schedule_one(self, timeout: Optional[float] = 0.5) -> bool:
        """Pop one pending pod, schedule, bind, assume. Returns True if
        a pod was processed (scheduler.go:113-158)."""
        cfg = self.config
        pod = cfg.pod_queue.pop(timeout=timeout)
        if pod is None:
            return False
        if pod.spec.node_name:
            return True  # raced: already bound
        if cfg.bind_limiter is not None:
            cfg.bind_limiter.accept()
        start = time.monotonic()
        with tracing.trace(
            "schedule_one", pod=pod.metadata.name
        ) as tr:
            tr.step("enqueue")
            try:
                t0 = time.monotonic()
                with tracing.span("algorithm"):
                    dest = cfg.algorithm.schedule(pod, cfg.node_lister)
                _ALGO_LATENCY.observe(time.monotonic() - t0)
            except (FitError, NoNodesError, KeyError) as e:
                # KeyError: a node vanished between list and predicate
                # lookup (the watch mutates the cache concurrently) —
                # treat like an unschedulable attempt and retry.
                _SCHEDULED.inc(result="unschedulable")
                cfg.client.record_event(
                    pod, "FailedScheduling", str(e), source="scheduler"
                )
                self._requeue_later(pod)
                return True
            try:
                t0 = time.monotonic()
                # "bind_one", not "bind": a single-pod HTTP bind and a
                # 50k-pod bulk commit must not share one series.
                with tracing.phase("bind_one"):
                    cfg.binder.bind(
                        pod.metadata.name, dest,
                        namespace=pod.metadata.namespace or "default",
                    )
                _BIND_LATENCY.observe(time.monotonic() - t0)
            except APIError as e:
                _SCHEDULED.inc(result="bind_error")
                cfg.client.record_event(
                    pod, "FailedBinding", str(e), source="scheduler"
                )
                self._requeue_later(pod)
                return True
            # Assume so capacity is held before the watch confirms
            # (scheduler.go:142-157).
            pod.spec.node_name = dest
            cfg.modeler.assume_pod(pod)
            _SCHEDULED.inc(result="scheduled")
            _E2E_LATENCY.observe(time.monotonic() - start)
            cfg.client.record_event(
                pod, "Scheduled",
                f"Successfully assigned {pod.metadata.name} to {dest}",
                source="scheduler",
            )
            return True

    def _refetch_and_requeue(self, pod: Pod) -> None:
        """Re-fetch `pod` and re-add it to the queue if still pending.
        Drops it only when the apiserver says it no longer exists (404);
        any other error retries with the stale snapshot — the bind CAS
        still protects against double-assignment."""
        try:
            fresh = self.config.client.get(
                "pods", pod.metadata.name,
                namespace=pod.metadata.namespace or "default",
            )
        except APIError as e:
            if e.code == 404:
                return  # deleted: stop retrying
            fresh = pod  # transient server error: retry with the snapshot
        except Exception:
            fresh = pod  # apiserver hiccup: retry with the snapshot
        if not fresh.spec.node_name:
            self.config.pod_queue.add(fresh)

    def _requeue_later(self, pod: Pod) -> None:
        """Exponential-backoff retry. Mirrors factory.go:257-286: after
        the backoff, RE-FETCH the pod from the apiserver and drop it if
        it is gone or got assigned in the meantime."""
        key = f"{pod.metadata.namespace}/{pod.metadata.name}"
        delay = self.config.backoff.duration(key)

        def later():
            self._backoff_wait(delay)
            if self._stop.is_set():
                return
            self._refetch_and_requeue(pod)

        threading.Thread(target=later, daemon=True).start()

    def _requeue_many(
        self, pods: List[Pod], epoch: Optional[int] = None
    ) -> None:
        """Batch-friendly requeue: ONE worker thread re-adds the whole
        rejected set at each pod's backoff deadline (the per-pod-thread
        scalar mechanism would spawn up to max_batch threads). ``epoch``
        is the capacity epoch the failed solve read its cluster state
        at (see _backoff_wait)."""
        if not pods:
            return
        now = time.monotonic()
        schedule = sorted(
            (
                now
                + self.config.backoff.duration(
                    f"{p.metadata.namespace}/{p.metadata.name}"
                ),
                i,
            )
            for i, p in enumerate(pods)
        )

        def worker():
            # One capacity event releases the WHOLE rejected set: the
            # freed slot is contested by the full backlog in one tick,
            # not dribbled out across per-pod deadlines.
            released = False
            for deadline, i in schedule:
                wait = deadline - time.monotonic()
                if wait > 0 and not released:
                    released = self._backoff_wait(wait, epoch)
                if self._stop.is_set():
                    return
                self._refetch_and_requeue(pods[i])

        threading.Thread(target=worker, daemon=True).start()


class BatchScheduler(Scheduler):
    """TPU-backed batch mode: drain the whole pending backlog, solve it
    as one device problem, commit via bulk bindings. Falls back to the
    scalar per-pod path when the device solve fails (the north star's
    stock-FitPredicate fallback). Decision parity with the scalar path
    is the solver's contract (kubernetes_tpu.ops.solver)."""

    def __init__(
        self,
        config: SchedulerConfig,
        max_batch: int = 65536,
        batch_window: float = 0.02,
        mode: str = "scan",
        sidecar_path: Optional[str] = None,
        eviction_grace_seconds: Optional[int] = None,
    ):
        super().__init__(config)
        self.max_batch = max_batch
        self.batch_window = batch_window
        # Priority & preemption: victims terminate with this grace;
        # nominations (pod -> node reserved while victims drain) expire
        # shortly after it so a wedged eviction can't pin a pod forever.
        from kubernetes_tpu.server.api import DEFAULT_EVICTION_GRACE_SECONDS

        self.eviction_grace_seconds = (
            DEFAULT_EVICTION_GRACE_SECONDS
            if eviction_grace_seconds is None
            else int(eviction_grace_seconds)
        )
        # pod key -> (node, priority, monotonic expiry). The preemptor
        # is skipped by later preemption passes while this is live; the
        # priority-ordered drain is what actually holds the freed slot
        # against lower-priority placements.
        self._nominations: Dict[str, Tuple[str, int, float]] = {}
        # "scan" = sequential-parity solver — the default AND, with the
        # pallas kernel (ops/pallas_scan.py), the fastest backlog mode
        # on a single TPU; "wave" = wave-commit solver (valid
        # placements, approximate decision-order parity — ops/wave.py;
        # still the best sustained-churn mode); "sinkhorn" =
        # Sinkhorn-matched waves (congestion-priced assignment, fewest
        # device steps — ops/sinkhorn.py); "auto" = topology-aware
        # (scan+pallas on one chip, wave on a mesh —
        # batch.resolve_batch_mode).
        from kubernetes_tpu.scheduler.batch import resolve_batch_mode

        mode = resolve_batch_mode(mode)
        if mode not in ("scan", "wave", "sinkhorn"):
            raise ValueError(f"unknown batch mode {mode!r}")
        self.mode = mode
        # Optional process isolation: solve through a solver sidecar
        # (ops/sidecar.py) — the control plane never touches the
        # accelerator, and sidecar failure degrades to the scalar
        # fallback below instead of taking the scheduler down.
        self.sidecar = None
        if sidecar_path:
            from kubernetes_tpu.ops.sidecar import SidecarSolver

            self.sidecar = SidecarSolver(sidecar_path)
        self.fallback_count = 0
        self._capacity_sampled_mono = 0.0
        # Policy routing (round-2 VERDICT Weak #1): a non-default spec
        # either lowers to the scan solver or pins the batch to the
        # scalar path — decided once, loudly.
        spec = config.algorithm_spec
        self.spec = None if spec.is_default() else spec
        self.policy_scalar = False  # spec unlowerable: scalar-only batch
        if self.spec is not None:
            try:
                lower_spec(self.spec)
            except UnloweredPolicyError as e:
                self.policy_scalar = True
                _LOG.warning(
                    "scheduler policy is not device-lowerable (%s); "
                    "batch mode will run the configured plugins on the "
                    "scalar path", e,
                )
            else:
                if self.mode != "scan":
                    _LOG.warning(
                        "batch mode %r does not support non-default "
                        "scheduler policy; using the policy-aware scan "
                        "solver instead", self.mode,
                    )
                    self.mode = "scan"

    def _step(self) -> None:
        self.schedule_batch()

    # -- gang scheduling ----------------------------------------------

    def _gang_groups(self, pending: List[Pod], assigned=None):
        """Partition the drained backlog into PodGroups (empty when no
        pod carries the group label — the common case costs one label
        scan and nothing else). PodGroup specs come from the daemon's
        podgroups INFORMER (no per-tick cluster-wide LIST on the hot
        path); only a cache miss — a group the watch hasn't delivered
        yet, or one that was deleted — falls back to one read-through
        LIST so gang semantics never ride a stale cache.

        Returns None when the read-through fetch failed TRANSIENTLY:
        the caller must defer the grouped pods (requeue), never
        schedule them per-pod — silently dropping gang semantics is
        exactly the partial placement this subsystem exists to prevent.
        Only a server that genuinely does not serve the resource
        (older apiserver: 400/404) degrades to per-pod scheduling."""
        from kubernetes_tpu.scheduler import gang

        needed = {
            gang.group_key(p.metadata.namespace or "default", name)
            for p in pending
            for name in (gang.pod_group_name(p),)
            if name
        }
        if not needed:
            return []
        by_key = {
            gang.group_key(pg.metadata.namespace, pg.metadata.name): pg
            for pg in self.config.podgroups.store.list()
        }
        missing = needed - by_key.keys()
        if missing:
            # A read-through already CONFIRMED some groups absent (the
            # authoritative LIST is read-your-writes): they're deleted
            # — degrade to per-pod (partition treats unknown groups as
            # minMember 0) instead of re-fetching the whole collection
            # every tick while their member pods sit in requeue.
            now = time.monotonic()
            memo = getattr(self, "_missing_groups", None)
            if memo is None:
                memo = self._missing_groups = {}
            missing = {
                k for k in missing if memo.get(k, 0.0) <= now
            }
        if missing:
            # Informer lag or deleted group: ONE read-through fetch
            # disambiguates (admission guarantees the group existed at
            # pod-create time, so a genuine miss means deletion).
            try:
                pgs, _ = self.config.client.list("podgroups")
            except APIError as e:
                if e.code in (400, 404):
                    return []  # resource not served: per-pod is all there is
                return None  # transient server error: defer the gangs
            except Exception:
                return None  # transport failure: defer the gangs
            by_key = {
                gang.group_key(pg.metadata.namespace, pg.metadata.name): pg
                for pg in pgs
            }
            # Still absent from the authoritative LIST = deleted; memo
            # with a TTL so a recreated group is picked up promptly
            # even if the informer misses it.
            expiry = time.monotonic() + 30.0
            memo = self._missing_groups
            if len(memo) > 4096:
                memo.clear()
            for k in needed - by_key.keys():
                memo[k] = expiry

        def min_member_of(ns: str, name: str):
            pg = by_key.get(gang.group_key(ns, name))
            return pg.spec.min_member if pg is not None else None

        if assigned is None:
            assigned = self.config.pod_lister.list()
        return gang.partition_backlog(
            pending, assigned=assigned, min_member_of=min_member_of
        )

    @staticmethod
    def _split_deferred_gangs(pending: List[Pod]) -> Tuple[List[Pod], List[Pod]]:
        """(ungrouped, grouped) split for the defer-on-fetch-failure
        path: grouped pods wait for resolvable specs."""
        from kubernetes_tpu.scheduler import gang

        ungrouped = [p for p in pending if not gang.pod_group_name(p)]
        grouped = [p for p in pending if gang.pod_group_name(p)]
        return ungrouped, grouped

    def _gang_counts_fn(self):
        """Acceptance reducer: the device masked-segment-reduction when
        this daemon solves on device; the host twin for the scalar /
        sidecar shapes (the sidecar's arrays live in its process)."""
        if self.policy_scalar or self.sidecar is not None:
            return None  # gang_solve defaults to the host reducer
        from kubernetes_tpu.ops.pipeline import gang_member_counts_device

        return gang_member_counts_device

    def _bind_groups_atomic(
        self,
        group_binds: Dict[str, Tuple[str, List[Tuple[str, str]]]],
        outcome: Dict[Tuple[str, str], dict],
    ) -> None:
        """Commit each accepted group through bind_bulk(atomic=True):
        a mid-batch conflict rejects the whole group server-side (no
        stragglers), surfacing per-pod Aborted statuses the caller
        requeues."""
        from kubernetes_tpu.scheduler.gang import OUTCOMES

        for _gkey, (ns, items) in sorted(group_binds.items()):
            results = self.config.binder.bind_bulk(
                items, namespace=ns, atomic=True
            )
            for (pod_name, _dest), res in zip(items, results):
                outcome[(ns, pod_name)] = res
            if any(r.get("status") != "Success" for r in results):
                OUTCOMES.inc(outcome="bind_rollback")

    @staticmethod
    def _bind_retryable(res: dict) -> bool:
        """A failed bind outcome that should requeue the pod. A plain
        409 means the pod raced and IS bound (by someone else) — drop
        it; 409 Aborted means its gang's atomic batch rolled back and
        the pod is still pending."""
        return res.get("code") != 409 or res.get("reason") == "Aborted"

    def _drain(self, timeout: Optional[float]) -> List[Pod]:
        """Pop the first pod (blocking) then everything already queued,
        up to max_batch (amortizes solves under churn). The drained
        batch solves highest-priority-first (stable within a priority
        band, preserving arrival order) — the reference's priority
        queue shape, and the mechanism that holds a nominated pod's
        freed capacity against lower-priority placements: when victims
        exit, the nominated (higher-priority) pod gets first claim in
        the very tick the capacity appears."""
        first = self.config.pod_queue.pop(timeout=timeout)
        if first is None:
            return []
        batch = [first]
        deadline = time.monotonic() + self.batch_window
        while len(batch) < self.max_batch:
            wait = deadline - time.monotonic()
            pod = self.config.pod_queue.pop(timeout=max(0.0, wait))
            if pod is None:
                break
            batch.append(pod)
        batch = [p for p in batch if not p.spec.node_name]
        batch.sort(key=lambda p: -(p.spec.priority or 0))
        return batch

    # -- priority & preemption ----------------------------------------

    @staticmethod
    def _pod_key(pod: Pod) -> str:
        from kubernetes_tpu.models.objects import pod_full_key

        return pod_full_key(pod)

    def _maybe_preempt(
        self, unbound: List[Pod], nodes, assigned, groups=()
    ) -> int:
        """Preemption pass over the tick's unschedulable pods: solve
        victim selection (device path, scalar fallback), enforce the
        gang all-or-nothing guard, then nominate + gracefully evict.
        The preemptors themselves stay in the requeue loop — they bind
        through the ordinary solve once their victims exit. Returns
        nominations granted."""
        from kubernetes_tpu.models.objects import pod_can_preempt, pod_priority

        now = time.monotonic()
        for key in [
            k for k, (_, _, exp) in self._nominations.items() if exp <= now
        ]:
            del self._nominations[key]
        candidates = [
            p for p in unbound
            if pod_priority(p) > 0
            and pod_can_preempt(p)
            and self._pod_key(p) not in self._nominations
        ]
        _PREEMPT_NOMINATED.set(len(self._nominations))
        if not candidates:
            return 0
        with tracing.phase("preempt", pods=len(candidates)):
            return self._preempt(
                candidates, unbound, nodes, assigned, now, groups
            )

    def _preempt(
        self, candidates, unbound, nodes, assigned, now, groups=()
    ) -> int:
        from kubernetes_tpu.models.objects import pod_priority
        from kubernetes_tpu.scheduler.batch import (
            preempt_backlog_scalar,
            preempt_backlog_tpu,
        )
        from kubernetes_tpu.scheduler.gang import drop_partial_gang_preemptions

        cfg = self.config
        try:
            if self.policy_scalar or self.sidecar is not None:
                # Sidecar/scalar-pinned daemons never touch the local
                # device for the main solve; same for victim selection.
                decisions = preempt_backlog_scalar(candidates, nodes, assigned)
            else:
                decisions = preempt_backlog_tpu(candidates, nodes, assigned)
        except Exception:
            self.fallback_count += 1
            try:
                decisions = preempt_backlog_scalar(candidates, nodes, assigned)
            except Exception:
                _LOG.exception("preemption solve failed on both paths")
                _PREEMPT_OUTCOMES.inc(outcome="error")
                return 0
        covered = frozenset(self._nominations)
        solved = list(decisions)
        decisions, dropped = drop_partial_gang_preemptions(
            unbound, candidates, decisions, covered_keys=covered,
            groups=groups or (),
        )
        for gkey in dropped:
            _PREEMPT_OUTCOMES.inc(outcome="gang_partial")
            _LOG.info(
                "preemption for pod group %s dropped: not every unbound "
                "member could be granted a nomination", gkey,
            )
        granted = 0
        for pod, dec, pre_guard in zip(candidates, decisions, solved):
            if dec is None:
                # Grants the gang guard nulled are accounted by their
                # group's gang_partial above, not double-counted as
                # per-pod infeasibility. Either way the flight
                # recorder's decision for the pod gains the preemption
                # verdict (the explain surface's rejection reason).
                if pre_guard is None:
                    from kubernetes_tpu.ops.preemption import (
                        REASON_INFEASIBLE,
                    )

                    _PREEMPT_OUTCOMES.inc(outcome="infeasible")
                    flightrecorder.DEFAULT.record_preemption(
                        self._pod_key(pod), "preempt_infeasible",
                        reason=REASON_INFEASIBLE,
                    )
                else:
                    flightrecorder.DEFAULT.record_preemption(
                        self._pod_key(pod), "preempt_gang_partial",
                        reason="pod group preemption dropped: not every "
                        "unbound member could be granted a nomination",
                    )
                continue
            ns = pod.metadata.namespace or "default"
            key = self._pod_key(pod)
            evicted = 0
            gone = 0
            for vkey in dec.victims:
                vns, _, vname = vkey.partition("/")
                try:
                    # Chaos seam: an injected eviction failure takes
                    # the same broad-except path a real transport
                    # outage would — counted evict_failed below, no
                    # nomination recorded, retried next tick.
                    faults.fire(faults.SCHED_EVICT_ERROR, vkey)
                    cfg.client.evict(
                        vname, namespace=vns,
                        grace_period_seconds=self.eviction_grace_seconds,
                    )
                except APIError as e:
                    if e.code == 404:
                        gone += 1  # already gone: capacity freed anyway
                        continue
                    _LOG.warning("eviction of %s failed: %s", vkey, e)
                    continue
                except Exception:
                    _LOG.exception("eviction of %s failed", vkey)
                    continue
                evicted += 1
                cfg.client.record_event(
                    {"kind": "Pod",
                     "metadata": {"name": vname, "namespace": vns}},
                    "Preempted",
                    f"Preempted by {key} on node {dec.node}",
                    source="scheduler", namespace=vns,
                )
            _PREEMPT_VICTIMS.inc(evicted)
            if evicted + gone == 0:
                # Every eviction failed transiently: no capacity was
                # (or will be) freed, so recording a nomination would
                # just freeze the preemptor out of re-solving for the
                # whole grace+slack window. Retry next tick.
                _PREEMPT_OUTCOMES.inc(outcome="evict_failed")
                flightrecorder.DEFAULT.record_preemption(
                    key, "preempt_evict_failed", node=dec.node,
                    victims=dec.victims,
                    reason="every victim eviction failed; retrying",
                )
                continue
            try:
                # Publish the reservation so operators (and HA peers)
                # can see why the freed capacity is spoken for.
                cfg.client.patch(
                    "pods", pod.metadata.name,
                    {"status": {"nominatedNodeName": dec.node}},
                    namespace=ns,
                )
            except Exception:
                _LOG.debug(
                    "nominatedNodeName write for %s failed", key,
                    exc_info=True,
                )
            _PREEMPT_OUTCOMES.inc(outcome="nominated")
            flightrecorder.DEFAULT.record_preemption(
                key, "preempt_nominated", node=dec.node,
                victims=dec.victims,
            )
            self._nominations[key] = (
                dec.node, pod_priority(pod),
                now + self.eviction_grace_seconds + NOMINATION_SLACK_SECONDS,
            )
            # Retry promptly: the nominated pod must contest the freed
            # capacity the tick it appears, not after a grown backoff.
            cfg.backoff.reset(key)
            granted += 1
        _PREEMPT_NOMINATED.set(len(self._nominations))
        return granted

    def _explain_shed(self) -> bool:
        """Whether this tick's bound-pod explain capture should defer
        off the latency path. The plain batch daemon never sheds (the
        explain phase already runs outside the solve path); the
        pipelined incremental daemon always does."""
        return False

    def _queue_deferred_explain(self, ctx) -> None:
        """Accept a deferred bound-table explain context (no-op here;
        the pipelined daemon queues it for the commit worker's idle
        drain)."""

    def _observe_informer_staleness(self) -> None:
        """Set scheduler_informer_staleness_seconds per informer:
        seconds since each watch-fed cache last processed a delta or
        relist. Under churn a growing value means this daemon is
        solving against an increasingly stale cluster view (a quiet
        cluster legitimately grows it too — read it against event
        rates, see docs/architecture.md)."""
        cfg = self.config
        now = time.monotonic()
        for resource, ref in (
            ("pods_pending", cfg._pod_reflector),
            ("pods_scheduled", cfg.scheduled_pods.reflector),
            ("nodes", cfg.nodes.reflector),
            ("services", cfg.services.reflector),
            ("podgroups", cfg.podgroups.reflector),
        ):
            ts = getattr(ref, "last_event_mono", 0.0)
            if ts:
                sli.INFORMER_STALENESS.set(now - ts, resource=resource)

    # -- capacity & fragmentation plane --------------------------------

    #: Idle-tick capacity refresh cadence (the PR 9 staleness rule:
    #: telemetry must keep moving on an idle cluster, but a full sample
    #: per empty poll tick would be pure overhead).
    CAPACITY_IDLE_REFRESH_S = 2.0

    def start(self) -> "BatchScheduler":
        # The capacity kernel's cold XLA compile (~1.5s) must never
        # land in-band: a solve-thread stall that long lets a fast
        # wave finish bind+running before the commit worker announces
        # its decision milestones. Warm both probe-count buckets on a
        # background thread before traffic arrives.
        def _warm():
            try:
                from kubernetes_tpu.utils import capacity as capmod

                capmod.DEFAULT.warm(len(self.config.nodes.store.list()))
            except Exception:
                _LOG.debug("capacity warm failed", exc_info=True)

        threading.Thread(
            target=_warm, daemon=True, name="capacity-warm"
        ).start()
        return super().start()  # type: ignore[return-value]

    def _sample_capacity(self, pending: Optional[List[Pod]] = None) -> None:
        """One capacity-plane sample (utils/capacity.py) inside its own
        ``capacity`` phase span: occupancy columns straight off the
        session's host mirror when one exists (the already-staged
        matrices), otherwise rebuilt from the watch caches. Runs per
        resolved tick plus the idle refresh below. Telemetry only —
        it never raises into the tick."""
        try:
            from kubernetes_tpu.models.columnar import (
                mem_to_mib_ceil,
                pod_resource_limits,
            )
            from kubernetes_tpu.utils import capacity as capmod

            cfg = self.config
            if pending:
                shapes = []
                for pod in pending:
                    cpu, mem = pod_resource_limits(pod)
                    shapes.append((float(cpu), float(mem_to_mib_ceil(mem))))
                capmod.DEFAULT.note_backlog_shapes(shapes)
            session = getattr(self, "_session", None)
            with tracing.phase("capacity"):
                if session is not None:
                    cols, names = capmod.session_columns(session)
                else:
                    cols, names = capmod.cluster_columns(
                        cfg.nodes.store.list(), cfg.pod_lister.list()
                    )
                capmod.DEFAULT.sample(
                    cols,
                    names,
                    backlog_depth=len(cfg.pod_queue),
                    oldest_age_s=sli.DEFAULT.oldest_unbound_age_s(),
                )
            self._capacity_sampled_mono = time.monotonic()
        except Exception:
            _LOG.debug("capacity sample failed", exc_info=True)

    def _refresh_capacity_idle(self) -> None:
        """Idle-tick half of the sampling cadence: refresh the capacity
        series when no tick has sampled them for a beat, so the plane
        stays live (and the trend ring honest) on a quiet cluster."""
        if (
            time.monotonic() - getattr(self, "_capacity_sampled_mono", 0.0)
            < self.CAPACITY_IDLE_REFRESH_S
        ):
            return
        self._sample_capacity()

    # -- flight recorder ----------------------------------------------

    def _record_decisions(
        self, rows, nodes, services, assigned_pre, solve_s=0.0, stats=None
    ) -> None:
        """Feed the scheduling flight recorder: one SolveRecord for the
        tick plus one Decision per drained pod (outcome + chosen node),
        with bounded per-node explain verdicts captured in their OWN
        phase — never inside the solve path (the phase=solve p99 gate
        bench.py publishes must not move). rows are (pod, dest,
        outcome, gang_key); assigned_pre is the pre-solve occupancy
        (None = derive it from the post-solve lister by subtracting
        this tick's binds — the incremental daemon's shape)."""
        if not rows:
            return
        # Post-solve telemetry sample (utils/sli.py): the compile-cache
        # sentinel reflects THIS tick's compiles next to its phase
        # histograms (the every-tick pre-drain sample in schedule_batch
        # covers idle/stalled ticks).
        sli.observe_device_telemetry()
        # Wave/sinkhorn batch solves return placements only; their
        # convergence figures were parked by observe_solve_telemetry —
        # consume them (once) so this tick's SolveRecord carries them.
        # The incremental daemon passes explicit stats (session
        # last_stats); the pop still runs so a later tick can never
        # inherit this solve's numbers.
        tele = flightrecorder.take_last_solve_telemetry()
        if not stats and tele is not None and tele["mode"] == self.mode:
            stats = {"waves": tele["waves"]}
            if self.mode == "sinkhorn":
                stats["sinkhorn_iters"] = tele["iterations"]
                stats["sinkhorn_residual"] = tele["residual"]
        stats = stats or {}
        tick = flightrecorder.DEFAULT.next_tick()
        trace_id = tracing.current_trace_id()
        flightrecorder.DEFAULT.record_solve(
            flightrecorder.SolveRecord(
                tick=tick, trace_id=trace_id, mode=self.mode,
                pods=len(rows), duration_s=solve_s,
                waves=int(stats.get("waves", 0)),
                sinkhorn_iterations=int(stats.get("sinkhorn_iters", 0)),
                sinkhorn_residual=stats.get("sinkhorn_residual"),
                incremental=bool(stats.get("incremental", False)),
            )
        )
        decisions: Dict[str, flightrecorder.Decision] = {}
        for pod, dest, outcome, gkey in rows:
            key = self._pod_key(pod)
            decisions[key] = flightrecorder.Decision(
                pod=key, tick=tick, trace_id=trace_id, mode=self.mode,
                outcome=outcome, node=dest or "", group=gkey or "",
            )
        # Announce outcomes to decision sinks NOW (SLI "decision"
        # milestone, utils/sli.py) — the explain readback below may
        # stall seconds on a first-bucket XLA compile, and a fast pod
        # can complete its whole lifecycle in that window. record()
        # re-announces; sinks are idempotent by contract.
        flightrecorder.notify_decision_sinks(
            (d.pod, d.outcome) for d in decisions.values()
        )
        limit = flightrecorder.explain_limit()
        # Non-default policies have no device explain lowering (the
        # readback evaluates the default pipeline), and sidecar daemons
        # keep the control plane off the local accelerator; outcome
        # records still land, verdict tables are skipped. Pipelined
        # daemons additionally SHED under pressure (_explain_shed):
        # the readback is a device dispatch of its own and must never
        # sit on the next pod's bind latency — bound-pod tables are
        # dropped, UNBOUND pods (the thing operators explain) keep
        # theirs, and full capture resumes when the cluster quiets.
        shed = self._explain_shed()
        has_unbound = any(dest is None for _p, dest, _o, _g in rows)
        if limit > 0 and self.spec is None and self.sidecar is None:
            if not shed or has_unbound:
                # Shed = pressure path: unbound pods (the thing
                # operators explain) still capture inline; bound
                # tables defer below.
                try:
                    with tracing.phase(
                        "explain", pods=min(len(rows), limit)
                    ):
                        self._attach_verdicts(
                            rows, decisions, nodes, services,
                            assigned_pre, limit,
                            only="unbound" if shed else None,
                        )
                except Exception:
                    _LOG.debug(
                        "explain readback failed for tick %d", tick,
                        exc_info=True,
                    )
            if shed:
                # Bound-pod verdict tables attach POST-HOC: Decision
                # objects live in the ring, so the commit worker's
                # idle drain amends the same records readers see.
                self._queue_deferred_explain(
                    (rows, decisions, nodes, services, assigned_pre,
                     limit)
                )
        flightrecorder.DEFAULT.record(decisions.values())

    def _attach_verdicts(
        self, rows, decisions, nodes, services, assigned_pre, limit,
        only: Optional[str] = None,
    ) -> None:
        """Per-node verdicts from the device explain readback. Unbound
        pods are explained against the POST-solve occupancy (why they
        are stuck NOW, including this tick's own placements — since
        occupancy only grows, a pod the scan left behind has a failing
        predicate on every node in that state); bound pods against the
        PRE-solve state (the view they won under). Unbound pods get
        first claim on the budget — they are what operators explain.
        ``only`` restricts the pass: "unbound" (the pipelined daemon's
        inline pressure capture) or "bound" (its deferred worker-idle
        half)."""
        import copy

        from kubernetes_tpu.models.objects import pod_full_key
        from kubernetes_tpu.ops.pipeline import explain_backlog

        unbound = [pod for pod, dest, _, _ in rows if dest is None][:limit]
        budget = 0 if only == "unbound" else limit - len(unbound)
        if only == "bound":
            unbound = []
        bound = []
        for pod, dest, _, _ in rows:
            if dest is None or budget <= 0:
                continue
            # Bound this tick (spec.node_name may already carry the
            # assumed binding): explain the pre-bind view, or the
            # HostName predicate would pin the verdict to the answer.
            ep = copy.deepcopy(pod)
            ep.spec.node_name = ""
            bound.append(ep)
            budget -= 1
        post = self.config.pod_lister.list()
        if assigned_pre is None:
            bound_keys = {
                self._pod_key(pod)
                for pod, dest, outcome, _ in rows
                if dest is not None and outcome == "bound"
            }
            assigned_pre = [
                q for q in post if pod_full_key(q) not in bound_keys
            ]
        top_k = flightrecorder.explain_top_k()
        max_failed = flightrecorder.explain_failed_nodes()
        if bound:
            for entry in explain_backlog(
                bound, nodes, assigned_pre, services,
                top_k=top_k, max_failed=max_failed,
            ):
                d = decisions.get(entry["pod"])
                if d is not None:
                    d.attach(entry)
        if unbound:
            for entry in explain_backlog(
                unbound, nodes, post, services,
                top_k=top_k, max_failed=max_failed,
            ):
                d = decisions.get(entry["pod"])
                if d is not None:
                    d.attach(entry)

    def schedule_batch(self, timeout: Optional[float] = 0.5) -> int:
        """One drain+solve+commit cycle; returns pods processed."""
        t_drain = time.monotonic()
        # Telemetry sample EVERY tick, idle ones included: a wedged
        # informer produces empty ticks, and a staleness gauge that
        # only updates on busy ticks would freeze at a healthy value
        # exactly when the feed it watches stalls. (_record_decisions
        # samples again post-solve for compile-cache freshness.)
        self._observe_informer_staleness()
        sli.observe_device_telemetry()
        pending = self._drain(timeout)
        if not pending:
            self._refresh_capacity_idle()
            return 0
        # One trace per cycle (a per-pod trace at 50k-pod batches would
        # be pure overhead): the pod set rides the trace for filtering,
        # the phase spans (enqueue/lower/upload/solve/readback/bind)
        # tell one pod's story because every pod in the batch shares
        # them.
        with tracing.trace(
            "schedule_batch",
            pods=(p.metadata.name for p in pending),
            start=t_drain,
        ) as tr:
            tr.child(
                "enqueue", start=t_drain, end=time.monotonic(),
                pods=len(pending), mode=self.mode,
            )
            return self._solve_and_commit(pending)

    def _solve_and_commit(self, pending: List[Pod]) -> int:
        from kubernetes_tpu.scheduler.batch import (
            schedule_backlog_scalar,
            schedule_backlog_sinkhorn,
            schedule_backlog_tpu,
            schedule_backlog_wave,
        )

        cfg = self.config
        start = time.monotonic()
        nodes = cfg.nodes.store.list()  # unfiltered; snapshot encodes readiness
        assigned = cfg.pod_lister.list()
        services = cfg.service_lister.list()
        if self.policy_scalar:
            # Unlowerable policy: the configured plugins run scalar —
            # never default-policy decisions (VERDICT r2 Weak #1).
            def solver(pending, nodes, assigned, services):
                return schedule_backlog_scalar(
                    pending, nodes, assigned, services, spec=self.spec
                )
        elif self.sidecar is not None:
            # The sidecar honors the batch mode too (the request
            # carries it), so wave + sidecar compose instead of the
            # sidecar silently downgrading an explicit wave request.
            def solver(pending, nodes, assigned, services):
                # Distinct phase label: this times the whole remote
                # round-trip (the sidecar's own lower/upload/readback
                # happen in its process), not in-process dispatch.
                with tracing.phase("solve_sidecar", mode=self.mode):
                    return self.sidecar.solve(
                        pending, nodes, assigned, services, mode=self.mode,
                        spec=self.spec,
                    )
        elif self.mode == "wave":
            solver = schedule_backlog_wave
        elif self.mode == "sinkhorn":
            solver = schedule_backlog_sinkhorn
        else:
            def solver(pending, nodes, assigned, services):
                return schedule_backlog_tpu(
                    pending, nodes, assigned, services, spec=self.spec
                )
        # Gang partitioning: grouped pods place all-or-nothing (the
        # acceptance loop wraps WHATEVER solver this daemon runs —
        # device, sidecar, or policy-pinned scalar).
        groups = self._gang_groups(pending, assigned)
        deferred: List[Pod] = []
        if groups is None:
            # Couldn't resolve PodGroup specs this tick: defer the
            # grouped pods (retry after backoff) and solve the rest —
            # scheduling a gang member per-pod would break the
            # all-or-nothing contract.
            pending, deferred = self._split_deferred_gangs(pending)
            self._requeue_many(deferred)
            groups = []
            if not pending:
                return len(deferred)

        def run(solve_fn, counts_fn):
            if not groups:
                return solve_fn(pending, nodes, assigned, services), []
            from kubernetes_tpu.scheduler.gang import gang_solve

            dests, _accepted, denied = gang_solve(
                solve_fn, pending, nodes, assigned, services, groups,
                counts_fn=counts_fn,
            )
            return dests, denied

        try:
            t0 = time.monotonic()
            destinations, denied = run(solver, self._gang_counts_fn())
            solve_s = time.monotonic() - t0
            _ALGO_LATENCY.observe(solve_s)
        except Exception:
            # Device path unavailable: scalar fallback with the
            # CONFIGURED plugin set — and the HOST acceptance reducer
            # (the device reducer would just re-raise the same outage).
            self.fallback_count += 1
            try:
                destinations, denied = run(
                    lambda p, n, a, s: schedule_backlog_scalar(
                        p, n, a, s, spec=self.spec
                    ),
                    None,
                )
            except Exception:
                self._requeue_many(pending)
                return len(pending)
            solve_s = time.monotonic() - t0

        denied_at: Dict[int, str] = {
            i: g.key for g in denied for i in g.indices
        }
        gkey_at: Dict[int, str] = {
            i: g.key for g in groups for i in g.indices
        }
        # Commit placed pods in one bulk call, grouped by namespace;
        # accepted gangs commit separately, each as one atomic batch.
        by_ns: Dict[str, List] = {}
        group_binds: Dict[str, Tuple[str, List[Tuple[str, str]]]] = {}
        placed: List[Tuple[Pod, str]] = []
        rejected: List[Pod] = []
        for i, (pod, dest) in enumerate(zip(pending, destinations)):
            if dest is None:
                _SCHEDULED.inc(result="unschedulable")
                message = (
                    f'pod group "{denied_at[i]}" rejected: fewer than '
                    "minMember pods schedulable"
                    if i in denied_at
                    else "no node fits"
                )
                cfg.client.record_event(
                    pod, "FailedScheduling", message, source="scheduler"
                )
                rejected.append(pod)
                continue
            ns = pod.metadata.namespace or "default"
            gkey = gkey_at.get(i)
            if gkey is not None:
                group_binds.setdefault(gkey, (ns, []))[1].append(
                    (pod.metadata.name, dest)
                )
            else:
                by_ns.setdefault(ns, []).append((pod.metadata.name, dest))
            placed.append((pod, dest))

        t0 = time.monotonic()
        outcome: Dict[Tuple[str, str], dict] = {}
        with tracing.phase("bind", pods=len(placed)):
            try:
                for ns, items in by_ns.items():
                    results = cfg.binder.bind_bulk(items, namespace=ns)
                    for (pod_name, _dest), res in zip(items, results):
                        outcome[(ns, pod_name)] = res
                self._bind_groups_atomic(group_binds, outcome)
            except Exception:
                # Transport/apiserver failure mid-commit: pods without a
                # recorded outcome get retried (already-committed ones
                # are 409s next round, which is fine).
                pass
        if by_ns or group_binds:
            _BIND_LATENCY.observe(time.monotonic() - t0)

        bind_outcome: Dict[str, str] = {}
        for pod, dest in placed:
            ns = pod.metadata.namespace or "default"
            res = outcome.get((ns, pod.metadata.name), {})
            if res.get("status") == "Success":
                pod.spec.node_name = dest
                cfg.modeler.assume_pod(pod)
                self._nominations.pop(f"{ns}/{pod.metadata.name}", None)
                _SCHEDULED.inc(result="scheduled")
                bind_outcome[self._pod_key(pod)] = "bound"
                cfg.client.record_event(
                    pod, "Scheduled",
                    f"Successfully assigned {pod.metadata.name} to {dest}",
                    source="scheduler",
                )
            elif not self._bind_retryable(res):
                _SCHEDULED.inc(result="bind_conflict")  # raced; pod is bound
                bind_outcome[self._pod_key(pod)] = "bind_conflict"
            else:
                _SCHEDULED.inc(result="bind_error")
                bind_outcome[self._pod_key(pod)] = "bind_error"
                rejected.append(pod)
        # Flight recorder: the tick's decisions (and their bounded
        # explain verdicts) land before the preemption pass so it can
        # amend the unbound pods' records with preemption verdicts.
        rows = []
        for i, (pod, dest) in enumerate(zip(pending, destinations)):
            if dest is None:
                oc = "gang_rejected" if i in denied_at else "unschedulable"
            else:
                oc = bind_outcome.get(self._pod_key(pod), "bind_error")
            rows.append((pod, dest, oc, gkey_at.get(i)))
        self._record_decisions(
            rows, nodes, services, assigned, solve_s=solve_s
        )
        # Preemption: pods the solve could not place anywhere may evict
        # lower-priority pods and hold a nomination while the victims'
        # grace drains; they bind through the ordinary solve on retry.
        unbound = [p for p, d in zip(pending, destinations) if d is None]
        if unbound:
            # Fresh occupancy view: this tick's own binds were assumed
            # into the modeler after `assigned` was captured.
            self._maybe_preempt(
                unbound, nodes, cfg.pod_lister.list(), groups=groups
            )
        self._requeue_many(rejected)
        self._sample_capacity(pending)
        _E2E_LATENCY.observe(time.monotonic() - start)
        return len(pending) + len(deferred)


class _SessionInvalidated(Exception):
    """The in-flight resolve already invalidated the session (and
    counted the failure in fallback_count); the raising tick only
    needs the fallback routing, not a second count."""


class IncrementalBatchScheduler(BatchScheduler):
    """Session-backed batch mode: cluster state stays device-resident.

    The plain BatchScheduler re-lowers the FULL cluster (every node row
    + every assigned pod) each tick — fine for draining one backlog,
    but under sustained churn the re-lowering dominates the tick and
    with it the pod-to-bind latency. This daemon keeps a SolverSession
    (ops/incremental.py): node occupancy/bitsets/service counts live on
    the accelerator across ticks, watch deltas patch single node rows,
    and each tick uploads only that tick's pending pods against the
    donated device carry.

    Reference analog: the scheduler's watch-fed caches ARE its
    incremental state (factory.go:180-193) — this lifts the same
    stay-in-sync-by-deltas design onto device-resident arrays.

    Consistency contract: any surprise (vocab/slot overflow ->
    RebuildRequired, device error, scalar fallback, service-set change)
    invalidates the session; the next tick rebuilds it from the
    authoritative watch caches. Handlers are idempotent, so replaying
    an event already reflected in a freshly built session is harmless.

    Micro-tick cadence (the latency path): with ``microticks`` on (the
    default), the drain is EVENT-driven — a single wake event fed by
    FIFO arrivals, watch deltas, and commit releases replaces the
    fixed-period drain, so an idle daemon solves a lone pod the moment
    it arrives, while under churn the solve time itself coalesces
    arrivals (plus an adaptive ``batch_window`` once ``coalesce_min``
    pods drain instantly). The tick pipeline overlaps three stages:
    tick k's ``bind_bulk`` HTTP commits run on a dedicated commit
    worker while tick k+1's jitted solve runs on device and tick k+2's
    pods stage on the host (``SolverSession.solve_async``). Decision /
    SLI milestone order is preserved — the commit worker is a single
    FIFO thread. ``prewarm_buckets`` compiles the small pod-bucket
    executables at session build so a fresh bucket never stalls a live
    tick.
    """

    def __init__(
        self,
        config: SchedulerConfig,
        pod_bucket: int = 0,
        prewarm_buckets: int = 0,
        microticks: bool = True,
        coalesce_min: int = 64,
        commit_depth: int = 4,
        **kw,
    ):
        super().__init__(config, **kw)
        if self.policy_scalar or self.spec is not None:
            # Non-default policy: the session solver replays only the
            # default pipeline; stay on the parent's per-tick path.
            raise ValueError(
                "incremental batch mode supports the default policy only"
            )
        import collections

        self.pod_bucket = pod_bucket  # fixed tick upload bucket (0=pow2)
        self.prewarm_buckets = prewarm_buckets  # 0 = no pre-warm
        self.microticks = microticks
        # Instantaneously-drained pods at/above which the adaptive
        # coalescing window engages (below it: solve immediately).
        self.coalesce_min = coalesce_min
        self._session = None
        self._event_q: "collections.deque" = collections.deque()
        # Session releases the commit worker requests (409/bind-error
        # rollbacks): applied on the solve loop, never cross-thread.
        self._release_q: "collections.deque" = collections.deque()
        # One wake event, many feeds: FIFO arrivals, cluster deltas,
        # commit releases — the micro-tick drain waits on THIS instead
        # of polling pop(timeout).
        self._wake = threading.Event()
        config.pod_queue.attach_wake(self._wake)
        # Bounded commit pipeline: depth>0 keeps backpressure — a solve
        # loop outrunning the API plane blocks on put() instead of
        # growing an unbounded bind backlog.
        self._commit_q: "queue.Queue" = queue.Queue(maxsize=commit_depth)
        self._commit_thread: Optional[threading.Thread] = None
        # Deferred bound-pod explain contexts, newest-win (worker-idle
        # drain attaches the tables post-hoc once the loop has been
        # quiet for _EXPLAIN_QUIET_S).
        self._deferred_explain: "collections.deque" = collections.deque(
            maxlen=4
        )
        self._last_busy_mono = 0.0
        # Duty-cycle baseline: when the previous tick's solve resolved
        # (utils/profiler.py — the tick "period" is resolve-to-resolve).
        self._last_tick_resolved_mono = 0.0
        # The dispatched-but-unresolved tick: (PendingSolve, ctx).
        self._inflight = None
        self._inflight_keys: frozenset = frozenset()
        config.cluster_events = self._on_cluster_event

    # Called from reflector threads: enqueue + wake only.
    def _on_cluster_event(self, kind: str, etype: str, obj) -> None:
        self._event_q.append((kind, etype, obj))
        if (kind == "node" and etype == "ADDED") or (
            kind == "pod" and etype == "DELETED"
        ):
            # Capacity freed: release backoff waiters so the backlog
            # contests it the tick it appears. Deliberately NOT node
            # MODIFIED — kubelet status heartbeats arrive every few
            # seconds per node and would defeat the backoff entirely
            # (a cordon lift rides the ordinary backoff deadline).
            self._capacity_freed()
        self._wake.set()

    # -- commit pipeline ----------------------------------------------

    def start(self) -> "IncrementalBatchScheduler":
        if self.microticks and self._commit_thread is None:
            self._commit_thread = threading.Thread(
                target=self._commit_worker, daemon=True
            )
            self._commit_thread.start()
        return super().start()  # type: ignore[return-value]

    def stop(self) -> None:
        self._stop.set()
        super().stop()
        # Flush the pipeline IN ORDER: queued jobs first (the worker
        # drains them), THEN the outstanding solve — its commit runs
        # inline now that _stop is set, and committing it while the
        # worker still held earlier jobs would race and reorder ticks.
        # If the run thread outlived the join (wedged in a compile),
        # do NOT touch its in-flight state from this thread — an
        # unsynchronized double resolve would double-charge host rows
        # and double-issue binds.
        if self._thread is None or not self._thread.is_alive():
            try:
                self._flush_commits()
                self._resolve_inflight()
            except Exception:
                _LOG.debug(
                    "in-flight solve flush on stop failed", exc_info=True
                )
        else:
            _LOG.warning(
                "scheduler run thread still alive at stop; leaving its "
                "in-flight tick unresolved"
            )
        worker = self._commit_thread
        if worker is not None:
            self._commit_thread = None
            self._commit_q.put(None)
            worker.join(timeout=10)

    def kill(self) -> None:
        """Abrupt-death analog of stop() — the chaos harness's kill -9
        (tools/soak.py, the restart-invariant tests). Queued commit
        jobs are DROPPED unexecuted and the in-flight solve abandoned:
        a dead process commits nothing, so there is deliberately no
        flush here. The session keeps charges for pods that never
        bound; recovery is a FRESH daemon rebuilding its SolverSession
        from LIST+watch."""
        self._stop.set()
        try:
            while True:
                self._commit_q.get_nowait()
                self._commit_q.task_done()
        except queue.Empty:
            pass
        self._commit_q.put(None)
        if self._thread is not None:
            self._thread.join(timeout=10)
        worker = self._commit_thread
        if worker is not None:
            self._commit_thread = None
            worker.join(timeout=10)

    @property
    def _pipelined(self) -> bool:
        """True while commits may ride the worker thread and solves may
        stay in flight across ticks. Manual schedule_batch() calls on a
        non-started daemon run fully synchronously."""
        t = self._commit_thread
        return (
            self.microticks
            and t is not None
            and t.is_alive()
            and not self._stop.is_set()
        )

    def _commit_worker(self) -> None:
        while True:
            try:
                job = self._commit_q.get(timeout=0.1)
            except queue.Empty:
                # Idle gap: attach deferred bound-pod verdict tables
                # (runs concurrently with the solve loop — on a busy
                # box GIL contention beats serializing the dispatch
                # onto the bind path).
                self._run_deferred_explain()
                continue
            try:
                if job is None:
                    return
                self._commit_job(job)
            except Exception:
                _LOG.exception("commit pipeline job failed")
            finally:
                self._commit_q.task_done()

    def _flush_commits(self) -> None:
        """Barrier: every queued commit job has executed. Used before a
        session rebuild — the rebuilt snapshot reads the pod lister,
        and a bind the worker has not committed yet would otherwise be
        in neither the caches nor the modeler's assumptions."""
        t = self._commit_thread
        if t is not None and t.is_alive():
            self._commit_q.join()

    def _release(self, key: str) -> None:
        """Route a session charge release (bind conflict/rollback) back
        to the solve loop; the session is single-threaded by design."""
        self._release_q.append(key)
        self._wake.set()

    def _drain_releases(self) -> None:
        while self._release_q:
            key = self._release_q.popleft()
            if self._session is not None:
                self._session.delete_assigned(key)

    def _resolve_inflight(self, prefer_inline: bool = False) -> int:
        """Block on the outstanding tick's readback (if any), then hand
        its commit job to the pipeline. Returns the pods resolved.
        prefer_inline: the caller has no further work queued (idle
        resolve) — committing on THIS thread skips a GIL handoff to
        the worker, which on small hosts costs more than it overlaps;
        honored only when the worker has nothing in flight (order)."""
        inflight, self._inflight = self._inflight, None
        self._inflight_keys = frozenset()
        if inflight is None:
            return 0
        handle, ctx = inflight
        try:
            results = handle.result()
            self._observe_device_profile(handle)
        except Exception:
            # Device/readback failure mid-pipeline: invalidate the
            # session and send the tick's pods back through the queue
            # (the next tick rebuilds and re-solves them).
            self._session = None
            self.fallback_count += 1
            for pod in ctx["pending"]:
                self.config.pod_queue.add(pod)
            return 0
        self._finish_tick(
            handle._session, results, ctx,
            ctx.get("stage_s", 0.0) + handle.dispatch_s + handle.block_s,
            prefer_inline=prefer_inline,
        )
        return len(ctx["pending"])

    def _observe_device_profile(self, handle) -> None:
        """Per-tick device-time accounting (utils/profiler.py): the
        in-flight window (solve dispatch -> PendingSolve.result()) over
        the resolve-to-resolve tick period gives the duty cycle; the
        blocked readback share of that window gives the realized
        solve/commit overlap. Empty handles (idle flushes) observe
        nothing — they had no device work to account."""
        if not handle.pending:
            return
        start = getattr(handle, "dispatched_mono", 0.0)
        end = getattr(handle, "resolved_mono", 0.0)
        if not start or not end or end <= start:
            return
        prev = self._last_tick_resolved_mono
        self._last_tick_resolved_mono = end
        if not prev or end <= prev:
            # First tick (or clock wobble): no period to divide by —
            # baseline only. Observing device_s/device_s here would
            # inject a phantom 1.0 duty sample per daemon instance,
            # which a short run's p99 then reads as full saturation.
            return
        profiler.observe_tick(end - start, end - prev, handle.block_s)

    def _finish_tick(
        self, session, results, ctx, solve_s, prefer_inline=False
    ) -> None:
        """Shared tick epilogue: convergence stats + solve latency onto
        the ctx, then the commit submission (worker or inline) — one
        implementation for the gang, synchronous, and resolved-
        pipelined tick shapes."""
        ctx["solve_s"] = solve_s
        stats = dict(getattr(session, "last_stats", {}) or {})
        stats["incremental"] = True
        ctx["stats"] = stats
        _ALGO_LATENCY.observe(solve_s)
        self._submit_commit(results, ctx, prefer_inline=prefer_inline)
        # Post-tick capacity sample off the session host mirror — the
        # matrices this very tick solved against, no re-staging.
        self._sample_capacity(ctx.get("pending"))

    def _submit_commit(self, results, ctx, prefer_inline=False) -> None:
        if self._pipelined and not (
            prefer_inline and self._commit_q.unfinished_tasks == 0
        ):
            self._commit_q.put((results, ctx))
        else:
            self._commit_job((results, ctx))
            self._drain_releases()

    def prewarm(self) -> None:
        """Build the session (and pre-compile its executables when
        prewarm_buckets is set) NOW — callers that know traffic is
        coming invoke this before start() so the first pod pays neither
        the build nor a bucket compile."""
        if self._session is None:
            self._session = self._build_session()

    def _explain_shed(self) -> bool:
        # On the pipelined path, bound-pod verdict capture ALWAYS
        # defers: the explain readback is a device dispatch of its own
        # (~45ms on CPU hosts) and even a "cluster looks quiet right
        # now" inline capture lands squarely on the next arrival's
        # bind latency. Unbound pods still capture inline (operators
        # explain THOSE); bound tables attach post-hoc from the commit
        # worker's idle drain. Manual (non-started) ticks keep the
        # synchronous full capture.
        return self._pipelined

    def _queue_deferred_explain(self, ctx) -> None:
        self._deferred_explain.append(ctx)

    #: Seconds the solve loop must be quiet before deferred bound-pod
    #: tables attach — the capture's Python-side snapshot build would
    #: otherwise contend (GIL) with live ticks on small hosts.
    _EXPLAIN_QUIET_S = 0.5

    def _run_deferred_explain(self) -> None:
        """Worker-idle half of verdict capture: attach bound-pod
        tables to Decision records already in the ring, but only once
        the solve loop has been quiet for a beat. Best-effort by
        design — the deque is bounded (newest ticks win: a cluster
        saturated forever keeps only its latest tables), and the
        occupancy view is read at attach time, so tables reflect the
        cluster as of shortly after the bind (the exact pre/post-solve
        states remain on the synchronous path). Unbound pods never
        wait on this — their tables capture inline."""
        if not self._deferred_explain:
            return
        if (
            time.monotonic() - self._last_busy_mono < self._EXPLAIN_QUIET_S
            or self._inflight is not None
        ):
            return
        try:
            ctx = self._deferred_explain.popleft()
        except IndexError:
            return
        rows, decisions, nodes, services, assigned_pre, limit = ctx
        try:
            with tracing.phase("explain", pods=min(len(rows), limit)):
                self._attach_verdicts(
                    rows, decisions, nodes, services, assigned_pre,
                    limit, only="bound",
                )
        except Exception:
            _LOG.debug("deferred explain capture failed", exc_info=True)

    def _build_session(self):
        from kubernetes_tpu.ops import SolverSession

        cfg = self.config
        # Drop deltas that predate the snapshot we are about to read:
        # everything already in the caches is captured by the build;
        # anything racing in lands in the queue and replays after
        # (idempotent). Clear FIRST, then read. Pending charge
        # RELEASES die with the old session too — they reference its
        # charges, and applying one to the rebuilt session (whose
        # snapshot already reflects the authoritative bindings) would
        # delete a legitimate charge and overcommit the node.
        self._event_q.clear()
        self._release_q.clear()
        nodes = cfg.nodes.store.list()
        services = cfg.service_lister.list()
        # pod_lister = scheduled cache ∪ live assumptions: pods WE just
        # bound whose watch events haven't landed yet must occupy their
        # rows in the rebuilt session (same race the scalar path's
        # modeler covers; also decodes the raw cache).
        assigned = cfg.pod_lister.list()
        # Headroom: node slots bucket up; vocab words sized for the
        # fleet's label/port/volume variety with slack for churn.
        session = SolverSession(
            nodes,
            services=services,
            assigned=assigned,
            node_capacity=max(64, int(len(nodes) * 1.25)),
            mode=self.mode,
            pod_bucket=self.pod_bucket,
        )
        if self.prewarm_buckets:
            t0 = time.monotonic()
            n = session.prewarm(self.prewarm_buckets)
            _LOG.info(
                "session pre-warm: %d executables compiled in %.1fs "
                "(pod buckets up to %d + dirty-row scatter widths)",
                n, time.monotonic() - t0, self.prewarm_buckets,
            )
        return session

    @staticmethod
    def _obj_key(obj) -> str:
        """Canonical pod key over typed pods OR wire dicts (the raw
        cache and decode_deleted paths deliver dicts). Uses the SAME
        empty-namespace normalization as columnar.pod_key (the session
        keys) and the pending-path by_key maps — one scheme, so an
        empty-namespace pod can never be silently dropped between the
        solve and the bind (ADVICE r5)."""
        if isinstance(obj, dict):
            m = obj.get("metadata", {})
            return f"{m.get('namespace') or 'default'}/{m.get('name', '')}"
        return f"{obj.metadata.namespace or 'default'}/{obj.metadata.name}"

    def _apply_events(self, session) -> bool:
        """Drain watch deltas into the session. Returns False when the
        session must be rebuilt (service set changed). Events may carry
        wire dicts (raw cache / key-only deletes): deletes use the key
        alone; foreign bound pods decode on demand."""
        while self._event_q:
            kind, etype, obj = self._event_q.popleft()
            if kind == "service":
                return False  # frozen service set: resync
            if kind == "node":
                if etype == "DELETED":
                    name = (
                        obj.get("metadata", {}).get("name", "")
                        if isinstance(obj, dict)
                        else obj.metadata.name
                    )
                    session.remove_node(name)
                else:
                    session.upsert_node(obj)
            elif kind == "pod":
                key = self._obj_key(obj)
                if etype == "DELETED":
                    session.delete_assigned(key)
                elif not session.has_assigned(key):
                    # Bound by someone else (static pod, another
                    # scheduler instance) or resync replay.
                    if isinstance(obj, dict):
                        obj = _decode_pod(obj)
                    session.add_assigned(obj)
        return True

    def _topup(self, pending: List[Pod]) -> List[Pod]:
        """Stage late arrivals into the tick about to dispatch (called
        after the previous tick's blocking resolve — anything queued
        during that block rides THIS solve). Gang-labeled pods are
        re-queued instead: they must go through the partition step at
        the next tick's head, never bypass it."""
        session = self._session
        if not self.microticks or session is None:
            return []
        room = self.max_batch - len(pending)
        if room <= 0:
            return []
        from kubernetes_tpu.scheduler import gang

        q = self.config.pod_queue
        seen = {self._obj_key(p) for p in pending}
        # The staged batch solves in priority-sorted array order (the
        # mechanism that holds a nominated pod's freed capacity): a
        # late arrival may only APPEND if it doesn't outrank the
        # batch's floor — a higher-priority pod waits one tick and
        # heads the next sorted drain instead of solving behind
        # lower-priority pods.
        floor = min(
            ((p.spec.priority or 0) for p in pending), default=0
        )
        extra: List[Pod] = []
        while len(extra) < room:
            pod = q.pop(timeout=0.0)
            if pod is None:
                break
            try:
                if pod.spec.node_name:
                    continue
                if gang.pod_group_name(pod) or (
                    (pod.spec.priority or 0) > floor
                ):
                    q.add(pod)
                    break
                key = self._obj_key(pod)
                if (
                    key in seen
                    or key in self._inflight_keys
                    or session.has_assigned(key)
                ):
                    continue
                seen.add(key)
                session.add_pending(pod)
                extra.append(pod)
            except Exception:
                # A popped pod must never be lost: it is either staged
                # (in `extra`, requeued by the caller's fallback) or
                # back in the queue before the error propagates.
                q.add(pod)
                raise
        return extra

    def _sweep(self) -> List[Pod]:
        """Non-blocking drain of everything already queued (micro-tick
        shape: never wait for stragglers — the solve itself coalesces
        arrivals under churn)."""
        q = self.config.pod_queue
        batch: List[Pod] = []
        while len(batch) < self.max_batch:
            pod = q.pop(timeout=0.0)
            if pod is None:
                break
            batch.append(pod)
        return batch

    def _drain(self, timeout: Optional[float]) -> List[Pod]:
        if not self.microticks:
            return super()._drain(timeout)
        # Event-driven micro-tick drain: sweep what is queued; if
        # nothing is, wait on the wake event (FIFO arrival, watch
        # delta, commit release) instead of a fixed-period pop — a lone
        # pod on an idle cluster solves the moment its watch event
        # lands. With a solve in flight, never block: the caller must
        # resolve it (its readback has been overlapping this wait).
        batch = self._sweep()
        if not batch:
            if self._inflight is not None:
                return []
            self._wake.clear()
            batch = self._sweep()  # re-check after clear: no lost wake
            if not batch:
                if not self._wake.wait(timeout):
                    return []
                batch = self._sweep()
                if not batch:
                    return []
        # A cleared wake now means "no arrivals since this drain" —
        # the explain-shed pressure signal reads it; clearing is safe
        # because every consumer (sweep, release/delta drains) re-
        # checks its queue each tick rather than relying on the event.
        self._wake.clear()
        if (
            len(batch) >= self.coalesce_min
            and len(batch) < self.max_batch
            and self.batch_window > 0
        ):
            # Churn regime: the instantaneous sweep was busy, so pay a
            # short coalescing window to amortize the solve — bounded
            # by max_batch exactly like the fixed-period drain.
            deadline = time.monotonic() + self.batch_window
            while len(batch) < self.max_batch:
                wait = deadline - time.monotonic()
                if wait <= 0:
                    break
                pod = self.config.pod_queue.pop(timeout=wait)
                if pod is None:
                    break
                batch.append(pod)
        batch = [p for p in batch if not p.spec.node_name]
        batch.sort(key=lambda p: -(p.spec.priority or 0))
        return batch

    def schedule_batch(self, timeout: Optional[float] = 0.5) -> int:
        t_drain = time.monotonic()
        # Every-tick telemetry sample — see BatchScheduler.schedule_batch.
        self._observe_informer_staleness()
        sli.observe_device_telemetry()
        pending = self._drain(timeout)
        if not pending:
            # Flush the in-flight tick first: its readback has been
            # overlapping the wait that just came back empty. Nothing
            # else is queued, so commit inline — no worker handoff.
            self._resolve_inflight(prefer_inline=True)
            # Keep the session current while idle so the next burst
            # doesn't pay a rebuild.
            if self._session is not None:
                try:
                    self._drain_releases()
                    if not self._apply_events(self._session):
                        self._session = None
                except Exception:
                    # RebuildRequired, decode error, anything — the
                    # consumed delta is gone, so the session can no
                    # longer be trusted.
                    self._session = None
            elif self.prewarm_buckets and self.config.wait_for_sync(0):
                # Idle + no session + pre-warm configured: build NOW so
                # the first pod pays neither the build nor a compile.
                try:
                    self._session = self._build_session()
                except Exception:
                    _LOG.debug("eager session build failed", exc_info=True)
                    self._session = None
            else:
                # No session to apply them to, and the next build
                # snapshots the caches anyway: don't let deltas pile
                # up unboundedly in a quiet cluster.
                self._event_q.clear()
            self._refresh_capacity_idle()
            return 0
        with tracing.trace(
            "schedule_batch",
            pods=(p.metadata.name for p in pending),
            start=t_drain,
        ) as tr:
            tr.child(
                "enqueue", start=t_drain, end=time.monotonic(),
                pods=len(pending), mode=self.mode, incremental=True,
            )
            return self._session_solve_and_commit(pending)

    def _session_solve_and_commit(self, pending: List[Pod]) -> int:
        cfg = self.config
        start = time.monotonic()
        self._last_busy_mono = start  # gates the deferred explain drain
        try:
            t0 = time.monotonic()
            if self._session is None:
                # A stale in-flight handle (its session was invalidated
                # by a failed tick) must commit before the rebuild
                # snapshots the pod lister, or its binds double-book.
                self._resolve_inflight()
                self._flush_commits()
                self._session = self._build_session()
            # Capacity baseline for this tick's retry backoffs: sampled
            # BEFORE the delta drain, so a victim exiting after this
            # point releases the tick's rejects immediately even if the
            # bump lands before their requeue worker starts waiting.
            with self._capacity_cond:
                epoch = self._capacity_epoch
            self._drain_releases()
            if not self._apply_events(self._session):
                self._resolve_inflight()
                self._flush_commits()
                self._session = self._build_session()
            groups = self._gang_groups(pending)
            deferred: List[Pod] = []
            if groups is None:
                # PodGroup specs unresolvable this tick: defer the
                # grouped pods rather than scheduling them per-pod.
                pending, deferred = self._split_deferred_gangs(pending)
                self._requeue_many(deferred)
                groups = []
            # A drained pod may have been bound ELSEWHERE since it was
            # queued (another scheduler instance; HA failover overlap)
            # — its watch event just charged the session. Feeding it to
            # solve() would double-charge and orphan the true charge
            # when the 409 rollback fires. A pod still IN FLIGHT from
            # the previous dispatch is equally off-limits: its first
            # placement has not landed yet.
            with tracing.phase("lower", pods=len(pending)):
                for pod in pending:
                    key = (
                        f"{pod.metadata.namespace or 'default'}/"
                        f"{pod.metadata.name}"
                    )
                    if (
                        key not in self._inflight_keys
                        and not self._session.has_assigned(key)
                    ):
                        self._session.add_pending(pod)
            ctx = {
                "pending": pending,
                "deferred": len(deferred),
                "groups": groups,
                "gkey_of": {
                    f"{pending[i].metadata.namespace or 'default'}/"
                    f"{pending[i].metadata.name}": g.key
                    for g in groups
                    for i in g.indices
                },
                "denied_keys": set(),
                "start": start,
                "epoch": epoch,
            }
            if groups:
                from kubernetes_tpu.ops import SessionGang
                from kubernetes_tpu.scheduler.gang import OUTCOMES

                # Gang ticks run synchronously: the all-or-nothing
                # acceptance loop re-solves to a fixed point, so the
                # previous tick must be fully resolved first.
                self._resolve_inflight()
                if self._session is None:
                    # The resolve failed and invalidated the session
                    # (its own pods are already requeued): this tick
                    # falls through to the full-relower fallback.
                    raise _SessionInvalidated(
                        "session invalidated during in-flight resolve"
                    )
                gangs = [
                    SessionGang(
                        key=g.key,
                        min_member=g.min_member,
                        bound=g.bound,
                        pod_keys=frozenset(
                            f"{pending[i].metadata.namespace or 'default'}/"
                            f"{pending[i].metadata.name}"
                            for i in g.indices
                        ),
                    )
                    for g in groups
                ]
                results, denied_keys = self._session.solve_gang(gangs)
                ctx["denied_keys"] = set(denied_keys)
                for g in gangs:
                    OUTCOMES.inc(
                        outcome=(
                            "rejected" if g.key in ctx["denied_keys"]
                            else "accepted"
                        )
                    )
                self._finish_tick(
                    self._session, results, ctx, time.monotonic() - t0
                )
                return len(pending) + len(deferred)
            # Pipelined dispatch: resolve the PREVIOUS tick (its
            # readback overlapped this tick's drain/stage and its
            # commit now rides the worker, overlapping THIS solve),
            # then enqueue this tick's jitted solve and return without
            # a host sync — the next drain overlaps its device time.
            self._resolve_inflight()
            if self._session is None:
                # See the gang branch: a failed resolve invalidated
                # the session; this tick goes through the fallback.
                raise _SessionInvalidated(
                    "session invalidated during in-flight resolve"
                )
            # Top-up: pods that arrived WHILE the resolve blocked join
            # this tick instead of waiting out another solve — under
            # saturation (solve time >= inter-arrival) this is what
            # makes the batch size track the solve time instead of
            # pinning every tick at one pod.
            pending = pending + self._topup(pending)
            ctx["pending"] = pending
            ctx["stage_s"] = time.monotonic() - t0
            handle = self._session.solve_async()
            if self._pipelined:
                self._inflight = (handle, ctx)
                self._inflight_keys = frozenset(handle.keys)
                return len(pending) + len(deferred)
            results = handle.result()
            self._observe_device_profile(handle)
            self._finish_tick(
                self._session, results, ctx,
                ctx["stage_s"] + handle.dispatch_s + handle.block_s,
            )
            return len(pending) + len(deferred)
        except Exception as e:
            # RebuildRequired, device error, anything: invalidate and
            # fall back to the parent's full-relower tick (which itself
            # falls back to scalar if the device path is down). An
            # in-flight solve MUST commit (and the worker drain) first
            # — the fallback snapshots the pod lister, and uncommitted
            # binds would let it double-book their capacity.
            try:
                self._resolve_inflight()
                self._flush_commits()
            except Exception:
                _LOG.debug(
                    "in-flight flush before fallback failed",
                    exc_info=True,
                )
            self._session = None
            if not isinstance(e, _SessionInvalidated):
                # _SessionInvalidated's failure was already counted by
                # the resolve that raised it.
                self.fallback_count += 1
            for pod in pending:
                cfg.pod_queue.add(pod)
            return super().schedule_batch(timeout=0.0)

    def _commit_job(self, job) -> None:
        """Commit one resolved tick: bulk binds, events, flight-
        recorder/SLI records, the preemption pass, and requeues. Runs
        on the commit worker thread when the pipeline is live (the
        HTTP round-trips overlap the next tick's solve) and inline
        otherwise. Jobs execute in tick order — the worker is one FIFO
        thread — so no decision/SLI milestone is lost or reordered.
        NEVER touches the session: charge releases are routed back to
        the solve loop via _release()."""
        # Chaos seam: the daemon "dies" between solve and commit — the
        # job raises before any bind lands, the session keeps charges
        # for pods that never bound, and recovery is a daemon restart
        # that rebuilds its SolverSession from LIST+watch (the soak
        # harness's daemon-restart-mid-gang epoch).
        faults.fire(faults.SCHED_COMMIT_CRASH)
        results, ctx = job
        cfg = self.config
        gkey_of: Dict[str, str] = ctx["gkey_of"]
        denied_keys = ctx["denied_keys"]
        by_key = {
            f"{p.metadata.namespace or 'default'}/{p.metadata.name}": p
            for p in ctx["pending"]
        }
        by_ns: Dict[str, List] = {}
        group_binds: Dict[str, Tuple[str, List[Tuple[str, str]]]] = {}
        placed: List[Tuple[Pod, str]] = []
        rejected: List[Pod] = []
        for key, dest in results:
            pod = by_key.get(key)
            if pod is None:
                continue
            if dest is None:
                _SCHEDULED.inc(result="unschedulable")
                gkey = gkey_of.get(key)
                message = (
                    f'pod group "{gkey}" rejected: fewer than minMember '
                    "pods schedulable"
                    if gkey in denied_keys
                    else "no node fits"
                )
                cfg.client.record_event(
                    pod, "FailedScheduling", message, source="scheduler"
                )
                rejected.append(pod)
                continue
            ns = pod.metadata.namespace or "default"
            gkey = gkey_of.get(key)
            if gkey is not None:
                group_binds.setdefault(gkey, (ns, []))[1].append(
                    (pod.metadata.name, dest)
                )
            else:
                by_ns.setdefault(ns, []).append((pod.metadata.name, dest))
            placed.append((pod, dest))

        t0 = time.monotonic()
        outcome: Dict[Tuple[str, str], dict] = {}
        with tracing.phase("bind", pods=len(placed)):
            try:
                for ns, items in by_ns.items():
                    bind_results = cfg.binder.bind_bulk(items, namespace=ns)
                    for (pod_name, _dest), res in zip(items, bind_results):
                        outcome[(ns, pod_name)] = res
                self._bind_groups_atomic(group_binds, outcome)
            except Exception:
                pass  # unrecorded outcomes retry; dupes 409 next round
        if by_ns or group_binds:
            _BIND_LATENCY.observe(time.monotonic() - t0)

        bind_outcome: Dict[str, str] = {}
        for pod, dest in placed:
            ns = pod.metadata.namespace or "default"
            key = f"{ns}/{pod.metadata.name}"
            res = outcome.get((ns, pod.metadata.name), {})
            if res.get("status") == "Success":
                pod.spec.node_name = dest
                cfg.modeler.assume_pod(pod)
                self._nominations.pop(key, None)
                _SCHEDULED.inc(result="scheduled")
                bind_outcome[key] = "bound"
                cfg.client.record_event(
                    pod, "Scheduled",
                    f"Successfully assigned {pod.metadata.name} to {dest}",
                    source="scheduler",
                )
            elif not self._bind_retryable(res):
                # Raced: someone else bound it. The session charged OUR
                # placement; release it — the true binding arrives via
                # the scheduled-pods watch and re-charges the right row.
                self._release(key)
                _SCHEDULED.inc(result="bind_conflict")
                bind_outcome[key] = "bind_conflict"
            else:
                # Bind error OR the gang's atomic batch rolled back
                # (409 Aborted): release the session charge and retry.
                self._release(key)
                _SCHEDULED.inc(result="bind_error")
                bind_outcome[key] = "bind_error"
                rejected.append(pod)
        # Flight recorder: this tick's decisions + convergence stats
        # (pre-solve occupancy is derived inside — the raw scheduled
        # cache only decodes when verdict capture is on). Runs before
        # the preemption pass so it can amend the unbound records.
        rows = []
        for key, dest in results:
            pod = by_key.get(key)
            if pod is None:
                continue
            if dest is None:
                oc = (
                    "gang_rejected"
                    if gkey_of.get(key) in denied_keys
                    else "unschedulable"
                )
            else:
                oc = bind_outcome.get(key, "bind_error")
            rows.append((pod, dest, oc, gkey_of.get(key)))
        self._record_decisions(
            rows, cfg.nodes.store.list(), cfg.service_lister.list(),
            None, solve_s=ctx.get("solve_s", 0.0),
            stats=ctx.get("stats") or {"incremental": True},
        )
        # Preemption over this tick's unplaceable pods — same pass as
        # the parent daemon; the session is not consulted (victims are
        # selected from the watch caches, and their exits flow back in
        # as ordinary pod DELETED deltas).
        unbound = [
            by_key[key]
            for key, dest in results
            if dest is None and key in by_key
        ]
        if unbound:
            self._maybe_preempt(
                unbound, cfg.nodes.store.list(), cfg.pod_lister.list(),
                groups=ctx["groups"],
            )
        self._requeue_many(rejected, epoch=ctx.get("epoch"))
        _E2E_LATENCY.observe(time.monotonic() - ctx["start"])
