"""Gang scheduling: PodGroup partitioning + all-or-nothing acceptance.

The paper's batch solver materializes the whole pending backlog as
pod x node matrices, which makes group-level ("gang" / co-scheduling)
feasibility a per-group segment reduction over arrays it already holds
— something the reference's one-pod-at-a-time loop
(plugin/pkg/scheduler/scheduler.go) cannot express without
backtracking. Multi-host TPU training jobs need it: a 16-host slice
job with 15 pods bound deadlocks the cluster (Gandiva/Tiresias-style
DL schedulers solve the same problem; see PAPERS.md).

Mechanics:

- pods join a group via the POD_GROUP_LABEL label naming a PodGroup in
  their namespace (models/objects.py; admission gates membership);
- `partition_backlog` splits a drained backlog into GangGroups, each
  carrying the group's minMember and the count of members ALREADY
  bound (earlier ticks count toward the gang);
- `gang_solve` wraps any backlog solver (scalar oracle, device scan,
  wave, sinkhorn, sidecar) in the acceptance loop: solve, reduce
  per-group placed counts (host numpy by default; the device path
  passes ops.pipeline.gang_member_counts_device — a masked segment
  reduction over the solver's own arrays), atomically reject every
  group short of minMember, release its tentative placements by
  RE-SOLVING the surviving backlog from scratch, and repeat to a fixed
  point. Re-solving (rather than patching assignments) is what keeps
  the scalar and device paths decision-parity: the sequential policy's
  downstream choices depend on the full committed prefix.

Commits ride bind_bulk(atomic=True): a mid-batch conflict rejects the
whole group server-side instead of leaving stragglers bound.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.objects import POD_GROUP_LABEL, Pod
from kubernetes_tpu.utils import metrics, tracing

#: Group-level solve outcomes. accepted/rejected come from the solve's
#: acceptance loop; bind_rollback from an atomic commit that conflicted
#: server-side; timeout from the gang lifecycle controller.
OUTCOMES = metrics.DEFAULT.counter(
    "gang_solve_outcomes_total",
    "PodGroup gang outcomes by kind",
    ("outcome",),
)

PHASE_PENDING = "Pending"
PHASE_SCHEDULED = "Scheduled"
PHASE_UNSCHEDULABLE = "Unschedulable"


def pod_group_name(pod: Pod) -> str:
    """The PodGroup this pod belongs to ('' = ungrouped)."""
    return (pod.metadata.labels or {}).get(POD_GROUP_LABEL, "")


def pod_is_live(pod: Pod) -> bool:
    """Gang membership counts LIVE pods only: terminal pods keep their
    label and nodeName but no longer hold a slot or satisfy the floor —
    crediting a Failed member as 'bound' would let its replacement bind
    solo below minMember (the partial co-run gangs exist to prevent).
    Mirrors the admission plugin's maxMember counting rule."""
    return (
        pod.status.phase not in ("Succeeded", "Failed")
        and not pod.metadata.deletion_timestamp
    )


def group_key(namespace: str, name: str) -> str:
    return f"{namespace or 'default'}/{name}"


@dataclass
class GangGroup:
    """One PodGroup's slice of a drained backlog."""

    key: str  # "namespace/name"
    name: str
    namespace: str
    min_member: int
    indices: List[int] = field(default_factory=list)  # positions in pending
    bound: int = 0  # members already bound (count toward minMember)


def partition_backlog(
    pending: Sequence[Pod],
    assigned: Sequence[Pod] = (),
    min_member_of: Optional[Callable[[str, str], Optional[int]]] = None,
) -> List[GangGroup]:
    """Partition a backlog into its gang groups (ungrouped pods are
    simply absent). `min_member_of(namespace, name)` resolves a group's
    declared minMember; None (unknown group — admission normally
    prevents this, but the scheduler must not wedge on a deleted
    PodGroup) degrades the group to minMember 0, i.e. ordinary
    per-pod scheduling. Already-bound members from `assigned` count
    toward the gang: a group partially bound by an earlier tick only
    needs the remainder."""
    groups: Dict[str, GangGroup] = {}
    for i, pod in enumerate(pending):
        name = pod_group_name(pod)
        if not name:
            continue
        ns = pod.metadata.namespace or "default"
        key = group_key(ns, name)
        g = groups.get(key)
        if g is None:
            mm = min_member_of(ns, name) if min_member_of is not None else None
            g = groups[key] = GangGroup(
                key=key, name=name, namespace=ns, min_member=int(mm or 0)
            )
        g.indices.append(i)
    if groups:
        for pod in assigned:
            name = pod_group_name(pod)
            if not name or not pod.spec.node_name or not pod_is_live(pod):
                continue
            g = groups.get(group_key(pod.metadata.namespace or "default", name))
            if g is not None:
                g.bound += 1
    return [groups[k] for k in sorted(groups)]


def drop_partial_gang_preemptions(
    unbound: Sequence[Pod],
    candidates: Sequence[Pod],
    decisions: Sequence[Optional[object]],
    covered_keys: frozenset = frozenset(),
    groups: Sequence[GangGroup] = (),
) -> Tuple[List[Optional[object]], List[str]]:
    """Gang/preemption interaction guard: a preemptor that belongs to a
    PodGroup preempts for the WHOLE gang or not at all. Victims must
    only be evicted when the gang can actually land afterwards, or pods
    die to free capacity the all-or-nothing solve then refuses to use
    and the group stays stranded half-placed. Two conditions, both
    required:

    - every unbound member visible this tick got a nomination this
      pass (or already holds one — `covered_keys`); a member excluded
      from `candidates` by priority/policy still vetoes;
    - when `groups` (the tick's partitioned GangGroups, carrying
      minMember and the already-bound credit) names the gang, the
      granted+covered+bound count must reach minMember — members
      sitting in backoff requeue are invisible to `unbound`, and a
      2-of-3 grant would evict victims for a gang the solve still
      rejects until the third member resurfaces.

    `decisions` aligns with `candidates`. Returns the filtered
    decision list and the dropped groups' keys.
    """
    from kubernetes_tpu.models.objects import pod_full_key

    need: Dict[str, set] = {}
    for pod in unbound:
        name = pod_group_name(pod)
        if name:
            key = group_key(pod.metadata.namespace or "default", name)
            need.setdefault(key, set()).add(pod_full_key(pod))
    if not need:
        return list(decisions), []
    granted = {
        pod_full_key(c): i
        for i, (c, d) in enumerate(zip(candidates, decisions))
        if d is not None
    }
    floor_of = {g.key: (g.min_member, g.bound) for g in groups}
    out = list(decisions)
    dropped: List[str] = []
    for gkey, keys in sorted(need.items()):
        ok_count = sum(1 for k in keys if k in granted or k in covered_keys)
        min_member, bound = floor_of.get(gkey, (0, 0))
        if ok_count == len(keys) and ok_count + bound >= min_member:
            continue
        had_any = False
        for k in keys:
            i = granted.get(k)
            if i is not None:
                out[i] = None
                had_any = True
        if had_any:
            dropped.append(gkey)
    return out, dropped


def member_counts_host(
    placed: np.ndarray, group_ids: np.ndarray, num_groups: int
) -> np.ndarray:
    """Host (numpy) twin of ops.matrices.gang_member_counts — the
    scalar-parity fallback's reducer."""
    mask = placed & (group_ids >= 0)
    return np.bincount(
        group_ids[mask], minlength=num_groups
    ).astype(np.int32)[:num_groups]


Solver = Callable[
    [Sequence[Pod], Sequence[object], Sequence[Pod], Sequence[object]],
    List[Optional[str]],
]


def gang_solve(
    solver: Solver,
    pending: Sequence[Pod],
    nodes,
    assigned: Sequence[Pod] = (),
    services=(),
    groups: Sequence[GangGroup] = (),
    counts_fn: Optional[Callable] = None,
) -> Tuple[List[Optional[str]], List[GangGroup], List[GangGroup]]:
    """Solve `pending` with group-level all-or-nothing acceptance.

    Returns (destinations, accepted_groups, rejected_groups) —
    destinations aligned with `pending`; every pod of a rejected group
    maps to None. Each rejection round releases the rejected group's
    tentative assignments back into the solve by re-solving the
    surviving backlog from scratch against the same cluster state, so
    capacity a rejected gang would have consumed is available to the
    rest (and the sequential decision order stays parity-exact across
    the scalar and device paths). Terminates in <= len(groups)+1
    rounds: each round either converges or rejects >= 1 more group.
    """
    counts_fn = counts_fn or member_counts_host
    n = len(pending)
    if not groups:
        return list(solver(pending, nodes, assigned, services)), [], []
    group_ids = np.full(n, -1, np.int32)
    for gi, g in enumerate(groups):
        for i in g.indices:
            group_ids[i] = gi
    destinations: List[Optional[str]] = [None] * n
    rejected: set = set()
    with tracing.span("gang", groups=len(groups), pods=n):
        while True:
            active = [i for i in range(n) if group_ids[i] not in rejected]
            dests = (
                solver([pending[i] for i in active], nodes, assigned, services)
                if active
                else []
            )
            destinations = [None] * n
            for i, d in zip(active, dests):
                destinations[i] = d
            with tracing.phase("gang_accept", groups=len(groups)):
                placed = np.fromiter(
                    (d is not None for d in destinations), bool, count=n
                )
                counts = counts_fn(placed, group_ids, len(groups))
            newly = [
                gi
                for gi, g in enumerate(groups)
                if gi not in rejected
                and int(counts[gi]) + g.bound < g.min_member
            ]
            if not newly:
                break
            rejected.update(newly)
    for gi in range(len(groups)):
        OUTCOMES.inc(outcome="rejected" if gi in rejected else "accepted")
    accepted = [g for gi, g in enumerate(groups) if gi not in rejected]
    denied = [g for gi, g in enumerate(groups) if gi in rejected]
    return destinations, accepted, denied
