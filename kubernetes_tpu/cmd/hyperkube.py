"""hyperkube: every daemon in one binary, dispatched on argv[1].

Reference: cmd/hyperkube/main.go:34-38 (hk.AddServer for apiserver,
controller-manager, scheduler, kubelet, proxy) — plus ktctl and the
local-up-cluster composition for parity with hack/local-up-cluster.sh.

Usage:
    python -m kubernetes_tpu.cmd.hyperkube <server> [flags...]
    servers: apiserver, controller-manager, scheduler, kubelet, proxy,
             ktctl, local-up-cluster
"""

from __future__ import annotations

import sys
from typing import List, Optional

from kubernetes_tpu.cmd import daemons

SERVERS = {
    "apiserver": daemons.apiserver_main,
    "controller-manager": daemons.controller_manager_main,
    "scheduler": daemons.scheduler_main,
    "kubelet": daemons.kubelet_main,
    "proxy": daemons.proxy_main,
}


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        names = sorted(SERVERS) + ["ktctl", "local-up-cluster"]
        print(f"usage: hyperkube <server> [flags]\nservers: {', '.join(names)}")
        return 0 if argv else 1
    name, rest = argv[0], argv[1:]
    if name == "ktctl":
        from kubernetes_tpu.cli.ktctl import main as ktctl_main

        return ktctl_main(rest)
    if name == "local-up-cluster":
        from kubernetes_tpu.cmd.localup import main as localup_main

        return localup_main(rest)
    fn = SERVERS.get(name)
    if fn is None:
        print(f"error: unknown server {name!r}", file=sys.stderr)
        return 1
    return fn(rest)


if __name__ == "__main__":
    sys.exit(main())
