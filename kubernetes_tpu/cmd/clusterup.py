"""Multi-host cluster composition: the cluster/ (kube-up) analog.

Reference: cluster/kube-up.sh + per-provider scripts provision a
master and N nodes, start the daemons on each, and install addons
(cluster/gce/util.sh, cluster/addons/). Here the same composition is
an inventory-driven planner with two providers:

- local:  every component runs as a hyperkube subprocess on THIS
          machine (the testable profile; hosts in the inventory are
          ignored). State (pids, ports) is recorded in the state dir
          so kube-down can tear the cluster down.
- ssh:    the same per-host command plan executed through `ssh <host>`
          (or printed with --dry-run for inspection/automation). Hosts
          must share the repo checkout at the same path.

The plan a single inventory produces:
  master host:  apiserver (--data-dir for durability) and, per
                control_plane_replicas, controller-manager + scheduler
                pairs with --leader-elect (hot standbys; the batch
                scheduler when the inventory says so)
  node hosts:   one kubelet each (process or fake runtime) + optional
                kube-proxy
  addons:       python -m kubernetes_tpu.addons (--dns/--monitoring)

Inventory (JSON):
  {"master": {"host": "10.0.0.1", "port": 8080, "data_dir": "/var/..."},
   "control_plane_replicas": 2,
   "batch_scheduler": true,
   "nodes": [{"name": "node-0", "host": "10.0.0.2"}, ...],
   "runtime": "fake" | "process",
   "addons": ["dns", "monitoring"]}
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
HYPERKUBE = os.path.join(REPO, "bin", "hyperkube")

#: argv prefix for reaching a remote host. A seam, not a constant for
#: style: tests substitute a shim that replays real ssh's semantics
#: locally (join the command words with spaces, hand the result to a
#: shell to re-parse) so the REMOTE code path — quoting, pidfile
#: daemonization, teardown-by-ssh — executes for real even on boxes
#: with no sshd (VERDICT r3 next #6).
SSH_BASE = ("ssh",)


def _ssh_argv(host: str, command_words: List[str]) -> List[str]:
    return [*SSH_BASE, host, "--", *command_words]


def load_inventory(path: str) -> dict:
    with open(path) as f:
        inv = json.load(f)
    inv.setdefault("master", {})
    inv["master"].setdefault("host", "127.0.0.1")
    inv["master"].setdefault("port", 8080)
    inv.setdefault("control_plane_replicas", 1)
    inv.setdefault("nodes", [])
    inv.setdefault("runtime", "fake")
    inv.setdefault("addons", [])
    return inv


def plan(inv: dict) -> List[Tuple[str, str, List[str]]]:
    """-> [(host, role, argv)] in start order."""
    m = inv["master"]
    server = f"http://{m['host']}:{m['port']}"
    out: List[Tuple[str, str, List[str]]] = []
    apiserver = [
        sys.executable, HYPERKUBE, "apiserver",
        "--address", "0.0.0.0" if inv["nodes"] else "127.0.0.1",
        "--port", str(m["port"]),
    ]
    if m.get("data_dir"):
        apiserver += ["--data-dir", m["data_dir"]]
    out.append((m["host"], "apiserver", apiserver))
    for i in range(int(inv["control_plane_replicas"])):
        out.append(
            (m["host"], f"controller-manager-{i}", [
                sys.executable, HYPERKUBE, "controller-manager",
                "--server", server, "--leader-elect",
                "--healthz-port", "-1",
            ])
        )
        sched = [
            sys.executable, HYPERKUBE, "scheduler",
            "--server", server, "--leader-elect", "--healthz-port", "-1",
        ]
        if inv.get("batch_scheduler"):
            sched.append("--batch")
        out.append((m["host"], f"scheduler-{i}", sched))
    for node in inv["nodes"]:
        kubelet = [
            sys.executable, HYPERKUBE, "kubelet",
            "--server", server, "--node-name", node["name"],
        ]
        if inv["runtime"] == "process":
            kubelet += ["--root-dir", node.get(
                "root_dir", f"/tmp/ktpu-{node['name']}"
            )]
        else:
            kubelet.append("--fake-runtime")
        out.append((node.get("host", "127.0.0.1"), f"kubelet-{node['name']}", kubelet))
    if inv["addons"]:
        # The addons run on the master host; other hosts reach them at
        # the master's address, so that is what gets published in the
        # Services' Endpoints (loopback would strand multi-host nodes).
        addons = [sys.executable, "-m", "kubernetes_tpu.addons",
                  "--server", server, "--publish",
                  "--endpoint-host", m["host"]]
        for a in inv["addons"]:
            addons.append(f"--{a}")
        out.append((m["host"], "addons", addons))
    return out


def _wait_healthy(server: str, timeout: float = 30.0) -> bool:
    import urllib.request

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(server + "/healthz", timeout=2) as r:
                if r.status == 200:
                    return True
        except Exception:
            pass
        time.sleep(0.3)
    return False


def up(inv: dict, state_dir: str, provider: str = "local",
       dry_run: bool = False) -> int:
    steps = plan(inv)
    if dry_run:
        for host, role, argv in steps:
            print(f"[{host}] {role}: {' '.join(argv)}")
        return 0
    os.makedirs(state_dir, exist_ok=True)
    server = f"http://{inv['master']['host']}:{inv['master']['port']}"
    state_path = os.path.join(state_dir, "cluster.json")
    state: Dict[str, dict] = {}

    def persist():
        # After EVERY start, so a kube-up crash mid-bring-up still
        # leaves kube-down something to tear down.
        with open(state_path, "w") as f:
            json.dump({"inventory": inv, "components": state}, f, indent=2)

    try:
        for host, role, argv in steps:
            remote = provider == "ssh" and host not in ("127.0.0.1", "localhost")
            info: Dict[str, object] = {"host": host, "remote": remote}
            if remote:
                # The remote side records its own pid so kube-down can
                # SIGTERM the daemon itself, not just the ssh client.
                # The script ships as ONE pre-quoted word: ssh joins its
                # argv with spaces and the remote login shell re-parses
                # the result, so an unquoted script would word-split
                # (`sh -c echo` puts $$ in $0 and blanks the pidfile).
                # Port-qualified: two clusters (or a re-run against a
                # stale /tmp) must not read each other's pids.
                pidfile = f"/tmp/ktpu-{inv['master']['port']}-{role}.pid"
                info["pidfile"] = pidfile
                script = (
                    f"echo $$ > {shlex.quote(pidfile)} && "
                    f"exec {shlex.join(argv)}"
                )
                argv = _ssh_argv(host, ["sh", "-c", shlex.quote(script)])
            log = os.path.join(state_dir, f"{role}.log")
            proc = subprocess.Popen(
                argv,
                stdout=open(log, "w"),
                stderr=subprocess.STDOUT,
                cwd=REPO,
                start_new_session=True,
            )
            info["pid"] = proc.pid
            info["log"] = log
            state[role] = info
            persist()
            print(f"started {role} (pid {proc.pid}) on {host}")
            if role == "apiserver" and not _wait_healthy(server):
                raise RuntimeError("apiserver never became healthy")
    except Exception as e:
        print(f"bring-up failed ({e}); tearing down started components",
              file=sys.stderr)
        down(state_dir)
        return 1
    print(f"cluster up: {server} ({len(steps)} components; "
          f"state in {state_dir})")
    print(f"  try: bin/ktctl get nodes --server {server}")
    return 0


def _signal_component(info: dict, sig: int) -> None:
    if info.get("remote"):
        subprocess.run(
            _ssh_argv(
                info["host"],
                [f"kill -{int(sig)} $(cat {shlex.quote(info['pidfile'])}) "
                 f"2>/dev/null || true"],
            ),
            check=False,
        )
    try:
        os.killpg(info["pid"], sig)
    except (ProcessLookupError, PermissionError):
        pass


def down(state_dir: str) -> int:
    path = os.path.join(state_dir, "cluster.json")
    if not os.path.exists(path):
        print(f"no cluster state at {path}", file=sys.stderr)
        return 1
    with open(path) as f:
        state = json.load(f)
    # Reverse order: kubelets/addons before the apiserver.
    for role, info in reversed(list(state["components"].items())):
        _signal_component(info, signal.SIGTERM)
        print(f"stopped {role} (pid {info['pid']})")
    time.sleep(0.5)
    for role, info in state["components"].items():
        _signal_component(info, signal.SIGKILL)
    os.unlink(path)
    return 0


def up_main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-up")
    p.add_argument("--inventory", "-i", required=True)
    p.add_argument("--state-dir", default=".kube-cluster")
    p.add_argument("--provider", choices=("local", "ssh"), default="local")
    p.add_argument("--dry-run", action="store_true")
    args = p.parse_args(argv)
    return up(
        load_inventory(args.inventory), args.state_dir,
        provider=args.provider, dry_run=args.dry_run,
    )


def down_main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-down")
    p.add_argument("--state-dir", default=".kube-cluster")
    args = p.parse_args(argv)
    return down(args.state_dir)
