"""Per-daemon launchers + the hyperkube multiplexer.

Reference: cmd/ (kube-apiserver, kube-scheduler, kube-controller-
manager, kubelet, kube-proxy — each a flag struct + Run()) and
cmd/hyperkube/main.go:34-38 (one binary that dispatches on argv[1]).
"""
