"""Daemon entry points with flag surfaces.

Reference: each cmd/*/app/server.go defines a <X>Server struct whose
fields are flags and a Run() that assembles the daemon
(cmd/kube-apiserver/app/server.go:82-185, cmd/kubelet/app/
server.go:252, plugin/cmd/kube-scheduler/app/server.go:49-161,
cmd/kube-proxy/app/server.go:91-132). Here each daemon is a
`main(argv) -> rc` plus a `start_*(args)` assembler the composition
layer (hyperkube / local-up-cluster) reuses in-process.
"""

from __future__ import annotations

import argparse
import signal
import threading
from typing import List, Optional

from kubernetes_tpu.client import Client, HTTPTransport


def _server_flag(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--server", "-s", default="http://127.0.0.1:8080",
        help="apiserver base URL",
    )


class HealthServer:
    """Per-daemon /healthz + /metrics listener (reference: every
    daemon mounts healthz and prometheus handlers on its own port —
    scheduler plugin/cmd/kube-scheduler/app/server.go:105-109,
    controller-manager :10252, proxy --healthz-port 10249). `checks`
    are callables returning (ok, msg); /healthz is 200 only when all
    pass."""

    def __init__(self, port: int, checks=None, host: str = "127.0.0.1"):
        import http.server

        from kubernetes_tpu.utils import metrics as metricspkg

        checks = checks or []

        class Handler(http.server.BaseHTTPRequestHandler):
            disable_nagle_algorithm = True

            def log_message(self, fmt, *a):  # noqa: N802
                pass

            def _send(self, code, payload, ctype="text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    problems = []
                    for check in checks:
                        try:
                            ok, msg = check()
                        except Exception as e:
                            ok, msg = False, f"{type(e).__name__}: {e}"
                        if not ok:
                            problems.append(msg)
                    if problems:
                        self._send(500, ("; ".join(problems)).encode())
                    else:
                        self._send(200, b"ok")
                elif self.path == "/metrics":
                    payload = metricspkg.DEFAULT.render()
                    if isinstance(payload, str):
                        payload = payload.encode()
                    self._send(200, payload, "text/plain; version=0.0.4")
                else:
                    self._send(404, b"not found")

        import http.server as hs

        self.httpd = hs.ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True,
        )

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def start(self) -> "HealthServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def _loop_alive_check(daemon):
    """Healthy while the daemon's loop thread is alive (the HA standby
    wrapper has no loop thread of its own — report ok)."""

    def check():
        t = getattr(daemon, "_thread", None)
        if t is None:
            return True, "ok"
        return t.is_alive(), "ok" if t.is_alive() else "loop not running"

    return check


def _start_health(args, checks) -> Optional[HealthServer]:
    """Bind the daemon's healthz port if enabled (<0 disables). Bind
    failure is non-fatal — a daemon must not die because its health
    port is taken."""
    port = getattr(args, "healthz_port", -1)
    if port is None or port < 0:
        return None
    try:
        srv = HealthServer(port, checks).start()
    except OSError as e:
        import sys

        print(f"warning: healthz port {port} unavailable: {e}", file=sys.stderr)
        return None
    print(f"healthz serving on 127.0.0.1:{srv.port}")
    return srv


def _wait_forever() -> None:
    stop = threading.Event()

    def handler(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    stop.wait()


# -- apiserver --------------------------------------------------------


def apiserver_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-apiserver")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument(
        "--admission-control", default="",
        help="comma-separated admission plugin names (default chain "
        "when empty)",
    )
    p.add_argument("--basic-auth-file", default="")
    p.add_argument("--token-auth-file", default="")
    p.add_argument("--authorization-policy-file", default="")
    p.add_argument(
        "--data-dir", default="",
        help="directory for the durable store (WAL + snapshots); empty "
        "keeps master state in memory only. Plays etcd's role in the "
        "reference (hack/local-up-cluster.sh:152-153).",
    )
    p.add_argument(
        "--data-fsync", dest="data_fsync", action="store_true",
        default=True,
        help="fsync WAL records before acking writes (group-committed "
        "across concurrent writers). ON by default: etcd's contract — "
        "the one the reference's checkpoint/resume story leans on — is "
        "fsync-before-ack.",
    )
    p.add_argument(
        "--no-data-fsync", dest="data_fsync", action="store_false",
        help="trade power-loss durability for write latency: WAL "
        "records flush to the OS (survives process death, NOT power "
        "loss) and acks don't wait for the disk",
    )
    p.add_argument("--tls-cert-file", default="")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument(
        "--client-ca-file", default="",
        help="CA bundle for x509 client-certificate authentication "
        "(CommonName = user, Organizations = groups; "
        "pkg/apiserver/authn.go:35)",
    )
    p.add_argument(
        "--max-requests-inflight", type=int, default=400,
        help="cap on concurrently-served non-long-running API requests "
        "(429 beyond it; 0 disables). Reference: "
        "cmd/kube-apiserver --max-requests-inflight / "
        "pkg/apiserver/handlers.go MaxInFlightLimit.",
    )
    return p


def start_apiserver(args):
    """Returns the running APIHTTPServer."""
    from kubernetes_tpu.server.api import APIServer
    from kubernetes_tpu.server.httpserver import APIHTTPServer

    store = None
    if getattr(args, "data_dir", ""):
        from kubernetes_tpu.store.kvstore import KVStore

        store = KVStore(
            data_dir=args.data_dir, fsync=getattr(args, "data_fsync", True)
        )
    api = APIServer(store=store)
    if args.admission_control:
        from kubernetes_tpu.server import admission as adm

        api.admission = adm.new_from_plugins(
            api, [n for n in args.admission_control.split(",") if n]
        )
    authenticator = authorizer = None
    if args.basic_auth_file or args.token_auth_file:
        from kubernetes_tpu.server import auth

        parts = []
        if args.basic_auth_file:
            parts.append(auth.PasswordAuthenticator.from_file(args.basic_auth_file))
        if args.token_auth_file:
            parts.append(auth.TokenAuthenticator.from_file(args.token_auth_file))
        authenticator = auth.UnionAuthenticator(parts)
    if args.authorization_policy_file:
        from kubernetes_tpu.server import auth

        authorizer = auth.ABACAuthorizer.from_file(args.authorization_policy_file)
    return APIHTTPServer(
        api,
        host=args.address,
        port=args.port,
        authenticator=authenticator,
        authorizer=authorizer,
        publish_master=True,
        max_in_flight=getattr(args, "max_requests_inflight", 400),
        tls_cert_file=getattr(args, "tls_cert_file", ""),
        tls_key_file=getattr(args, "tls_private_key_file", ""),
        client_ca_file=getattr(args, "client_ca_file", ""),
    ).start()


def apiserver_main(argv: Optional[List[str]] = None) -> int:
    args = apiserver_parser().parse_args(argv)
    srv = start_apiserver(args)
    # Health plane (retention sampler + alert engine) lives in the
    # apiserver process for the daemon topology — /debug/alerts,
    # /debug/timeseries and /debug/health read it process-locally.
    # KT_TIMESERIES=0 opts a deployment out.
    from kubernetes_tpu.client import Client, LocalTransport
    from kubernetes_tpu.utils import alerts, timeseries

    alerts.ensure_started(client=Client(LocalTransport(srv.api)))
    print(f"apiserver listening on {srv.address}")
    try:
        _wait_forever()
    finally:
        timeseries.SAMPLER.stop()
        srv.stop()
    return 0


# -- scheduler --------------------------------------------------------


def scheduler_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-scheduler")
    _server_flag(p)
    p.add_argument("--algorithm-provider", default="DefaultProvider")
    p.add_argument(
        "--policy-config-file", default="",
        help="JSON scheduler policy (plugin/pkg/scheduler/api)",
    )
    p.add_argument(
        "--batch", action="store_true",
        help="TPU batch mode: solve pending backlogs on-device. With "
        "the default policy and no sidecar this boots the ALWAYS-"
        "RESIDENT incremental session daemon (device-resident cluster "
        "state, event-driven micro-ticks, pipelined commits — the "
        "production latency path); --batch-full-relower opts back "
        "into the per-tick full-relower daemon",
    )
    p.add_argument(
        "--batch-full-relower", action="store_true",
        help="with --batch: re-lower the full cluster every tick "
        "(the pre-incremental BatchScheduler) instead of the "
        "device-resident session",
    )
    p.add_argument(
        "--prewarm-buckets", type=int, default=128,
        help="pre-compile the incremental session's solve executables "
        "for pod buckets up to this size (and the dirty-row scatter "
        "widths) at session build, so a fresh bucket never stalls a "
        "live tick; 0 disables",
    )
    p.add_argument(
        "--batch-mode", default="scan",
        choices=["scan", "wave", "sinkhorn", "auto"],
        help="scan = sequential-parity solver (default; with the "
        "pallas kernel also the fastest backlog mode on one TPU); "
        "wave = wave-commit solver (approximate decision-order "
        "parity; best sustained-churn throughput); sinkhorn = "
        "congestion-priced assignment waves (fewest device steps); "
        "auto = scan unless the solve runs over a device mesh — the "
        "daemons construct no mesh yet, so auto currently always "
        "selects scan here (docs/performance.md, mesh crossover)",
    )
    p.add_argument(
        "--solver-sidecar", default="",
        help="unix socket of a solver sidecar process "
        "(python -m kubernetes_tpu.ops.sidecar <socket>); the control "
        "plane then never touches the accelerator, and sidecar failure "
        "falls back to the scalar path",
    )
    p.add_argument(
        "--batch-incremental", action="store_true",
        help="keep cluster state device-resident across ticks "
        "(SolverSession): watch deltas patch node rows, each tick "
        "uploads only the new pending pods — the sustained-churn mode; "
        "implies --batch; default policy only",
    )
    _healthz_flag(p, 10251)
    _leader_flags(p)
    return p


def _healthz_flag(p: argparse.ArgumentParser, default: int) -> None:
    p.add_argument(
        "--healthz-port", type=int, default=default,
        help="own /healthz + /metrics port (reference per-daemon "
        "defaults: scheduler 10251, controller-manager 10252, proxy "
        "10249); negative disables",
    )


def start_scheduler(args, client=None):
    import json

    from kubernetes_tpu.scheduler.daemon import (
        BatchScheduler,
        IncrementalBatchScheduler,
        Scheduler,
        SchedulerConfig,
    )

    client = client or Client(HTTPTransport(args.server))
    policy = None
    if args.policy_config_file:
        with open(args.policy_config_file) as f:
            policy = json.load(f)
    incremental = getattr(args, "batch_incremental", False)
    # Promotion (ISSUE 12): a plain --batch request with the default
    # policy and no sidecar boots the always-resident incremental
    # session daemon — the production scheduling loop. Policy and
    # sidecar configurations keep the full-relower daemon (the session
    # replays only the default pipeline), as does an explicit
    # --batch-full-relower.
    wants_batch = (
        args.batch or args.batch_mode != "scan" or args.solver_sidecar
    )
    if (
        wants_batch
        and not incremental
        and not getattr(args, "batch_full_relower", False)
        and not policy
        and not args.solver_sidecar
    ):
        incremental = True

    def factory():
        config = SchedulerConfig(
            client, provider_name=args.algorithm_provider, policy=policy,
            raw_scheduled_cache=incremental,
        ).start()
        config.wait_for_sync()
        # --batch-mode/--solver-sidecar/--batch-incremental imply
        # --batch: silently dropping an explicit request onto the
        # scalar per-pod path is a footgun.
        if incremental:
            if policy or args.solver_sidecar:
                # Same loud failure the class itself raises: the
                # session replays only the default pipeline, and a
                # silent downgrade to full-relower mode would betray
                # the flag's promise.
                raise SystemExit(
                    "--batch-incremental supports the default policy "
                    "only (drop --policy-config-file/--solver-sidecar, "
                    "or drop --batch-incremental)"
                )
            return IncrementalBatchScheduler(
                config, mode=args.batch_mode,
                prewarm_buckets=getattr(args, "prewarm_buckets", 0),
            ).start()
        if (
            args.batch or args.batch_mode != "scan" or args.solver_sidecar
            or incremental
        ):
            return BatchScheduler(
                config,
                mode=args.batch_mode,
                sidecar_path=args.solver_sidecar or None,
            ).start()
        return Scheduler(config).start()

    return _maybe_ha(args, client, "kube-scheduler", factory)


def scheduler_main(argv: Optional[List[str]] = None) -> int:
    args = scheduler_parser().parse_args(argv)
    daemon = start_scheduler(args)
    health = _start_health(args, [_loop_alive_check(daemon)])
    print(f"scheduler running against {args.server}")
    try:
        _wait_forever()
    finally:
        daemon.stop()
        if health:
            health.stop()
    return 0


# -- controller manager ----------------------------------------------


def controller_manager_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-controller-manager")
    _server_flag(p)
    p.add_argument(
        "--cloud-provider", default="",
        help="cloud provider name (e.g. 'tpu', 'fake')",
    )
    p.add_argument("--node-grace-period", type=float, default=40.0)
    p.add_argument("--node-eviction-timeout", type=float, default=20.0)
    _healthz_flag(p, 10252)
    _leader_flags(p)
    return p


def _leader_flags(p: argparse.ArgumentParser) -> None:
    p.add_argument(
        "--leader-elect", action="store_true",
        help="run hot-standby: only the lease holder is active "
        "(contrib/pod-master analog)",
    )
    p.add_argument("--leader-elect-identity", default="")


def _maybe_ha(args, client, lock_name: str, factory):
    """Wrap a daemon factory in leader election when asked."""
    if not getattr(args, "leader_elect", False):
        return factory()
    import os
    import socket

    from kubernetes_tpu.utils.leaderelect import HAHotStandby

    identity = args.leader_elect_identity or f"{socket.gethostname()}-{os.getpid()}"
    return HAHotStandby(client, lock_name, identity, factory).start()


def start_controller_manager(args, client=None):
    from kubernetes_tpu.controllers import ControllerManager

    client = client or Client(HTTPTransport(args.server))
    provider = None
    if args.cloud_provider:
        from kubernetes_tpu import cloudprovider

        provider = cloudprovider.get_provider(args.cloud_provider)

    def factory():
        return ControllerManager(
            client,
            cloud_provider=provider,
            node_grace_period=args.node_grace_period,
            node_eviction_timeout=args.node_eviction_timeout,
        ).start()

    return _maybe_ha(args, client, "kube-controller-manager", factory)


def _manager_health_check(mgr):
    def check():
        if not hasattr(mgr, "controllers"):
            # HA hot-standby wrapper (no controllers of its own while
            # standby; the live manager is inside it when leading).
            return True, "ok"
        running = getattr(mgr, "running", True)
        n = len(mgr.controllers or [])
        if not running:
            return False, "controller manager stopped"
        return n > 0, f"{n} controllers running" if n else "no controllers"

    return check


def controller_manager_main(argv: Optional[List[str]] = None) -> int:
    args = controller_manager_parser().parse_args(argv)
    mgr = start_controller_manager(args)
    health = _start_health(args, [_manager_health_check(mgr)])
    print(f"controller-manager running against {args.server}")
    try:
        _wait_forever()
    finally:
        mgr.stop()
        if health:
            health.stop()
    return 0


# -- kubelet ----------------------------------------------------------


def kubelet_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-kubelet")
    _server_flag(p)
    p.add_argument("--node-name", required=True)
    p.add_argument("--root-dir", default="")
    p.add_argument("--manifest-dir", default="")
    p.add_argument(
        "--manifest-url", default="",
        help="poll this URL for static pod manifests (config/http.go)",
    )
    p.add_argument("--cpu", default="4")
    p.add_argument("--memory", default="8Gi")
    p.add_argument("--max-pods", type=int, default=110)
    p.add_argument(
        "--fake-runtime", action="store_true",
        help="in-memory runtime (integration testing); default is the "
        "process runtime when --root-dir is set",
    )
    p.add_argument(
        "--container-runtime", default="",
        choices=["", "fake", "process", "sandbox"],
        help="runtime backend (reference: kubelet --container_runtime "
        "docker|rkt). sandbox = namespace-isolated pods + image store "
        "(needs root + util-linux); default: process when --root-dir "
        "is set, else fake",
    )
    p.add_argument("--http-port", type=int, default=0)
    p.add_argument(
        "--cluster-dns", default="",
        help="DNS VIP injected into containers as "
        "KUBERNETES_CLUSTER_DNS (reference: kubelet --cluster-dns "
        "writes pod resolv.conf)",
    )
    p.add_argument("--cluster-domain", default="cluster.local")
    return p


def start_kubelet(args, client=None):
    from kubernetes_tpu.kubelet.agent import Kubelet
    from kubernetes_tpu.kubelet.runtime import FakeRuntime

    client = client or Client(HTTPTransport(args.server))
    choice = getattr(args, "container_runtime", "") or (
        "fake" if args.fake_runtime or not args.root_dir else "process"
    )
    if choice == "fake":
        runtime = FakeRuntime()
    elif choice == "sandbox":
        from kubernetes_tpu.kubelet.sandbox_runtime import (
            SandboxRuntime,
            sandbox_supported,
        )

        if not args.root_dir:
            raise SystemExit("--container-runtime sandbox needs --root-dir")
        if not sandbox_supported():
            raise SystemExit(
                "sandbox runtime unavailable (needs root + unshare/nsenter)"
            )
        runtime = SandboxRuntime(args.root_dir, node_name=args.node_name)
    else:
        from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime

        if not args.root_dir:
            raise SystemExit("--container-runtime process needs --root-dir")
        runtime = ProcessRuntime(args.root_dir, node_name=args.node_name)
    if getattr(args, "cluster_dns", ""):
        # Reference: --cluster-dns/--cluster-domain flow into every
        # container's resolv.conf (cmd/kubelet/app/server.go); the
        # process-runtime analog is env injection — apps dial the DNS
        # VIP directly (it is really routable under real portals).
        runtime.cluster_dns = args.cluster_dns
        runtime.cluster_domain = getattr(args, "cluster_domain", "cluster.local")
    return Kubelet(
        client,
        node_name=args.node_name,
        runtime=runtime,
        cpu=args.cpu,
        memory=args.memory,
        max_pods=args.max_pods,
        manifest_dir=args.manifest_dir or None,
        manifest_url=args.manifest_url or None,
        root_dir=args.root_dir or None,
        serve_http=True,
        http_port=args.http_port,
    ).start()


def kubelet_main(argv: Optional[List[str]] = None) -> int:
    args = kubelet_parser().parse_args(argv)
    kubelet = start_kubelet(args)
    port = kubelet.http.port if kubelet.http else "-"
    print(f"kubelet {args.node_name} running (http port {port})")
    try:
        _wait_forever()
    finally:
        kubelet.stop()
    return 0


# -- proxy ------------------------------------------------------------


def proxy_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-proxy")
    _server_flag(p)
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument(
        "--real-portals", action="store_true", default=True,
        help="install service VIPs on loopback and bind listeners at "
        "clusterIP:port (the openPortal/iptables analog; needs root, "
        "falls back to rule-table portals otherwise)",
    )
    p.add_argument(
        "--no-real-portals", dest="real_portals", action="store_false"
    )
    _healthz_flag(p, 10249)
    return p


def start_proxy(args, client=None):
    from kubernetes_tpu.proxy.config import ProxyServer

    client = client or Client(HTTPTransport(args.server))
    return ProxyServer(
        client,
        listen_ip=args.bind_address,
        real_portals=getattr(args, "real_portals", False),
    ).start()


def proxy_main(argv: Optional[List[str]] = None) -> int:
    args = proxy_parser().parse_args(argv)
    proxy = start_proxy(args)
    health = _start_health(args, [lambda: (True, "ok")])
    print(f"proxy running against {args.server}")
    try:
        _wait_forever()
    finally:
        proxy.stop()
        if health:
            health.stop()
    return 0
