"""local-up-cluster: a whole cluster in one process.

Reference: hack/local-up-cluster.sh — start etcd + apiserver +
controller-manager + scheduler + kubelet + proxy locally and print how
to talk to it. Here the store is in-process, daemons share it over
LocalTransport, and the apiserver speaks real HTTP for ktctl.
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from kubernetes_tpu.client import Client, LocalTransport


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpu-local-up-cluster")
    p.add_argument("--address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--nodes", type=int, default=2)
    p.add_argument(
        "--process-runtime", action="store_true",
        help="pods become real OS processes (default: fake runtime)",
    )
    p.add_argument(
        "--sandbox-runtime", action="store_true",
        help="pods become namespace-isolated process groups with an "
        "image store (the rkt-analog backend; needs root + util-linux)",
    )
    p.add_argument(
        "--cloud-provider", default="",
        help="register nodes from a cloud provider (e.g. 'tpu')",
    )
    p.add_argument(
        "--batch-scheduler", action="store_true",
        help="TPU-solved batch scheduling; boots the always-resident "
        "incremental session daemon (the default production path: "
        "device-resident cluster state, event-driven micro-ticks, "
        "pipelined commits) unless --batch-full-relower",
    )
    p.add_argument(
        "--batch-mode", default="scan",
        choices=["scan", "wave", "sinkhorn", "auto"],
        help="device solver mode for --batch-scheduler (scan = "
        "sequential-parity referee; wave/sinkhorn = high-throughput; "
        "auto = mesh-keyed, and with no mesh threaded through local-up "
        "it always selects scan today)",
    )
    p.add_argument(
        "--batch-incremental", action="store_true",
        help="device-resident session across scheduler ticks; implies "
        "--batch-scheduler (since ISSUE 12 this is what "
        "--batch-scheduler boots anyway — the flag remains for "
        "compatibility)",
    )
    p.add_argument(
        "--batch-full-relower", action="store_true",
        help="with --batch-scheduler: the per-tick full-relower "
        "BatchScheduler instead of the incremental session",
    )
    p.add_argument(
        "--prewarm-buckets", type=int, default=128,
        help="pre-compile the session's solve executables for pod "
        "buckets up to this size at session build (0 disables) — a "
        "fresh bucket never stalls a live tick",
    )
    p.add_argument(
        "--no-kube-proxy", dest="kube_proxy", action="store_false",
        default=True, help="skip the in-process kube-proxy",
    )
    p.add_argument(
        "--cluster-dns", action="store_true",
        help="start the DNS addon and publish it as the kube-dns "
        "service at 10.0.0.10 (cluster/addons/dns analog)",
    )
    p.add_argument(
        "--kubelet-http", action="store_true",
        help="kubelets talk to the apiserver over real HTTP instead of "
        "in-process calls (the reference's actual topology: watch "
        "fan-out, heartbeats and status writeback all cross the wire)",
    )
    return p


class LocalCluster:
    """Assembled cluster; start() everything, stop() tears down."""

    def __init__(self, args):
        from kubernetes_tpu.controllers import ControllerManager
        from kubernetes_tpu.scheduler.daemon import (
            BatchScheduler,
            IncrementalBatchScheduler,
            Scheduler,
            SchedulerConfig,
        )
        from kubernetes_tpu.server.api import APIServer
        from kubernetes_tpu.server.httpserver import APIHTTPServer

        self.args = args
        self.api = APIServer()
        self.http = APIHTTPServer(
            self.api, host=args.address, port=args.port, publish_master=True,
            max_in_flight=400,
        )
        self.kubelets = []
        self._tmp_roots = []
        self._kubelet_http = getattr(args, "kubelet_http", False)
        if not self._kubelet_http:
            # In-process transport: build now. HTTP kubelets are built
            # in start(), once the apiserver's port is known.
            self._build_kubelets(self._client)
        # Promotion (ISSUE 12): --batch-scheduler boots the always-
        # resident incremental session daemon unless the caller opts
        # back into the per-tick full relower.
        incremental = getattr(args, "batch_incremental", False) or (
            args.batch_scheduler
            and not getattr(args, "batch_full_relower", False)
        )
        self.scheduler_config = SchedulerConfig(
            self._client(), raw_scheduled_cache=incremental
        )
        if args.batch_scheduler or incremental:
            mode = getattr(args, "batch_mode", "scan")
            if incremental:
                prewarm = getattr(args, "prewarm_buckets", 0)
                self.scheduler_cls = lambda cfg: IncrementalBatchScheduler(
                    cfg, mode=mode, prewarm_buckets=prewarm
                )
            else:
                self.scheduler_cls = lambda cfg: BatchScheduler(
                    cfg, mode=mode
                )
        else:
            self.scheduler_cls = Scheduler
        self.scheduler = None
        provider = None
        if args.cloud_provider:
            from kubernetes_tpu import cloudprovider

            provider = cloudprovider.get_provider(args.cloud_provider)
        self.manager = ControllerManager(self._client(), cloud_provider=provider)

    def _client(self) -> Client:
        return Client(LocalTransport(self.api))

    def _build_kubelets(self, client_factory) -> None:
        import tempfile as _tempfile

        from kubernetes_tpu.kubelet.agent import Kubelet
        from kubernetes_tpu.kubelet.runtime import FakeRuntime

        sandbox = getattr(self.args, "sandbox_runtime", False)
        if sandbox:
            from kubernetes_tpu.kubelet.sandbox_runtime import sandbox_supported

            if not sandbox_supported():
                # Fail loudly: pods silently running UNsandboxed would
                # look isolated while providing nothing.
                raise SystemExit(
                    "--sandbox-runtime unavailable "
                    "(needs root + unshare/nsenter)"
                )
        for i in range(self.args.nodes):
            if sandbox:
                from kubernetes_tpu.kubelet.sandbox_runtime import SandboxRuntime

                root = _tempfile.mkdtemp(prefix=f"ktpu-node-{i}-")
                self._tmp_roots.append(root)
                runtime = SandboxRuntime(root, node_name=f"node-{i}")
            elif self.args.process_runtime:
                from kubernetes_tpu.kubelet.process_runtime import ProcessRuntime

                root = _tempfile.mkdtemp(prefix=f"ktpu-node-{i}-")
                self._tmp_roots.append(root)
                runtime = ProcessRuntime(root, node_name=f"node-{i}")
            else:
                runtime = FakeRuntime()
                root = None
            self.kubelets.append(
                Kubelet(
                    client_factory(),
                    node_name=f"node-{i}",
                    runtime=runtime,
                    root_dir=root,
                    serve_http=True,
                )
            )

    def start(self) -> "LocalCluster":
        self.http.start()
        if self._kubelet_http:
            from kubernetes_tpu.client import HTTPTransport

            # serialize=True: one multiplexed connection per kubelet
            # (the Go client shape) instead of one per kubelet thread —
            # at 100 kubelets the thread-per-connection apiserver would
            # otherwise carry ~5x the connection threads.
            self._build_kubelets(
                lambda: Client(HTTPTransport(self.http.address, serialize=True))
            )
        for kubelet in self.kubelets:
            kubelet.start()
        self.scheduler_config.start()
        self.scheduler_config.wait_for_sync()
        self.scheduler = self.scheduler_cls(self.scheduler_config).start()
        self.manager.start()
        # kube-proxy (hack/local-up-cluster.sh starts one too). Real
        # portals when we can install VIPs on loopback (root), so
        # service cluster IPs are actually dialable by any process —
        # e.g. the guestbook frontend using REDIS_MASTER_SERVICE_HOST.
        self.proxy = None
        if getattr(self.args, "kube_proxy", True):
            from kubernetes_tpu.proxy.config import ProxyServer

            self.proxy = ProxyServer(
                self._client(), real_portals=True
            ).start()
        self.dns = None
        if getattr(self.args, "cluster_dns", False):
            from kubernetes_tpu.addons import ClusterDNS

            client = self._client()
            self.dns = ClusterDNS(client).start()
            # Only advertise the well-known VIP when something will
            # actually listen there: a real-portal kube-proxy AND a
            # bindable 10.0.0.10:53 (CAP_NET_ADMIN alone doesn't imply
            # low-port bind rights). Otherwise the addon still serves
            # on its own bound port, but a dead kube-dns service must
            # not be published.
            if (
                self.proxy is not None
                and self.proxy.proxier.has_real_portals
                and self._dns_vip_bindable("10.0.0.10", 53)
            ):
                self.dns.publish(client)
                # Containers get the resolver address the reference
                # kubelet would write into resolv.conf.
                for kubelet in self.kubelets:
                    kubelet.runtime.cluster_dns = "10.0.0.10"
            else:
                import sys

                print(
                    "warning: --cluster-dns without real portals; "
                    f"DNS serves on 127.0.0.1:{self.dns.port} only "
                    "(kube-dns service not published)",
                    file=sys.stderr,
                )
        # Live component health (componentstatuses; the reference
        # master registers etcd + scheduler + controller-manager,
        # pkg/master/master.go getServersToValidate).
        self.api.register_component(
            "etcd-0", lambda: (True, "store serving")
        )
        self.api.register_component("scheduler", self._scheduler_health)
        self.api.register_component(
            "controller-manager", self._manager_health
        )
        # Health plane: retention sampler + burn-rate alert engine
        # (utils/alerts wires the sampler hook; alert state transitions
        # surface as cluster Events through this client). Honors
        # KT_TIMESERIES=0 for processes that must not grow a sampler
        # thread.
        from kubernetes_tpu.utils import alerts

        alerts.ensure_started(client=self._client())
        return self

    @staticmethod
    def _dns_vip_bindable(ip: str, port: int) -> bool:
        """Probe that the kube-dns VIP:port can actually be bound (the
        proxier will do exactly this once the service appears)."""
        import socket

        from kubernetes_tpu.proxy.portal import LoopbackPortals

        portals = LoopbackPortals()
        if not portals.acquire(ip):
            return False
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.bind((ip, port))
                return True
            except OSError:
                return False
            finally:
                s.close()
        finally:
            portals.release(ip)

    def _scheduler_health(self):
        sched = self.scheduler
        alive = (
            sched is not None
            and sched._thread is not None
            and sched._thread.is_alive()
        )
        return alive, "ok" if alive else "scheduler loop not running"

    def _manager_health(self):
        running = getattr(self.manager, "running", False)
        n = len(self.manager.controllers)
        if not running:
            return False, "controller manager stopped"
        return n > 0, f"{n} controllers running" if n else "no controllers"

    def stop(self) -> None:
        import shutil

        from kubernetes_tpu.utils import timeseries

        # The sampler is module-global (one per process, like the
        # metrics registry); local-up owns the process, so tearing the
        # cluster down stops it — tests must not leak the thread.
        timeseries.SAMPLER.stop()
        if getattr(self, "dns", None) is not None:
            self.dns.stop()
        if getattr(self, "proxy", None) is not None:
            self.proxy.stop()
        self.manager.stop()
        if self.scheduler is not None:
            self.scheduler.stop()
        for kubelet in self.kubelets:
            kubelet.stop()
            # Kill remaining pod processes before removing their roots.
            for uid in list(kubelet.runtime.list_pods()):
                try:
                    kubelet.runtime.kill_pod(uid)
                except Exception:
                    pass
        self.http.stop()
        for root in self._tmp_roots:
            shutil.rmtree(root, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    cluster = LocalCluster(args).start()
    print(f"cluster up: apiserver at {cluster.http.address}")
    print(f"  ktctl --server {cluster.http.address} get nodes")
    try:
        from kubernetes_tpu.cmd.daemons import _wait_forever

        _wait_forever()
    finally:
        cluster.stop()
        print("cluster stopped")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
