"""kubernetes_tpu — a TPU-native cluster orchestration framework.

A from-scratch re-design of the reference container-cluster manager
(Kubernetes pre-1.0, see /root/reference) built TPU-first:

- Declarative REST API over a CAS-versioned store with watch streams
  (reference: pkg/apiserver, pkg/tools/etcd_helper.go).
- Reconciliation controllers (reference: pkg/controller, pkg/service,
  pkg/cloudprovider/nodecontroller).
- A node agent with pluggable runtime (reference: pkg/kubelet).
- The differentiator: a batched scheduler whose predicate/priority
  pipeline emits dense pod x node feasibility and score matrices solved
  as an assignment problem on TPU via JAX/XLA/pjit (reference scalar
  loop: plugin/pkg/scheduler/generic_scheduler.go:60-171).
"""

__version__ = "0.1.0"
