"""Pipelined backlog solve: overlap host lowering + upload with the
device scan.

The sequential-parity scan (ops.solver) is latency-bound on device, and
the host work around it (columnar lowering, host->device transfer,
readback) would otherwise serialize with it. This module chunks the
pending backlog and chains the solver's DONATED node carry across
chunks: while the device scans chunk k, the (single-core) host lowers
and stages chunk k+1 — JAX dispatch is async, so the Python thread is
free the moment a chunk's solve is enqueued.

Decisions are bit-identical to the monolithic solve: chunking changes
WHEN pod rows reach the device, never the order they are scanned or the
carry they see. (Parity with the scalar oracle is therefore inherited
from ops.solver; tests/test_solver_parity.py checks both.)

There is no reference analog to cite — the reference schedules one pod
per HTTP round-trip (plugin/pkg/scheduler/scheduler.go:113-158); this
pipeline is the TPU-native replacement for that loop's concurrency.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.models.columnar import SnapshotBuilder
from kubernetes_tpu.models.objects import Node, Pod, Service
from kubernetes_tpu.ops.matrices import (
    device_nodes,
    device_pods,
    node_axis_multiple,
    pow2_bucket,
    shardings_for,
)
from kubernetes_tpu.ops.solver import DEFAULT_WEIGHTS, solve_with_state
from kubernetes_tpu.utils import sanitizer, sli, tracing

# Measured on v5e-1 at 50k x 5k with the pallas scan kernel: 12544
# (4 chunks) walls 0.61-0.66s vs 0.88-0.96s at 8192 and 0.71-0.76s at
# 25088 — scan chunk boundaries are free (bit-identical carry
# chaining), so the trade is purely per-chunk dispatch overhead vs
# critical-path first-chunk lowering. Wave mode keeps its own sweet
# spot (25088, set by bench.py): its boundaries DO cost partial waves,
# which is also why a progressive small-first-chunk ramp was tried and
# LOST for wave.
DEFAULT_CHUNK = 12544


def gang_member_counts_device(
    placed, group_ids, num_groups: int
) -> np.ndarray:
    """Device path of the gang-acceptance reduction: stage the host
    placed-mask + group-id columns, run the masked segment reduction
    (ops.matrices.gang_member_counts), and return host counts. Both
    axes pad to power-of-two buckets (pods with placed=False/id=-1 —
    masked out by construction): num_groups is a static jit arg and the
    pod length is a traced shape, so per-batch drift in either must not
    trigger an XLA recompile."""
    import jax.numpy as jnp

    from kubernetes_tpu.ops.matrices import gang_member_counts

    G = int(num_groups)
    if G <= 0:
        return np.zeros(0, np.int32)
    placed = np.asarray(placed, bool)
    gids = np.asarray(group_ids, np.int32)
    P = placed.shape[0]
    PP = pow2_bucket(max(P, 1), minimum=8)
    if PP != P:
        placed = np.pad(placed, (0, PP - P))
        gids = np.pad(gids, (0, PP - P), constant_values=-1)
    GP = pow2_bucket(G, minimum=8)
    counts = gang_member_counts(
        jnp.asarray(placed), jnp.asarray(gids), num_groups=GP
    )
    return np.asarray(counts)[:G]


def solve_backlog_pipelined(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    mesh=None,
    chunk: int = DEFAULT_CHUNK,
    weights=DEFAULT_WEIGHTS,
    mode: str = "scan",
) -> List[Optional[str]]:
    """Schedule the backlog; returns node names (None = unschedulable).

    mode="scan" (default) is bit-identical to schedule_backlog_tpu —
    the sequential-parity path. mode="wave"/"sinkhorn" runs the
    windowed batch solvers chunk-by-chunk over the SAME donated carry:
    chunk k+1's host lowering and upload overlap chunk k's device
    waves, so the end-to-end wall approaches the device-only wave
    time. Decisions are the approximate wave family's (quality gated
    by regret bounds in tests/test_quality_regression.py, published by
    bench.py), but every capacity/port/volume invariant still holds —
    the wave commit path enforces the same feasibility the scan does.
    Chunking never loosens quality vs a monolithic wave solve: chunks
    commit in backlog order, so a chunk's pods see strictly MORE
    committed state than the same pods in one big window ever would.
    """
    # jit dispatch + the final blocking readback must never run under a
    # sanitized lock (ktsan blocking-under-lock check; a multi-second
    # first-bucket compile under the apiserver or store lock would
    # freeze the control plane).
    sanitizer.check_blocking("jit-dispatch", "solve_backlog_pipelined")
    # Phase spans wrap whole host-side segments, never per-pod work —
    # their cost is a few monotonic reads per CHUNK. JAX dispatch is
    # async, so per-chunk "solve" measures dispatch; the device time
    # drains into the final blocking "readback".
    with tracing.phase("lower", pods=len(pending)):
        builder = SnapshotBuilder(pending, nodes, assigned, services)
        node_sharding, pod_sharding = shardings_for(mesh)
    with tracing.phase("upload"):
        # h2d transfer SLI is counted once, inside matrices._put_tree
        # (which device_nodes/device_pods funnel through) — counting
        # the host columns here too would double the metric.
        carry = device_nodes(
            builder.node_columns(), node_sharding,
            node_mult=node_axis_multiple(mesh),
        )
    # Convergence telemetry per chunk (device scalars — converted to
    # host ints only at the blocking readback, so the async overlap
    # never stalls on a telemetry copy).
    tele: List[Tuple] = []
    if mode == "scan":
        step = lambda dpods, carry: solve_with_state(dpods, carry, weights)
    elif mode == "wave":
        from kubernetes_tpu.ops.wave import solve_waves_with_state

        def step(dpods, carry):
            a, c, w = solve_waves_with_state(dpods, carry, weights)
            tele.append((w, None, None))
            return a, c
    elif mode == "sinkhorn":
        from kubernetes_tpu.ops.sinkhorn import solve_sinkhorn_with_state

        def step(dpods, carry):
            a, c, w, it, res = solve_sinkhorn_with_state(
                dpods, carry, weights
            )
            tele.append((w, it, res))
            return a, c
    else:
        raise ValueError(f"unknown pipeline mode {mode!r}")
    P = len(builder.pending)
    outs = []
    for ci, start in enumerate(range(0, max(P, 1), chunk)):
        with tracing.phase("lower", chunk=ci):
            cols = builder.pod_columns(start, min(start + chunk, P))
        # Full chunks share one executable; the (smaller) tail chunk
        # pads to its own 128 bucket rather than a full chunk, so small
        # backlogs and tails don't scan thousands of padding steps.
        with tracing.phase("upload", chunk=ci):
            dpods = device_pods(cols, pod_sharding)
        with tracing.phase("solve", chunk=ci):
            assignment, carry = step(dpods, carry)
            # Start this chunk's device->host copy NOW: it rides behind
            # the next chunk's device work instead of serializing at the
            # end (the final np.asarray finds the bytes already local).
            if hasattr(assignment, "copy_to_host_async"):
                assignment.copy_to_host_async()
        outs.append((assignment, cols.count))

    with tracing.phase("readback"):
        names = [n.metadata.name for n in builder.nodes]
        result: List[Optional[str]] = []
        n_nodes = len(builder.nodes)
        d2h = 0
        for assignment, count in outs:
            full = np.asarray(assignment)
            d2h += full.nbytes
            picks = full[:count]
            for j in picks.tolist():
                result.append(names[j] if 0 <= j < n_nodes else None)
        sli.note_transfer("d2h", d2h)
        if tele:
            from kubernetes_tpu.utils import flightrecorder

            waves = sum(int(w) for w, _, _ in tele)
            if mode == "sinkhorn":
                flightrecorder.observe_solve_telemetry(
                    "sinkhorn",
                    sum(int(it) for _, it, _ in tele),
                    residual=float(tele[-1][2]),
                    waves=waves,
                )
            else:
                flightrecorder.observe_solve_telemetry("wave", waves)
        return result


# -- explain readback ---------------------------------------------------


def explain_matrix(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    mesh=None,
):
    """Raw explain readback for a backlog against one FIXED cluster
    state (`assigned` pods charge occupancy; `pending` pods commit
    nothing — every row sees the same state). Returns (node_names,
    bits u32[P, N], components dict of i32[P, N]): bit i of bits[p, n]
    set means matrices.EXPLAIN_PREDICATES[i] rejected node n for pod
    p; bits == 0 is feasibility under the default pipeline. One kernel
    dispatch + one readback — never on the solve path (the daemons run
    it inside its own "explain" phase)."""
    from kubernetes_tpu.models.columnar import build_snapshot
    from kubernetes_tpu.ops.matrices import device_snapshot
    from kubernetes_tpu.ops.solver import explain_rows

    snap = build_snapshot(
        pending, nodes, assigned_pods=assigned, services=services
    )
    dsnap = device_snapshot(snap, mesh=mesh)
    bits, lr, bra, spread = explain_rows(dsnap.pods, dsnap.nodes)
    P, N = dsnap.n_pods, dsnap.n_nodes
    return (
        snap.nodes.names,
        np.asarray(bits)[:P, :N],
        {
            "leastRequested": np.asarray(lr)[:P, :N],
            "balanced": np.asarray(bra)[:P, :N],
            "spreading": np.asarray(spread)[:P, :N],
        },
    )


def explain_backlog(
    pending: Sequence[Pod],
    nodes: Sequence[Node],
    assigned: Sequence[Pod] = (),
    services: Sequence[Service] = (),
    mesh=None,
    top_k: int = 3,
    max_failed: int = 16,
) -> List[dict]:
    """Bounded per-pod explain verdicts — the flight recorder's shape.
    For each pending pod (aligned with the input): the top_k feasible
    nodes ranked by total default-priority score (lowest index wins
    ties, the solver's tie-break) with the score decomposition, up to
    max_failed individually-listed infeasible nodes, and aggregate
    failed-predicate counts over ALL nodes — a 5k-node cluster folds
    into a handful of reason counts, not 5k rows."""
    from kubernetes_tpu.models.objects import pod_full_key
    from kubernetes_tpu.ops.matrices import (
        EXPLAIN_PREDICATES,
        decode_predicate_bits,
    )

    pending = list(pending)
    if not pending:
        return []
    names, bits, comps = explain_matrix(
        pending, nodes, assigned, services, mesh=mesh
    )
    total = (
        comps["leastRequested"] + comps["balanced"] + comps["spreading"]
    )
    out: List[dict] = []
    n_nodes = len(names)
    for i, pod in enumerate(pending):
        row = bits[i]
        feasible = np.flatnonzero(row == 0)
        entry_nodes: List[dict] = []
        # Feasible candidates: score desc, node index asc on ties
        # (argsort is stable, so sorting by -score preserves index
        # order inside a score band — the scan's argmax tie-break).
        for j in feasible[np.argsort(-total[i][feasible], kind="stable")][
            :top_k
        ].tolist():
            entry_nodes.append(
                {
                    "node": names[j],
                    "ok": True,
                    "score": int(total[i, j]),
                    "components": {
                        k: int(v[i, j]) for k, v in comps.items()
                    },
                }
            )
        # Aggregate counts vectorized (one popcount per predicate bit,
        # not a Python loop over 5k nodes); only the max_failed nodes
        # listed individually pay per-node decoding.
        reason_counts: Dict[str, int] = {}
        for b, name in enumerate(EXPLAIN_PREDICATES):
            c = int(((row >> np.uint32(b)) & 1).sum())
            if c:
                reason_counts[name] = c
        for j in np.flatnonzero(row != 0)[:max_failed].tolist():
            entry_nodes.append(
                {
                    "node": names[j],
                    "ok": False,
                    "reasons": decode_predicate_bits(int(row[j])),
                }
            )
        out.append(
            {
                "pod": pod_full_key(pod),
                "feasibleNodes": int(len(feasible)),
                "totalNodes": n_nodes,
                "nodes": entry_nodes,
                "reasonCounts": reason_counts,
            }
        )
    return out
